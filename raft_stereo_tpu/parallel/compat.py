"""jax API compatibility for the sharded executors.

The ``parallel/`` executors were written against the top-level
``jax.shard_map`` API (``axis_names=`` manual axes, ``check_vma=``) and
the varying-manual ``jax.lax.pcast``.  Older jax (the 0.4.x line this
container ships) has neither: shard_map lives at
``jax.experimental.shard_map.shard_map`` with the complementary ``auto=``
(automatic axes) + ``check_rep=`` spelling, and ``pcast`` does not exist
— its job (marking a constant scan carry as device-varying so the
replication checker accepts a varying step output) is only needed by the
new checker in the first place.

This module is the one translation point, so every executor
(rows_sharded / rows_gru / corr_sharded) runs on both API generations
and none of them hand-rolls version sniffing.  On new jax the calls pass
straight through; on old jax:

* ``axis_names`` (manual) becomes ``auto = mesh.axis_names - axis_names``;
* ``check_rep`` is pinned False — partial-auto shard_map predates a
  working replication checker there, and the executors' correctness is
  pinned numerically by tests/test_rows_*.py, not by the checker;
* ``pcast_varying`` is the identity.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, axis_names, in_specs, out_specs,
              check_vma: bool = True):
    """``jax.shard_map`` with the new keyword surface, on either API
    generation.  ``axis_names`` is the set of MANUAL axes (the new
    spelling); all other mesh axes stay automatic."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, axis_names=set(axis_names),
                             in_specs=in_specs, out_specs=out_specs,
                             check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False, auto=auto)


def pcast_varying(x, axis):
    """``jax.lax.pcast(x, (axis,), to="varying")`` where it exists; the
    identity elsewhere (no varying-manual type system = nothing to
    cast)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    return x
