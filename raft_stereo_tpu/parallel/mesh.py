"""Device mesh + sharding helpers.

TPU-native replacement for the reference's ``nn.DataParallel``
(reference: train_stereo.py:134 — single-process replicate/scatter/gather).
Here parallelism is SPMD: one jitted program over a ``jax.sharding.Mesh``,
batches sharded along ``data``, params replicated; XLA inserts the gradient
``psum`` over ICI automatically from sharding propagation.

The ``corr`` axis is reserved for sharding the W2 (disparity-search) axis of
the correlation volume — the "long-context" analog for full-resolution inputs
(SURVEY.md §5).  It is wired up by ``parallel/corr_sharded.py``; plain
data-parallel training should use ``n_corr=1``.

Multi-host: call ``parallel.distributed.initialize()`` before ``make_mesh`` —
the mesh then spans all hosts' devices, with gradient collectives riding ICI
within a slice and DCN across slices.  Data loading shards per process as
CONTIGUOUS slices of each global batch (``StereoLoader`` process_index/
process_count): ``jax.devices()`` orders devices by process index, so with
the default mesh layout process ``p``'s addressable ``data``-axis rows are
exactly rows ``[p*local, (p+1)*local)`` of the global batch, and
``make_array_from_process_local_data`` in ``shard_batch`` reassembles the
global array without any permutation.  Keep loader slicing and mesh device
order in sync if either changes.  Nothing else changes; that is the point
of SPMD.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
CORR_AXIS = "corr"
# Context-parallel axis: shards the IMAGE-ROW (H) dimension of the encoders'
# full-resolution segment (parallel/rows_sharded.py) — the stereo analog of
# sequence parallelism, composing with data/corr on one mesh.
ROWS_AXIS = "rows"


def parse_mesh_spec(spec: str) -> Dict[str, int]:
    """``"rows=4"`` / ``"rows=2,corr=2"`` → ``{"rows": 4, "corr": 2}``.

    The serving-facing mesh declaration (``ServeConfig.xl_mesh`` /
    ``raft-serve --xl_mesh``): only the two inference-sharding axes are
    accepted — ``rows`` (image-row context parallelism,
    parallel/rows_sharded.py + rows_gru.py) and ``corr`` (disparity-search
    W2 sharding, parallel/corr_sharded.py).  Unnamed axes default to 1.
    Raises ``ValueError`` on unknown axes, non-integer or < 1 sizes, or a
    blank spec."""
    out = {"rows": 1, "corr": 1}
    seen = set()
    parts = [p.strip() for p in str(spec).split(",") if p.strip()]
    if not parts:
        raise ValueError(f"mesh spec {spec!r} is empty: use e.g. 'rows=4' "
                         f"or 'rows=2,corr=2'")
    for part in parts:
        k, sep, v = part.partition("=")
        k = k.strip()
        if k not in out or not sep:
            raise ValueError(
                f"mesh spec {spec!r}: expected comma-separated "
                f"'rows=N'/'corr=N' entries, got {part!r}")
        if k in seen:
            raise ValueError(f"mesh spec {spec!r}: axis {k!r} named twice")
        seen.add(k)
        try:
            out[k] = int(v.strip())
        except ValueError as e:
            raise ValueError(f"mesh spec {spec!r}: size {v!r} for axis "
                             f"{k!r} is not an integer") from e
        if out[k] < 1:
            raise ValueError(f"mesh spec {spec!r}: axis {k!r} size "
                             f"{out[k]} must be >= 1")
    return out


def mesh_spec_label(spec: Dict[str, int]) -> str:
    """Compact stable tag of a parsed mesh spec for executable keys and
    metric labels: ``{"rows": 4, "corr": 1}`` → ``"rows4"``,
    ``{"rows": 2, "corr": 2}`` → ``"rows2corr2"`` — what the serving
    engine appends to compile-cost and persist keys (``",mesh=rows4"``)."""
    out = ""
    for axis in ("rows", "corr"):
        n = int(spec.get(axis, 1))
        if n > 1:
            out += f"{axis}{n}"
    return out or "solo"


def make_mesh(n_data: int = 0, n_corr: int = 1, n_rows: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``(data, corr, rows)`` mesh.

    Args:
      n_data: devices along the batch axis; 0 = all remaining devices.
      n_corr: devices sharding the disparity-search (W2) axis.
      n_rows: devices sharding the image-row (H) axis of the full-res
        encoder segment (context parallelism).
      devices: explicit device list (default ``jax.devices()``).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n_data <= 0:
        if len(devices) % (n_corr * n_rows):
            raise ValueError(f"{len(devices)} devices not divisible by "
                             f"n_corr*n_rows={n_corr * n_rows}")
        n_data = len(devices) // (n_corr * n_rows)
    n = n_data * n_corr * n_rows
    if n > len(devices):
        raise ValueError(f"mesh wants {n_data}×{n_corr}×{n_rows}={n} devices "
                         f"but only {len(devices)} are available")
    if n < len(devices):
        import warnings
        warnings.warn(f"mesh uses {n} of {len(devices)} devices; "
                      f"{len(devices) - n} will sit idle", stacklevel=2)
    grid = np.asarray(devices[:n]).reshape(n_data, n_corr, n_rows)
    return Mesh(grid, (DATA_AXIS, CORR_AXIS, ROWS_AXIS))


def shard_batch(batch: Any, mesh: Mesh) -> Any:
    """Place a host batch on the mesh, sharded along the leading (batch) dim.

    Single-host: a plain ``device_put``.  Multi-host (mesh spans processes):
    each process passes the portion of the global batch its OWN devices
    address, and the global array is assembled with
    ``jax.make_array_from_process_local_data`` (``device_put`` cannot place a
    host-local array onto another process's devices).  With the default mesh
    layout the ``data`` axis is process-contiguous, so that portion is the
    process's slice (leading dim = global_batch // process_count); when
    another axis spans processes instead (e.g. the rows-across-processes
    layout in tests/distributed_worker.py), every process's devices address
    every data-axis row and the process-local portion is the FULL global
    batch — which data rows a process passes depends on which data shards
    its devices hold, not on process count alone."""
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    if any(d.process_index != jax.process_index() for d in mesh.devices.flat):
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            batch)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Fully replicate a pytree over the mesh (params / train state)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), tree)
