"""Context-parallel (image-row-sharded) GRU refinement loop.

``parallel/rows_sharded.py`` shards the encoders' full-resolution segment;
this module extends context parallelism through the REST of the forward —
the correlation volume, the per-iteration multilevel ConvGRU updates, and
convex upsampling.  The O(H) heavyweights — full-resolution stem
activations, the correlation volume, and the train scan's per-iteration
carries of every GRU level — stay sharded end to end; only the static
fine-level (1/2^nd-resolution) feature/context maps are replicated per
device, a deliberate sharding pin at the executor boundary (see the
``_pin`` note at the bottom).  That is what makes full-resolution TRAINING
scale across chips: the scan carries are memory a single chip cannot hold
at Middlebury-F-class frames.

Design — clamped extended windows, refreshed halos:

* Row geometry.  Device ``i`` owns fine-level rows ``[i*slab, (i+1)*slab)``
  and computes on the clamped window ``[start_i, start_i + slab + 2*halo)``
  with ``start_i = clamp(i*slab - halo, 0, H - slab - 2*halo)``.  Clamping
  (instead of zero-padding out-of-image halo rows) means every window row is
  a REAL image row, so the update block needs no row masking: at window
  edges interior to the image, SAME-padding pollution stays ≥ halo rows away
  from owned rows; at the image's true top/bottom the window edge COINCIDES
  with the image edge and SAME padding is exactly correct.
* Static tensors (feature maps → correlation volume/pyramid, per-level
  context biases) are windowed ONCE per forward via a neighbor
  ``lax.ppermute`` exchange.  Per-level halos halve with resolution
  (``halo >> level``), keeping windows aligned across the GRU pyramid.
* Per-iteration state (GRU hidden states, disparity) is cropped to owned
  rows at the end of each iteration and re-windowed at the start of the
  next — the only steady-state communication, ``2*halo`` boundary rows per
  level per iteration over ICI.
* Cross-resolution coupling.  ``pool2x`` is window-local by alignment.  The
  align-corners bilinear ``interp`` is NOT shift-invariant (its sampling
  grid depends on GLOBAL heights — ops/resize.py), so each device applies
  the GLOBAL interpolation matrix restricted to its window rows
  (host-precomputed, shipped as a mesh-sharded ``(n, dst, src)`` input).
  Source rows falling just outside the window (≤1, a property of the
  align-corners grid) are clamped to the window edge; the affected outputs
  are window-EDGE rows, swallowed by the halo margin.
* Exactness.  Owned-row outputs equal the unsharded computation up to float
  reassociation provided ``halo ≥`` the update block's per-iteration row
  receptive field (see ``default_gru_halo``); gradients are exact the same
  way because cropping zeroes every polluted row's cotangent and ``ppermute``
  transposes to the reverse permutation (tests/test_rows_gru.py asserts
  forward AND training-step parity on CPU meshes).

Reference parity note: the reference has no multi-device refinement path at
all — its only parallelism is ``nn.DataParallel`` batch replication
(train_stereo.py:134), and its alt backend exists because one GPU cannot
hold the full-resolution volume (core/corr.py:64-107).  This module is
capability beyond the reference, the stereo analog of ring-attention-style
sequence parallelism: halo exchange instead of all-to-all because stereo
correlation is per-row (epipolar) and convolution receptive fields are
local.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, PartitionSpec as P

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.parallel import compat
from raft_stereo_tpu.ops.grids import coords_grid_x
from raft_stereo_tpu.ops.resize import _interp_matrix
from raft_stereo_tpu.ops.upsample import convex_upsample


def default_gru_halo(cfg: RaftStereoConfig) -> int:
    """Fine-level halo rows covering one iteration's row receptive field.

    Audit of one full update (models/update.py): motion encoder ≤5 rows
    (7×7 flow conv dominates) + ConvGRU convs ≤2 + flow/mask heads ≤2 +
    interp window-edge error ≤2 → ≤11 fine rows; mid/coarse levels shrink
    ≤5/≤2 at their own resolution against halves of the halo.  16 covers it
    with margin.  ``slow_fast_gru`` with 3 GRU levels runs the coarse GRU
    three times per iteration (core/raft_stereo.py:124-130 analog), tripling
    the coarse-level shrink against a quarter of the halo → 32.
    ``slow_fast_gru`` with 2 GRU levels (the realtime preset) doubles only
    the MID-level update: 2 updates × ≤5 rows at level-1 resolution = ≤10
    mid rows against halo/2 = 8 fine rows = 16 mid... — conservatively, the
    mid-level window carries halo/2 = 8 mid rows ≥ the 2×≤2-row GRU-conv
    shrink plus the one ≤5-row encoder pass (run once at the fine level
    only), so 16 still covers it; ``test_rows_gru_slow_fast_two_level``
    pins this empirically at halo=16."""
    if cfg.slow_fast_gru and cfg.n_gru_layers == 3:
        return 32
    return 16


def _geometry(h_f: int, n: int, halo: int):
    """Per-device clamped window geometry at the fine level (numpy)."""
    slab = h_f // n
    idx = np.arange(n)
    starts = np.clip(idx * slab - halo, 0, h_f - slab - 2 * halo)
    off_ext = starts - (idx * slab - 2 * halo)   # offset into the 4h-extended slab
    own_off = idx * slab - starts                # owned rows' offset in the window
    return slab, starts, off_ext, own_off


def _restricted_rows_interp(h_src: int, h_dst: int, starts_src, starts_dst,
                            len_src: int, len_dst: int) -> np.ndarray:
    """Global align-corners interp matrix restricted to each device's window.

    Returns (n, len_dst, len_src): rows ``starts_dst[i] : +len_dst`` of the
    global ``(h_dst, h_src)`` matrix, with source columns clamped into
    ``starts_src[i] : +len_src`` (only window-edge outputs are affected —
    module docstring)."""
    mg = _interp_matrix(h_src, h_dst)            # (h_dst, h_src)
    n = len(starts_src)
    out = np.zeros((n, len_dst, len_src), np.float32)
    for i in range(n):
        block = mg[starts_dst[i]:starts_dst[i] + len_dst]      # (len_dst, h_src)
        rel = np.arange(h_src) - starts_src[i]
        cols = np.clip(rel, 0, len_src - 1)
        # the window-edge clamp is sound only while align-corners sources
        # fall at most 1 row outside the window (module docstring); if
        # _interp_matrix semantics ever change (e.g. half-pixel centers),
        # fail loudly at trace time instead of silently misplacing weight
        carries = np.abs(block).sum(axis=0) > 0                # (h_src,)
        clamp_dist = np.abs(rel - cols)
        # explicit raise, not `assert`: python -O strips asserts, which
        # would silently misplace weight — the exact failure this check
        # exists to make loud (it runs once at trace time, in NumPy)
        if int(clamp_dist[carries].max(initial=0)) > 1:
            raise AssertionError(
                "rows_gru: interp source row falls more than 1 row outside "
                "its device window — _interp_matrix semantics changed; "
                "re-derive the halo geometry")
        acc = np.zeros((len_src, len_dst), np.float32)
        np.add.at(acc, cols, block.T)
        out[i] = acc.T
    return out


def _make_window_interp(row_mats):
    """Build the update block's ``interp_fn`` from per-device row matrices.

    ``row_mats``: {(src_rows, dst_rows): (dst_rows, src_rows) traced array}.
    Width interpolation uses the global matrix unchanged (W is unsharded)."""

    def interp_fn(x, dest):
        sh, sw = x.shape[1], x.shape[2]
        dh, dw = dest.shape[1], dest.shape[2]
        m = row_mats.get((sh, dh))
        if m is None:
            # A window-local align-corners resize would be SILENTLY wrong
            # (its grid must come from GLOBAL heights — module docstring);
            # fail at trace time instead.
            raise KeyError(
                f"rows_gru: no restricted interp matrix for window rows "
                f"{sh}->{dh}; registered sites: {sorted(row_mats)} — a new "
                f"update-block interp site must be added to the executor's "
                f"interp_shapes")
        y = jnp.einsum("bhwc,oh->bowc", x, m.astype(x.dtype),
                       precision=jax.lax.Precision.HIGHEST)
        if sw != dw:
            mx = jnp.asarray(_interp_matrix(sw, dw), dtype=x.dtype)
            y = jnp.einsum("bhwc,ow->bhoc", y, mx,
                           precision=jax.lax.Precision.HIGHEST)
        return y

    return interp_fn


def validate_rows_gru(cfg: RaftStereoConfig, h_f: int, n: int) -> int:
    """Check geometry constraints; return the fine-level halo."""
    halo = cfg.rows_gru_halo or default_gru_halo(cfg)
    align = 2 ** (cfg.n_gru_layers - 1)
    if h_f % n:
        raise ValueError(f"rows_gru: fine-level height {h_f} not divisible "
                         f"by rows_shards={n}")
    slab = h_f // n
    if slab % align or halo % 4:
        raise ValueError(
            f"rows_gru: per-shard fine rows {slab} must be divisible by "
            f"{align} and halo {halo} by 4 (GRU pyramid alignment)")
    if slab < 2 * halo:
        raise ValueError(
            f"rows_gru: per-shard fine rows H/f/n = {slab} < 2*halo = "
            f"{2 * halo}; a single ppermute exchange can only source rows "
            f"from the adjacent shard — use fewer shards, a larger image, "
            f"or a smaller rows_gru_halo (≥ the per-iteration receptive "
            f"field; see default_gru_halo)")
    return halo


def rows_sharded_gru_loop(cfg: RaftStereoConfig, dtype, update_params,
                          fmap1: jnp.ndarray, fmap2: jnp.ndarray,
                          net_list: Sequence[jnp.ndarray],
                          context: Sequence[Tuple[jnp.ndarray, ...]],
                          disp0: jnp.ndarray, iters: int, test_mode: bool,
                          mesh: Mesh, axis: str):
    """Run the refinement loop with image rows sharded over ``mesh[axis]``.

    All array arguments are GLOBAL (B, H_l, W_l, ...) tensors from the
    encoders.  Returns exactly what the model's scan section returns:
    per-iteration full-resolution flows (train) or ``(disp_low, flow_up)``
    (test mode) — numerically equal to the unsharded loop on owned rows.
    """
    from raft_stereo_tpu.models.corr import make_corr_fn
    from raft_stereo_tpu.models.update import BasicMultiUpdateBlock

    n = mesh.shape[axis]
    if n != cfg.rows_shards or n < 2:
        raise ValueError(f"rows_gru: mesh axis {axis!r} size {n} != "
                         f"rows_shards={cfg.rows_shards} (need >= 2)")
    levels = cfg.n_gru_layers
    b, h_f, w_f, _ = net_list[0].shape
    factor = cfg.downsample_factor
    halo = validate_rows_gru(cfg, h_f, n)
    slab, starts, off_ext, own_off = _geometry(h_f, n, halo)

    for l in range(levels):
        if net_list[l].shape[1] != (h_f >> l):
            raise ValueError(
                f"rows_gru: level {l} height {net_list[l].shape[1]} != "
                f"{h_f >> l} — GRU levels must be exact halves")

    # Per-device offsets for every level, shipped as mesh-sharded inputs so
    # the shard body needs no axis_index branching.  Level-l values are the
    # fine values >> l — exact because slab, halo, and the clamp bound are
    # all divisible by 2**(levels-1).
    off_ext_arr = np.stack([off_ext >> l for l in range(levels)],
                           axis=1).astype(np.int32)       # (n, levels)
    own_off_arr = np.stack([own_off >> l for l in range(levels)],
                           axis=1).astype(np.int32)

    # Restricted interp matrices for the two cross-resolution sites
    # (coarse→mid, mid→fine), keyed by (src_rows, dst_rows) window sizes.
    interp_shapes = []
    interp_mats = []
    for l in range(levels - 1):           # site: level l+1 → level l
        len_dst = (slab >> l) + 2 * (halo >> l)
        len_src = (slab >> (l + 1)) + 2 * (halo >> (l + 1))
        interp_shapes.append((len_src, len_dst))
        interp_mats.append(_restricted_rows_interp(
            h_f >> (l + 1), h_f >> l, starts >> (l + 1), starts >> l,
            len_src, len_dst))

    param_specs = jax.tree_util.tree_map(lambda _: P(), update_params)
    rows = P(None, axis)
    ctx_specs = tuple(tuple(rows for _ in lvl) for lvl in context)
    net_specs = tuple(rows for _ in net_list)
    mat_specs = tuple(P(axis) for _ in interp_mats)

    if test_mode:
        out_specs = (rows, rows)
    else:
        out_specs = P(None, None, axis)   # (iters, B, H, W)

    perm_dn = [(j, j + 1) for j in range(n - 1)]   # rows from device i-1
    perm_up = [(j + 1, j) for j in range(n - 1)]   # rows from device i+1

    @functools.partial(
        compat.shard_map, mesh=mesh, axis_names={axis},
        in_specs=(param_specs, rows, rows, net_specs, ctx_specs, rows,
                  P(axis), P(axis), mat_specs),
        out_specs=out_specs)
    def run(ub_params, fmap1_l, fmap2_l, net_l, ctx_l, disp_l,
            off_ext_l, own_off_l, mats_l):
        off = off_ext_l[0]     # (levels,) this device's window offsets
        own = own_off_l[0]
        row_mats = {interp_shapes[l]: mats_l[l][0] for l in range(levels - 1)}

        def window(x, lvl):
            """Local slab → clamped extended window via neighbor exchange."""
            hl = halo >> lvl
            top = jax.lax.ppermute(x[:, -2 * hl:], axis, perm_dn)
            bot = jax.lax.ppermute(x[:, :2 * hl], axis, perm_up)
            ext = jnp.concatenate([top, x, bot], axis=1)
            return jax.lax.dynamic_slice_in_dim(
                ext, off[lvl], x.shape[1] + 2 * hl, axis=1)

        def crop(x, lvl, scale=1):
            return jax.lax.dynamic_slice_in_dim(
                x, own[lvl] * scale, (slab >> lvl) * scale, axis=1)

        # -------- static per-forward windows: features → corr, context
        fmap1_w = window(fmap1_l, 0)
        fmap2_w = window(fmap2_l, 0)
        ctx_w = [tuple(window(t, l) for t in ctx_l[l]) for l in range(levels)]
        corr_fn = make_corr_fn(cfg, fmap1_w, fmap2_w)

        # parent=None: this executor may run inside the model's own call
        # (a live flax module scope) — construct the functional twin
        # detached so flax doesn't try to register it as a submodule.
        ub = BasicMultiUpdateBlock(cfg, dtype=dtype,
                                   interp_fn=_make_window_interp(row_mats),
                                   parent=None)

        def apply_ub(*args, **kwargs):
            return ub.apply({"params": ub_params}, *args, **kwargs)

        rows_w = slab + 2 * halo
        grid_x = coords_grid_x(b, rows_w, w_f, dtype=jnp.float32)

        def gru_iter(net_w, disp_w):
            """One refinement iteration on windowed tensors — mirrors the
            model's ``gru_step`` (models/raft_stereo.py) exactly."""
            disp_w = jax.lax.stop_gradient(disp_w)
            corr = checkpoint_name(
                corr_fn(grid_x + disp_w).astype(dtype), "corr_lookup")
            flow2 = jnp.stack([disp_w, jnp.zeros_like(disp_w)],
                              axis=-1).astype(dtype)
            net_w = list(net_w)
            if levels == 3 and cfg.slow_fast_gru:
                net_w = apply_ub(net_w, ctx_w, iter_fine=False,
                                 iter_mid=False, update=False)
            if levels >= 2 and cfg.slow_fast_gru:
                net_w = apply_ub(net_w, ctx_w, iter_fine=False,
                                 iter_coarse=(levels == 3), update=False)
            net_w, up_mask, delta_flow = apply_ub(
                net_w, ctx_w, corr, flow2,
                iter_mid=(levels >= 2), iter_coarse=(levels == 3))
            disp_w = disp_w + delta_flow[..., 0].astype(jnp.float32)
            return net_w, disp_w, up_mask

        def upsample(disp_w, mask_w):
            up = convex_upsample(disp_w[..., None],
                                 mask_w.astype(jnp.float32), factor)
            return up[..., 0]

        if test_mode:
            def step(carry, _):
                net_o, disp_o, _m = carry
                net_w = [window(t, l) for l, t in enumerate(net_o)]
                net_w, disp_w, up_mask = gru_iter(net_w, window(disp_o, 0))
                return (tuple(crop(t, l) for l, t in enumerate(net_w)),
                        crop(disp_w, 0), crop(up_mask, 0)), None

            mask0 = jnp.zeros((b, slab, w_f, cfg.mask_channels), dtype)
            # the scan's step returns a device-varying cropped mask; the
            # constant initial carry must carry the same varying type
            mask0 = compat.pcast_varying(mask0, axis)
            (net_o, disp_o, mask_o), _ = jax.lax.scan(
                step, (tuple(net_l), disp_l, mask0), None, length=iters)
            flow_up_w = upsample(window(disp_o, 0), window(mask_o, 0))
            return disp_o, crop(flow_up_w, 0, factor)

        def step(carry, _):
            net_o, disp_o = carry
            net_w = [window(t, l) for l, t in enumerate(net_o)]
            net_w, disp_w, up_mask = gru_iter(net_w, window(disp_o, 0))
            flow_up = crop(upsample(disp_w, up_mask), 0, factor)
            return (tuple(crop(t, l) for l, t in enumerate(net_w)),
                    crop(disp_w, 0)), flow_up

        if cfg.remat_gru:
            step = jax.checkpoint(
                step, prevent_cse=False,
                policy=jax.checkpoint_policies.save_only_these_names(
                    *cfg.remat_save))
        _, flow_ups = jax.lax.scan(step, (tuple(net_l), disp_l), None,
                                   length=iters)
        return flow_ups

    # Pin the executor's inputs' H sharding in the surrounding auto-sharded
    # world.  Pure rows mesh (no data/corr axis — the full-resolution
    # -training regime): keep H SHARDED over the rows axis so the encoders'
    # ≤1/2-res tail stays row-sharded end to end — measured on the 8-dev
    # virtual mesh at 2048x2880, an UNSHARDED pin left ~49 GiB/device of
    # replicated tail backward stores (ROWSGRU_MEMORY_r05.json iters-6
    # probe), dwarfing the sharded loop.  With a data axis > 1 the pin
    # flips to H-UNSHARDED: tail convs sharded over (batch x rows)
    # simultaneously hit XLA's SPMD conv-KERNEL-gradient double-count
    # (reproduced and documented for the trunk executor,
    # parallel/rows_sharded.py); there the reshard happens at the
    # shard_map boundary and only the full-res segment + scan carries
    # stay sharded.
    from jax.sharding import NamedSharding
    unc = P.UNCONSTRAINED
    h_spec = axis if mesh.devices.size == n else None

    def _pin(x):
        spec = (P(unc, h_spec, unc, unc) if x.ndim == 4
                else P(unc, h_spec, unc))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec))

    fmap1, fmap2, disp0 = _pin(fmap1), _pin(fmap2), _pin(disp0)
    net_list = tuple(_pin(t) for t in net_list)
    context = tuple(tuple(_pin(t) for t in lvl) for lvl in context)

    return run(update_params, fmap1, fmap2, tuple(net_list),
               tuple(tuple(lvl) for lvl in context), disp0,
               jnp.asarray(off_ext_arr), jnp.asarray(own_off_arr),
               tuple(jnp.asarray(m) for m in interp_mats))
