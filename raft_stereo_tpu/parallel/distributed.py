"""Multi-host (DCN) distributed runtime.

The reference's only parallelism is single-process ``nn.DataParallel``
(reference: train_stereo.py:134) — no NCCL/MPI process groups exist there.
This module is the TPU-native communication backend that *replaces* that
stack: one jax process per host, ``jax.distributed.initialize`` over DCN,
and after that every collective (gradient psum, corr-shard psum) is an XLA
collective riding ICI within a slice and DCN across slices.  Nothing else
in the framework changes — the SPMD train step (training/step.py) and the
``(data, corr)`` mesh (parallel/mesh.py) are already global-view; this
module only supplies process bootstrap and per-process data sharding.

Usage (same program on every host):

    from raft_stereo_tpu.parallel import distributed
    distributed.initialize()            # no-op in single-process runs
    mesh = make_mesh()                  # spans ALL hosts' devices
    loader = StereoLoader(ds, batch_size=global_batch,
                          **distributed.loader_shard_kwargs())
    batch = shard_batch(local_batch, mesh)   # assembles the global array

On Cloud TPU, ``initialize()`` autodetects coordinator/process topology
from the TPU metadata; elsewhere set ``coordinator_address`` /
``num_processes`` / ``process_id`` explicitly (or the standard
``JAX_COORDINATOR_ADDRESS`` etc. environment variables).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

log = logging.getLogger(__name__)

_initialized = False

# Environment markers of a multi-process topology jax can auto-detect
# (explicit coordinator env, Cloud TPU pod workers, SLURM/OpenMPI ranks).
# Checked WITHOUT touching any jax API: jax.distributed.initialize must run
# before the first device query latches the backend, so the guard must not
# query jax itself.
_TOPOLOGY_ENV = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                 "MEGASCALE_COORDINATOR_ADDRESS")


def _env_topology_present() -> bool:
    if any(os.environ.get(k) for k in _TOPOLOGY_ENV):
        return True
    # A TPU pod lists MULTIPLE workers (comma-separated); a single hostname
    # is just a 1-worker slice and needs no process group.
    if "," in os.environ.get("TPU_WORKER_HOSTNAMES", ""):
        return True
    for k in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(os.environ.get(k, "1")) > 1:
                return True
        except ValueError:
            pass
    return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bootstrap multi-process jax; safe to call in single-process runs.

    Must run before the first device query in the process (jax latches the
    backend on first use) — which is why the single-process guard inspects
    only the environment, never jax state.  Idempotent."""
    global _initialized
    # jax.distributed.is_initialized landed after 0.4.x; on older jax the
    # module-level flag is the only (per-process, sufficient) guard.
    jax_says = getattr(jax.distributed, "is_initialized", lambda: False)
    if _initialized or jax_says():
        _initialized = True
        return
    if (coordinator_address is None and num_processes is None
            and process_id is None and not _env_topology_present()):
        # Plain single-process run with no detectable topology: nothing to
        # do, and calling jax.distributed.initialize would fail.
        _initialized = True
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    log.info("distributed: process %d/%d, %d local of %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def any_process(flag: bool) -> bool:
    """Global OR of a per-process bool.

    This is a COLLECTIVE in multi-process runs — every process must call it
    the same number of times.  The train loop calls it once per loop
    iteration and folds its own loader's exhaustion into ``flag``, so the
    invariant survives sharded loaders of UNEQUAL length: every process
    keeps entering the collective until the global OR fires, then all break
    together at the earliest exhaustion.  It coordinates the preemption
    stop: a SIGTERM landing on one host (or at
    different step boundaries on different hosts) must make EVERY process
    break the loop at the same step, or the processes that kept going would
    dispatch step collectives while the stopping one enters the collective
    checkpoint save — distributed deadlock (the maxtext/t5x
    reached-preemption-sync-point pattern)."""
    if jax.process_count() == 1:
        return bool(flag)
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(np.asarray(flag, np.int32))
    return bool(np.max(flags))


def local_devices_stable() -> List[jax.Device]:
    """This process's devices in a STABLE, process-independent order.

    ``jax.local_devices()`` order is backend-defined; everything that
    assigns work to devices by index — the serving engine's worker pool,
    xl mesh groups, the multi-host loader slicing contract in
    ``parallel/mesh.py`` — must agree on ONE ordering or two components
    on the same host can claim overlapping devices.  Sorting by device id
    makes the order a pure function of the topology."""
    return sorted(jax.local_devices(), key=lambda d: d.id)


def device_groups(group_size: int, n_groups: Optional[int] = None,
                  devices: Optional[Sequence[jax.Device]] = None,
                  skip: int = 0) -> List[Tuple[jax.Device, ...]]:
    """Partition local devices into DISJOINT ordered groups of
    ``group_size`` — the one helper the serving engine and the parallel
    runtime share for device discovery (an engine worker owns one group;
    an xl mesh group owns ``rows*corr`` devices).

    Args:
      group_size: devices per group (a solo worker is a 1-group; an xl
        ``rows=2,corr=2`` mesh is a 4-group).
      n_groups: how many groups to return; None = as many as fit.
      devices: explicit device list (default ``local_devices_stable()``).
      skip: leading devices to leave unassigned (e.g. the engine's solo
        workers occupy the head of the list; xl groups start after them).

    Returns the groups, each a tuple in stable order.  Returns an EMPTY
    list — never raises — when the devices cannot supply ``n_groups``
    full groups: the caller decides whether that is fatal (a declared
    data_parallel) or a typed skip (a replica without enough devices for
    the fleet's xl mesh, tools/compile_farm.py)."""
    if group_size < 1:
        raise ValueError(f"group_size={group_size} must be >= 1")
    if skip < 0:
        raise ValueError(f"skip={skip} must be >= 0")
    if devices is None:
        devices = local_devices_stable()
    pool = list(devices)[skip:]
    n_avail = len(pool) // group_size
    want = n_avail if n_groups is None else int(n_groups)
    if want < 0 or want > n_avail:
        return []
    return [tuple(pool[i * group_size:(i + 1) * group_size])
            for i in range(want)]


def loader_shard_kwargs() -> Dict[str, int]:
    """Per-process data-sharding kwargs for ``StereoLoader``: each process
    decodes only its contiguous slice of every global batch (the loader
    validates divisibility)."""
    return {"process_index": jax.process_index(),
            "process_count": jax.process_count()}
