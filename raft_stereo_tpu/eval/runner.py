"""Jitted inference runner with per-shape compile caching.

The eval datasets have per-image shapes (KITTI/ETH3D/Middlebury all vary);
under jit each padded shape compiles once and is reused.  The reference's
50-image warmup discard absorbs cuDNN autotuning — here it absorbs XLA
compilation the same way (reference: evaluate_stereo.py:77-82).
"""

from __future__ import annotations

import dataclasses
import logging
import time
import warnings
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.models.raft_stereo import RAFTStereo
from raft_stereo_tpu.ops.padding import InputPadder

log = logging.getLogger(__name__)

# Donated image buffers alias an output only when XLA finds one of the
# same byte size; the stereo forward returns a 1-channel f32 flow, so the
# 3-channel uint8 inputs never pair and every backend warns once per
# compile.  The donation is still declared (caller contract: inputs are
# consumed) so any future same-size output — warm-start state, multi-head
# returns — aliases without touching the dispatch sites; the warning is
# pure noise for this program shape.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

# GRU-iteration depth at which bf16 correlation measurably drifts on TRAINED
# weights: at iters=32 the per-pixel p99 reaches ~6.5-7 px with ΔEPE +0.04 px
# (BF16_DRIFT_r03.json), while at the realtime depth (7) drift is ≤0.03 px
# EPE.  Eval/demo runs at or past this depth flip the correlation features to
# fp32 (everything else stays bf16) unless the caller opts out.
DEEP_ITERS_FP32_CORR = 16


def effective_inference_config(config: RaftStereoConfig, iters: int,
                               corr_fp32_auto: bool = True
                               ) -> RaftStereoConfig:
    """The config an inference path should actually run: deep-iteration
    bf16 correlation gets ``corr_fp32`` flipped on (the measured 32-iter
    drift on trained weights, BF16_DRIFT_r03.json).  Shared by the solo
    ``InferenceRunner`` and the serving engine so both compile the same
    program for the same request class — the engine's batch-1 bucket is
    bitwise-equal to solo inference by construction."""
    if (corr_fp32_auto and iters >= DEEP_ITERS_FP32_CORR
            and config.mixed_precision and not config.corr_fp32):
        log.warning(
            "iters=%d >= %d with bf16 correlation: enabling corr_fp32 "
            "for this runner (measured 32-iter drift on trained "
            "weights, BF16_DRIFT_r03.json; pass corr_fp32_auto=False "
            "to keep bf16 corr)", iters, DEEP_ITERS_FP32_CORR)
        return dataclasses.replace(config, corr_fp32=True)
    return config


def early_exit_enabled(config: RaftStereoConfig) -> bool:
    """Whether ``make_forward`` programs for this config return the extra
    ``iters_used`` scalar (the convergence-gated while-loop path,
    models/raft_stereo.py)."""
    return config.exit_threshold_px > 0


def make_forward(model: RAFTStereo, iters: int, fetch_dtype=None,
                 donate_images: bool = True, warm_start: bool = False,
                 return_state: bool = False,
                 ctx: Optional[str] = None,
                 hidden_init: bool = False,
                 return_hidden: bool = False,
                 return_confidence: bool = False):
    """The one jitted inference program both the solo runner and the
    serving engine compile, per (padded shape, batch): cast -> forward ->
    optional half-precision fetch cast.  Built here so the two paths share
    one jaxpr by construction (the serving parity contract).

    With ``model.config.exit_threshold_px > 0`` the program returns
    ``(flow_up, iters_used)`` — the convergence-gated while-loop's actual
    trip count rides the fetch as one extra int32 scalar; otherwise the
    return is the flow alone and the program is bitwise-identical to the
    pre-early-exit build (``early_exit_enabled`` tells callers which
    contract they compiled).

    ``donate_images`` marks the image arguments donated
    (``donate_argnums``): both call sites upload fresh per-call device
    buffers, so the runtime is free to reclaim or alias them the moment
    the program consumes them.  Donation never changes numerics (tested)
    and the module-level filter above silences XLA's not-usable note for
    output shapes that cannot alias.

    Streaming variants (round 14 warm-start sessions; both default OFF,
    keeping the base program byte-for-byte the pre-session build):

    * ``return_state=True`` — the program additionally returns the final
      PADDED low-res x-flow (``flow_low``, (N, Hp/f, Wp/f) float32, f =
      ``config.downsample_factor``): the temporal state a streaming
      session feeds the next frame.  Same math, same ``flow_up`` values
      (pinned bitwise by tests/test_sessions.py) — one extra small
      output rides the fetch.  Return order: ``(flow_up, flow_low[,
      iters_used])``.
    * ``warm_start=True`` (implies ``return_state``) — the program takes
      a fourth traced argument ``flow_init`` ((N, Hp/f, Wp/f) float32)
      and seeds the GRU refinement from it instead of zero
      (models/raft_stereo.py; RAFT's warm start, arXiv 2109.07547 §3).
      ``flow_init`` is donated alongside the images when
      ``donate_images`` — it is the same shape/dtype as the
      ``flow_low`` output, so XLA can alias the state round-trip.
    * ``ctx`` ("save" | "reuse"; streaming only, implies the streaming
      signature) — the per-session CONTEXT cache (round 15): "save"
      appends the frame's context bundle (initial GRU hidden states +
      context biases, models/raft_stereo.py ``return_ctx``) as the LAST
      output; "reuse" appends the bundle as the LAST traced input and
      SKIPS the context encoder entirely (``ctx_init``) — the program a
      static-camera stream runs once the inter-frame delta proves the
      scene unchanged.  The bundle is a pytree and rides jit like any
      other argument; it is never donated (the session re-feeds it
      frame after frame from its host copy).
    * ``return_hidden=True`` (streaming only, implies the streaming
      signature) — the program additionally returns the FINAL per-level
      GRU hidden states (a tuple of (N, Hp/(f·2^l), Wp/(f·2^l), C_l)
      arrays in the model's compute dtype): the second half of the
      temporal state, which ``flow_init`` alone leaves cold (round-19
      hidden-state warm start).  Appended after ``iters_used`` and
      before the ctx bundle.
    * ``hidden_init=True`` (implies ``return_hidden``'s signature use —
      warm-h programs both consume and return the tree) — the program
      takes the previous frame's hidden tree as an extra traced input
      (after ``flow_init``, before any ctx bundle) and the refinement
      loop resumes from those EVOLVED states instead of the fresh
      ``tanh`` init.  Donated alongside the images when
      ``donate_images`` — same shapes/dtypes as the returned tree, so
      XLA can alias the state round-trip.

    * ``return_confidence=True`` — the program additionally returns the
      per-pixel confidence element (models/raft_stereo.py): one 2-tuple
      ``(conf_low, conf_up)`` of the (N, Hp/f, Wp/f) feature-resolution
      map and its convex-upsampled (N, Hp, Wp) full-res counterpart,
      both float32 in (0, 1], derived from the refinement loop's own
      convergence signals (final |Δdisparity|, trajectory EWMA, and —
      adaptive — the iteration-budget fraction).  Appended after
      ``iters_used`` and before the hidden tree.  Off (default) the
      program is bitwise-identical to the pre-confidence build (pinned
      by tests).  Composes with every streaming variant and with the
      base signature; unsupported on the mesh path
      (``make_forward_mesh``).

    Traced-input order (streaming): ``(variables, images1, images2
    [, flow_init][, hidden][, ctx])``; return order: ``(flow_up,
    flow_low[, iters_used][, confidence][, hidden][, ctx])``.

    With ``model.config.quant == "int8"`` every variant expects the
    QUANTIZED variable tree (quant/core.quantize_variables) and
    dequantizes it in-register at the top of the program — int8 is what
    uploads and resides; ``quant="int8_mxu"`` passes the int8 packs
    THROUGH to the traced program so the encoder convs run the
    int8×int8→int32 compute path (quant/matmul.QuantConv — the
    variables tree routes, no dequant is traced); ``quant="off"``
    builds the exact pre-quant jaxpr (no dequant ops are traced).
    """
    adaptive = early_exit_enabled(model.config)
    quantized = model.config.quant == "int8"

    def prepare(variables):
        if quantized:
            from raft_stereo_tpu.quant.core import dequantize_variables
            return dequantize_variables(variables)
        return variables

    if (warm_start or return_state or ctx is not None
            or hidden_init or return_hidden):
        if ctx not in (None, "save", "reuse"):
            raise ValueError(f"ctx={ctx!r}: use None, 'save', or 'reuse'")

        def fwd_stream(variables, images1, images2, *extra):
            img1 = images1.astype(jnp.float32)
            img2 = images2.astype(jnp.float32)
            pos = 0
            flow_init = None
            if warm_start:
                flow_init = extra[pos].astype(jnp.float32)
                pos += 1
            hidden = None
            if hidden_init:
                hidden = extra[pos]
                pos += 1
            ctx_init = extra[pos] if ctx == "reuse" else None
            kwargs = ({"return_confidence": True} if return_confidence
                      else {})
            out = model.apply(
                variables if not quantized else prepare(variables),
                img1, img2, iters=iters, test_mode=True,
                flow_init=flow_init, ctx_init=ctx_init,
                return_ctx=(ctx == "save"),
                hidden_init=hidden, return_hidden=return_hidden,
                **kwargs)
            flow_up = out[1]
            if fetch_dtype is not None:
                flow_up = flow_up.astype(fetch_dtype)
            # flow_low stays float32 regardless of fetch_dtype: it is the
            # next frame's init, and a half-precision state would compound
            # rounding frame over frame.  (The hidden tree rides in the
            # model's own compute dtype — it re-enters the SAME compute
            # path, so there is no precision boundary to cross.)
            ret = (flow_up, out[0].astype(jnp.float32))
            src = 2
            if adaptive:
                ret = ret + (out[src],)
                src += 1
            if return_confidence:
                ret = ret + (out[src],)
                src += 1
            if return_hidden:
                ret = ret + (out[src],)
                src += 1
            if ctx == "save":
                ret = ret + (out[src],)
            return ret

        donate: Tuple[int, ...] = ()
        if donate_images:
            donate = (1, 2)
            pos = 3
            if warm_start:
                donate = donate + (pos,)
                pos += 1
            if hidden_init:
                donate = donate + (pos,)
        return jax.jit(fwd_stream, donate_argnums=donate)

    def fwd(variables, images1, images2):  # (N, Hp, Wp, 3)
        img1 = images1.astype(jnp.float32)
        img2 = images2.astype(jnp.float32)
        kwargs = {"return_confidence": True} if return_confidence else {}
        out = model.apply(variables if not quantized
                          else prepare(variables),
                          img1, img2, iters=iters, test_mode=True,
                          **kwargs)
        flow_up = out[1]
        if fetch_dtype is not None:
            flow_up = flow_up.astype(fetch_dtype)
        if return_confidence:
            # Base-signature confidence: (flow_up[, iters_used], conf) —
            # the conf element is the model's (conf_low, conf_up) tuple.
            return ((flow_up, out[2], out[3]) if adaptive
                    else (flow_up, out[2]))
        return (flow_up, out[2]) if adaptive else flow_up

    return jax.jit(fwd, donate_argnums=(1, 2) if donate_images else ())


class MeshForward:
    """A mesh-sharded inference program with the ``make_forward`` calling
    convention (``fn(variables, images1, images2) -> flow_up``), plus the
    sharding-context plumbing a GSPMD trace needs.

    The model's sharded executors (``parallel/rows_sharded.py`` trunk,
    ``parallel/rows_gru.py`` loop, ``parallel/corr_sharded.py`` volume)
    discover their mesh through context managers that must be ACTIVE
    whenever the function traces — and jit traces lazily, at the first
    call for each shape and inside ``.lower()`` on the AOT path.  This
    wrapper re-enters the contexts around both entry points, so the
    serving engine can treat a sharded program exactly like a solo one
    (dispatch it, AOT-lower it for the persistent executable cache,
    instrument it through the CompileRegistry)."""

    def __init__(self, jitted, mesh, rows: int, corr: int):
        self._jitted = jitted
        self.mesh = mesh
        self._rows = rows
        self._corr = corr

    def _contexts(self):
        import contextlib

        from raft_stereo_tpu.parallel.corr_sharded import corr_sharding
        from raft_stereo_tpu.parallel.mesh import ROWS_AXIS
        from raft_stereo_tpu.parallel.rows_sharded import rows_sharding

        stack = contextlib.ExitStack()
        if self._rows > 1:
            stack.enter_context(rows_sharding(self.mesh, ROWS_AXIS))
        if self._corr > 1:
            stack.enter_context(corr_sharding(self.mesh))
        return stack

    def __call__(self, *args):
        with self._contexts():
            return self._jitted(*args)

    def lower(self, *args, **kwargs):
        with self._contexts():
            return self._jitted.lower(*args, **kwargs)


def make_forward_mesh(model: RAFTStereo, iters: int, mesh,
                      fetch_dtype=None, donate_images: bool = True):
    """Mesh-sharded variant of ``make_forward``: ONE jitted program whose
    forward runs sharded over ``mesh`` per the model config's
    ``rows_shards`` / ``corr_w2_shards`` (+ ``rows_gru`` for full-loop
    context parallelism), with the image buffers and parameters
    replicated in and the full-resolution disparity GATHERED out — the
    program an "xl" serving bucket dispatches when one full-resolution
    pair cannot fit (or meet latency) on one device
    (ROWSGRU_MEMORY_r05.json: 141 GiB at rows=1 vs 13.8 GiB/device on a
    16-way rows mesh).

    Same calling convention and numerics contract as the base program:
    ``fn(variables, images1, images2) -> (N, Hp, Wp) flow`` with the
    sharded output equal to the solo program's up to float reassociation
    (the MULTICHIP_r01–r05 parity line; tests/test_xl.py pins 5e-4).
    With a trivial mesh (every axis 1) this IS ``make_forward`` — the
    identical jaxpr, bitwise, so a rows=1 xl tier degrades to the solo
    program instead of a subtly different one.

    Restrictions (validated here so misconfigurations fail at build, not
    mid-dispatch): early exit is unsupported (the row-sharded loop
    executor runs a fixed-depth program — config.py already rejects the
    combination; the corr-only mesh inherits the same contract so every
    xl program has one output arity), and so are the streaming
    warm/ctx families (sessions stay single-device)."""
    import jax

    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = model.config
    rows, corr = cfg.rows_shards, cfg.corr_w2_shards
    if early_exit_enabled(cfg):
        raise ValueError(
            "make_forward_mesh: early exit (exit_threshold_px > 0) is "
            "unsupported on mesh-sharded programs — xl tiers run the "
            "fixed-depth program")
    if rows <= 1 and corr <= 1:
        # Trivial mesh: the solo program, bitwise (tests/test_xl.py).
        return make_forward(model, iters, fetch_dtype,
                            donate_images=donate_images)

    def fwd(variables, images1, images2):  # (N, Hp, Wp, 3)
        img1 = images1.astype(jnp.float32)
        img2 = images2.astype(jnp.float32)
        out = model.apply(variables, img1, img2, iters=iters,
                          test_mode=True)
        flow_up = out[1]
        if fetch_dtype is not None:
            flow_up = flow_up.astype(fetch_dtype)
        return flow_up

    # Replicated in, gathered out: the host uploads each image once per
    # device (megabytes — small next to the sharded activations), the
    # shard_map executors inside re-slice to their own row/bin spans, and
    # the caller fetches one assembled full-res disparity with no
    # host-side reassembly.
    repl = NamedSharding(mesh, P())
    jitted = jax.jit(fwd,
                     donate_argnums=(1, 2) if donate_images else (),
                     in_shardings=(repl, repl, repl),
                     out_shardings=repl)
    return MeshForward(jitted, mesh, rows, corr)


@dataclasses.dataclass
class StreamFrame:
    """One frame of a warm-started sequence (``InferenceRunner.run_stream``).

    ``flow`` is the usual unpadded (H, W) x-flow; ``flow_low`` is the
    PADDED low-res x-flow to feed back as the next frame's
    ``prev_flow_low`` — padded on purpose: consecutive frames share the
    padded grid, so the state round-trips without resampling."""

    flow: np.ndarray             # (H, W) float32 x-flow (= -disparity)
    flow_low: np.ndarray         # (Hp/f, Wp/f) float32 padded low-res state
    seconds: float               # same stop clock as __call__ (result fetch)
    iters_used: Optional[int]    # GRU trip count (None without early exit)
    warm: bool                   # True when prev_flow_low seeded the GRU
    # Final per-level GRU hidden states (tuple of (h_l, w_l, C_l) host
    # arrays, batch axis stripped) — the next frame's ``prev_hidden``.
    # None unless the caller asked for it (``carry_hidden``).
    hidden: Optional[object] = None

    @property
    def disparity(self) -> np.ndarray:
        return -self.flow


class InferenceRunner:
    """``runner(image1, image2)`` → full-resolution disparity-flow (H, W).

    Inputs are (H, W, 3) float/uint8 NumPy images; padding to /32,
    test-mode forward, and exact unpadding happen inside.
    """

    def __init__(self, config: RaftStereoConfig, variables,
                 iters: int = 32, divis_by: int = 32,
                 shape_bucket: Optional[int] = None,
                 max_cached_shapes: int = 16,
                 corr_fp32_auto: bool = True,
                 fetch_dtype: Optional[str] = None,
                 cost_registry=None, cost_site: str = "eval",
                 donate_images: bool = True,
                 exit_threshold_px: Optional[float] = None,
                 exit_min_iters: Optional[int] = None,
                 quant: Optional[str] = None,
                 quant_act_scales=None):
        """``shape_bucket`` (e.g. 64) pads to a coarser grid than the
        reference's /32, collapsing nearby image shapes into one compiled
        program — fewer Middlebury recompiles at the cost of deviating from
        the reference's exact padding (off by default; the parity tests
        require /32 semantics).  ``max_cached_shapes`` bounds the per-shape
        executable cache LRU-style so a many-shape eval (Middlebury-F) holds
        memory flat instead of accumulating compiled programs forever.
        ``corr_fp32_auto`` guards deep-iteration bf16 correlation: at
        ``iters >= DEEP_ITERS_FP32_CORR`` a mixed-precision config without
        ``corr_fp32`` gets it enabled here (with a one-line warning) —
        the measured 32-iter drift on trained weights is the reason
        (BF16_DRIFT_r03.json).  Pass False to measure raw bf16 numerics
        (tools/bf16_drift.py does).
        ``cost_registry`` (telemetry/costs.CompileRegistry | None): when
        set, every per-shape compile routes through the AOT path
        (``jit(...).lower(...).compile()``) so the executable's
        cost/memory analysis and compile wall time are recorded, and the
        cache's size/evictions feed its instruments; None (default) keeps
        the exact plain-``jax.jit`` dispatch.  ``cost_site`` labels the
        records ("eval" here, "serving" for service workers).
        ``fetch_dtype`` ("fp16" | "bf16" | None): cast the flow on DEVICE
        before the device->host fetch, halving the down-leg bytes — the
        dominant cost of the product path behind a bandwidth-bound tunnel
        (PRODUCT_r04.json: 162.7 ms/image fp32 fetch).  fp16 is the right
        half precision for a disparity map: |flow| < 2048 everywhere the
        metrics are defined (|d| < 192 — evaluate_stereo.py:133-135), so
        the worst ulp is 0.125 px at the far end and the mean rounding
        error is ~ulp/4, far below metric noise; bf16's 8-bit mantissa
        would round 190 px to ±0.75 px.  Results are returned as float32
        regardless.
        ``exit_threshold_px`` / ``exit_min_iters`` (None = the config's
        own knobs): adaptive GRU early exit — with a threshold > 0 the
        test-mode loop stops once the mean |Δdisparity| stalls
        (config.py), ``iters`` becomes the depth CAP, and every call
        records its actual trip count (``last_iters_used`` /
        ``iters_used_mean()``).  The default keeps the fixed-depth scan
        program bitwise-unchanged.
        ``quant`` (None = the config's own knob): "int8" runs this
        runner on the post-training int8 path — the given fp32
        ``variables`` are quantized HERE at construction
        (quant/core.quantize_variables; checkpoints on disk stay fp32)
        and every compiled program dequantizes in-register; "int8_mxu"
        additionally keeps the packs IN the traced program so encoder
        convs multiply int8×int8→int32 (quant/matmul.py).
        ``quant_act_scales`` (int8_mxu only): calibrated per-conv
        activation scales (quant/calibrate.conv_input_scales) baked
        into the packs at quantization time; None leaves every conv on
        the dynamic in-graph max-abs fallback."""
        if shape_bucket is not None and shape_bucket % divis_by:
            raise ValueError(f"shape_bucket={shape_bucket} must be a "
                             f"multiple of the model's /{divis_by} "
                             f"divisibility requirement")
        if max_cached_shapes < 1:
            raise ValueError(
                f"max_cached_shapes={max_cached_shapes} must be >= 1")
        # ``self.config`` stays the config AS GIVEN — consumers compare it
        # against their own (eval.validate.make_validation_fn re-creates the
        # runner on mismatch); the guard's flip lives in effective_config.
        self.config = config
        if (exit_threshold_px is not None or exit_min_iters is not None
                or quant is not None):
            config = dataclasses.replace(
                config,
                exit_threshold_px=(config.exit_threshold_px
                                   if exit_threshold_px is None
                                   else exit_threshold_px),
                exit_min_iters=(config.exit_min_iters
                                if exit_min_iters is None
                                else exit_min_iters),
                quant=config.quant if quant is None else quant)
        self.effective_config = effective_inference_config(
            config, iters, corr_fp32_auto)
        self.early_exit = early_exit_enabled(self.effective_config)
        if self.effective_config.quant != "off":
            # Host-side, once per runner: int8 weights are what upload
            # and reside on device; disk checkpoints stay fp32.
            from raft_stereo_tpu.quant.core import (quantize_variables,
                                                    tree_is_quantized)
            if not tree_is_quantized(variables):
                variables = quantize_variables(
                    variables, self.effective_config,
                    act_scales=quant_act_scales)
        # Per-call trip-count accounting (early exit only): the CLIs print
        # it and tools/early_exit_report.py averages it per validator.
        self.last_iters_used: Optional[int] = None
        self._iters_used_sum = 0
        self._iters_used_calls = 0
        self.variables = variables
        self.iters = iters
        self.divis_by = shape_bucket or divis_by
        self.max_cached_shapes = max_cached_shapes
        if fetch_dtype not in (None, "fp16", "bf16"):
            raise ValueError(f"fetch_dtype={fetch_dtype!r}: use 'fp16', "
                             f"'bf16', or None (full fp32 fetch)")
        self.fetch_dtype = {None: None, "fp16": jnp.float16,
                            "bf16": jnp.bfloat16}[fetch_dtype]
        self.model = RAFTStereo(self.effective_config)
        self.cost_registry = cost_registry
        self.cost_site = cost_site
        self.donate_images = donate_images
        self._compiled: Dict[Tuple[int, int], any] = {}
        # Streaming (warm-start) programs live in their own small cache:
        # they carry an extra state output (and, warm, an extra input),
        # so they are distinct executables from the ``_compiled`` ones —
        # and keeping them apart leaves the sessionless cache, its cost
        # keys, and its eviction accounting byte-for-byte untouched.
        self._stream_compiled: Dict[Tuple, any] = {}

    def _cost_key(self, padded_hw: Tuple[int, int], batch: int) -> str:
        """Stable label of one compile point in the cost registry —
        what GET /debug/compiles lists and what the serving MFU path
        looks up (``compiled_cost``)."""
        return (f"{self.cost_site}.forward"
                f"({padded_hw[0]}x{padded_hw[1]},b{batch})")

    def compiled_cost(self, padded_hw: Tuple[int, int], batch: int = 1):
        """The cost record for a compiled (padded shape, batch)
        executable, or None (no registry / not compiled yet / analysis
        degraded)."""
        if self.cost_registry is None:
            return None
        return self.cost_registry.get(self._cost_key(padded_hw, batch))

    def _forward_for(self, padded_hw: Tuple[int, int], batch: int = 1):
        """One compiled program per (PADDED shape, batch) covering
        cast -> forward.

        Keyed by the padded shape so distinct raw shapes that pad to the
        same grid share one executable (real KITTI-2015 mixes 375x1242 /
        370x1224 / 376x1241 — all 384x1248 padded; a raw-shape key would
        compile each).  Padding/unpadding happen on the HOST in NumPy: the
        device sees exactly one dispatch per image, which matters because
        on a remote-tunneled device per-op host round-trips — not compute —
        dominate the per-image product path (bench_product.py)."""
        key = (padded_hw, batch)
        if key not in self._compiled:
            while len(self._compiled) >= self.max_cached_shapes:
                # dicts iterate in insertion order -> drop the oldest
                evicted = next(iter(self._compiled))
                self._compiled.pop(evicted)
                log.info(
                    "compile cache full (max_cached_shapes=%d): evicting "
                    "oldest executable for padded shape %s batch %d — "
                    "its next use re-pays XLA compile time",
                    self.max_cached_shapes, evicted[0], evicted[1])
                if self.cost_registry is not None:
                    self.cost_registry.note_runner_eviction(
                        self._cost_key(*evicted), len(self._compiled))
            fwd = make_forward(self.model, self.iters, self.fetch_dtype,
                               donate_images=self.donate_images)
            if self.cost_registry is not None:
                # AOT-instrumented dispatch: first call lowers + compiles
                # through the registry (cost/memory analysis recorded),
                # later calls hit the cached executable (telemetry/costs).
                fwd = self.cost_registry.instrument(
                    fwd, key=self._cost_key(padded_hw, batch),
                    site=self.cost_site)
            self._compiled[key] = fwd
            if self.cost_registry is not None:
                self.cost_registry.note_runner_cache_size(
                    len(self._compiled))
        else:  # LRU refresh
            self._compiled[key] = self._compiled.pop(key)
        return self._compiled[key]

    # -------------------------------------------------- iters-used tracking
    def _note_iters_used(self, iters_used) -> int:
        used = int(iters_used)
        self.last_iters_used = used
        self._iters_used_sum += used
        self._iters_used_calls += 1
        return used

    def iters_used_mean(self) -> Optional[float]:
        """Mean GRU trip count over the calls since the last reset; None
        without early exit (the fixed path always runs ``iters``)."""
        if not self._iters_used_calls:
            return None
        return self._iters_used_sum / self._iters_used_calls

    def reset_iters_used(self) -> None:
        self.last_iters_used = None
        self._iters_used_sum = 0
        self._iters_used_calls = 0

    def __call__(self, image1: np.ndarray, image2: np.ndarray,
                 ) -> Tuple[np.ndarray, float]:
        """Returns ``(flow, seconds)`` — flow is (H, W) x-flow (=-disparity),
        seconds is the full per-image product path: host->device copy, pad,
        forward, unpad, and the host fetch of the result.

        The stop clock is the ``np.asarray`` fetch — a REAL device->host
        transfer.  ``jax.block_until_ready`` must NOT be the stop condition
        here: behind this environment's async device tunnel it returns at
        DISPATCH (measured, bench.py:9-14), which would make per-image FPS
        fiction.  A first call at a new padded shape includes XLA
        compilation; the warmup discard absorbs it (``FpsProtocol``), the
        way the reference's 50-image discard absorbs cuDNN autotune
        (reference: evaluate_stereo.py:77-82)."""
        assert image1.ndim == 3 and image1.shape == image2.shape
        t0 = time.perf_counter()
        padder = InputPadder((1,) + image1.shape, divis_by=self.divis_by)
        l, r, t, b = padder.pads
        # Host-side replicate pad (NumPy — microseconds) and caller-dtype
        # upload: KITTI/eval images arrive uint8, so the per-image copy is
        # 4x smaller; the cast to float happens on device inside the
        # compiled program.
        spec = ((t, b), (l, r), (0, 0))
        p1 = np.pad(np.asarray(image1), spec, mode="edge")
        p2 = np.pad(np.asarray(image2), spec, mode="edge")
        fwd = self._forward_for(p1.shape[:2])
        out = fwd(self.variables, jnp.asarray(p1[None]),
                  jnp.asarray(p2[None]))
        if self.early_exit:
            out, iters_used = out
            self._note_iters_used(iters_used)
        flow_padded = np.asarray(out)[0]
        flow = padder.unpad(flow_padded[None])[0]  # pure NumPy slicing
        if flow.dtype != np.float32:               # half-precision fetch
            flow = flow.astype(np.float32)
        elapsed = time.perf_counter() - t0
        return np.ascontiguousarray(flow), elapsed

    def run_batch(self, images1, images2) -> Tuple[np.ndarray, float]:
        """Batched product mode: ONE host->device upload, ONE compiled
        forward, ONE fetch for N same-shape pairs — amortizes the per-image
        round-trip latency that dominates remote-device deployments
        (PRODUCT_r03.json decomposition: ~116 ms RTT + ~176 ms transfers
        per image on the bench tunnel).  The per-image ``__call__`` remains
        the reference protocol (evaluate_stereo.py:60-109 is per-image by
        definition); this is the throughput surface.

        Args: ``images1``/``images2`` — sequences of (H, W, 3) images, all
        the same shape.  Returns ``(flows (N, H, W), seconds)``; the stop
        clock is the result fetch, as in ``__call__``.
        """
        assert len(images1) == len(images2) and len(images1) > 0
        shape = np.asarray(images1[0]).shape
        assert all(np.asarray(im).shape == shape
                   for im in (*images1, *images2)), \
            "run_batch requires same-shape pairs; pad upstream or use " \
            "per-image calls for mixed shapes"
        t0 = time.perf_counter()
        padder = InputPadder((1,) + shape, divis_by=self.divis_by)
        l, r, t, b = padder.pads
        spec = ((0, 0), (t, b), (l, r), (0, 0))
        p1 = np.pad(np.stack(images1), spec, mode="edge")
        p2 = np.pad(np.stack(images2), spec, mode="edge")
        fwd = self._forward_for(p1.shape[1:3], batch=len(images1))
        out = fwd(self.variables, jnp.asarray(p1), jnp.asarray(p2))
        if self.early_exit:
            out, iters_used = out
            self._note_iters_used(iters_used)
        flows_padded = np.asarray(out)
        flows = padder.unpad(flows_padded)
        if flows.dtype != np.float32:              # half-precision fetch
            flows = flows.astype(np.float32)
        elapsed = time.perf_counter() - t0
        return np.ascontiguousarray(flows), elapsed

    # ------------------------------------------------------------- streaming
    def _stream_forward_for(self, padded_hw: Tuple[int, int], warm: bool,
                            hidden_in: bool = False,
                            hidden_out: bool = False):
        """The state-returning (and, warm, state-consuming) program for
        one padded shape — the sequence/demo twin of the serving engine's
        warm bucket executables.  Bounded like ``_compiled``.  The
        hidden flags select the round-19 warm-h program variants; both
        False keeps the exact round-14 programs (and cache keys)."""
        key = (padded_hw, warm, hidden_in, hidden_out)
        if key not in self._stream_compiled:
            while len(self._stream_compiled) >= self.max_cached_shapes:
                self._stream_compiled.pop(
                    next(iter(self._stream_compiled)))
            self._stream_compiled[key] = make_forward(
                self.model, self.iters, self.fetch_dtype,
                donate_images=self.donate_images,
                warm_start=warm, return_state=True,
                hidden_init=hidden_in, return_hidden=hidden_out)
        else:  # LRU refresh
            self._stream_compiled[key] = self._stream_compiled.pop(key)
        return self._stream_compiled[key]

    def run_stream(self, image1: np.ndarray, image2: np.ndarray,
                   prev_flow_low: Optional[np.ndarray] = None,
                   prev_hidden: Optional[object] = None,
                   carry_hidden: bool = False) -> StreamFrame:
        """One frame of a temporally ordered sequence: like ``__call__``
        but the GRU warm-starts from ``prev_flow_low`` (the previous
        frame's ``StreamFrame.flow_low``) and the returned frame carries
        the state to chain forward.  ``prev_flow_low=None`` (frame 0, or
        after a scene cut) runs the cold zero-init — the same math as the
        sessionless path (pinned bitwise by tests/test_sessions.py).

        With early exit configured (``exit_threshold_px``) a warm frame
        typically stalls after far fewer iterations than a cold one —
        the FPS win bench_stream.py measures.  A ``prev_flow_low`` whose
        shape does not match this frame's padded low-res grid raises:
        resolution changes are a caller-visible stream break, not
        something to resample over silently.

        ``carry_hidden=True`` asks for the frame's final GRU hidden
        states on the returned ``StreamFrame.hidden``; passing them back
        as ``prev_hidden`` (together with ``prev_flow_low``) runs the
        warm-h program — the GRU resumes its own trajectory instead of
        re-deriving it from the context encoder every frame (round 19;
        requires ``prev_flow_low``, the hidden state is meaningless
        without the disparity it evolved against).  Both default off:
        the round-14 programs and their cache keys are untouched."""
        assert image1.ndim == 3 and image1.shape == image2.shape
        t0 = time.perf_counter()
        padder = InputPadder((1,) + image1.shape, divis_by=self.divis_by)
        l, r, t, b = padder.pads
        spec = ((t, b), (l, r), (0, 0))
        p1 = np.pad(np.asarray(image1), spec, mode="edge")
        p2 = np.pad(np.asarray(image2), spec, mode="edge")
        f = self.effective_config.downsample_factor
        low_hw = (p1.shape[0] // f, p1.shape[1] // f)
        warm = prev_flow_low is not None
        if prev_hidden is not None and not warm:
            raise ValueError("prev_hidden needs prev_flow_low: the "
                             "hidden state is meaningless without the "
                             "disparity it evolved against")
        if warm and tuple(prev_flow_low.shape) != low_hw:
            raise ValueError(
                f"prev_flow_low shape {prev_flow_low.shape} does not "
                f"match this frame's padded low-res grid {low_hw} — the "
                f"stream changed resolution; restart with "
                f"prev_flow_low=None")
        hidden_in = prev_hidden is not None
        hidden_out = carry_hidden or hidden_in
        fwd = self._stream_forward_for(p1.shape[:2], warm,
                                       hidden_in=hidden_in,
                                       hidden_out=hidden_out)
        args = [self.variables, jnp.asarray(p1[None]), jnp.asarray(p2[None])]
        if warm:
            args.append(jnp.asarray(
                np.ascontiguousarray(prev_flow_low, dtype=np.float32)[None]))
        if hidden_in:
            args.append(tuple(jnp.asarray(np.asarray(h)[None])
                              for h in prev_hidden))
        out = fwd(*args)
        iters_used = None
        pos = 2
        if self.early_exit:
            flow_up, flow_low = out[0], out[1]
            iters_used = self._note_iters_used(out[2])
            pos = 3
        else:
            flow_up, flow_low = out[0], out[1]
        hidden = None
        if hidden_out:
            hidden = tuple(np.asarray(h)[0] for h in out[pos])
        flow_padded = np.asarray(flow_up)[0]
        state = np.ascontiguousarray(np.asarray(flow_low)[0],
                                     dtype=np.float32)
        flow = padder.unpad(flow_padded[None])[0]
        if flow.dtype != np.float32:               # half-precision fetch
            flow = flow.astype(np.float32)
        return StreamFrame(flow=np.ascontiguousarray(flow),
                           flow_low=state,
                           seconds=time.perf_counter() - t0,
                           iters_used=iters_used, warm=warm,
                           hidden=hidden)

    def disparity(self, image1: np.ndarray, image2: np.ndarray) -> np.ndarray:
        """Positive disparity map (the demo/user-facing convention,
        reference: demo.py:47-50 saves ``-flow_up``)."""
        flow, _ = self(image1, image2)
        return -flow
