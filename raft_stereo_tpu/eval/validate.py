"""The four validation harnesses + the KITTI FPS benchmark protocol.

One generic loop parameterized by each benchmark's quirks, reproducing the
reference's metric definitions exactly (reference: evaluate_stereo.py:19-189):

| benchmark   | bad-px thr | valid mask                         | D1 aggregation |
|-------------|-----------:|------------------------------------|----------------|
| ETH3D       |        1.0 | valid >= 0.5                       | per-image mean |
| KITTI-2015  |        3.0 | valid >= 0.5                       | per-PIXEL pool |
| FlyingThings|        1.0 | valid >= 0.5 and |flow| < 192      | per-PIXEL pool |
| Middlebury  |        2.0 | valid >= -0.5 (occluded INCLUDED)  | per-image mean |
|             |            |   and flow > -1000                 |                |

KITTI additionally times each forward and reports FPS with the first 50
images discarded as warmup (evaluate_stereo.py:77-82,105-107) — under jit
the warmup absorbs XLA compilation instead of cuDNN autotuning.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Dict, Optional

import numpy as np

from raft_stereo_tpu.data import datasets as ds
from raft_stereo_tpu.eval.runner import InferenceRunner

log = logging.getLogger(__name__)

WARMUP_IMAGES = 50


def single_device_cfg(cfg):
    """Strip multi-device executor flags for the periodic validator: it is
    single-device inference, the sharded executors are numerically
    equivalent (their parity tests), and they would demand an active mesh
    context inside the hook."""
    if cfg.rows_shards > 1 or cfg.corr_w2_shards > 1 or cfg.rows_gru:
        import dataclasses
        return dataclasses.replace(cfg, rows_shards=1, corr_w2_shards=1,
                                   rows_gru=False)
    return cfg


def _validate(runner: InferenceRunner, dataset, name: str,
              bad_threshold: float,
              valid_fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
              pixel_pool_d1: bool, timed: bool = False,
              max_images: Optional[int] = None) -> Dict[str, float]:
    epe_list, out_list, elapsed = [], [], []
    n = len(dataset) if max_images is None else min(len(dataset), max_images)
    for i in range(n):
        sample = dataset[i]
        flow_gt = sample["flow"]
        valid_gt = sample["valid"]
        flow_pr, secs = runner(sample["image1"], sample["image2"])
        assert flow_pr.shape == flow_gt.shape, (flow_pr.shape, flow_gt.shape)
        if timed and i > WARMUP_IMAGES:
            elapsed.append(secs)

        epe = np.abs(flow_pr - flow_gt).ravel()
        val = valid_fn(valid_gt.ravel(), flow_gt.ravel())
        bad = epe > bad_threshold
        image_epe = float(epe[val].mean())
        image_bad = float(bad[val].mean())
        log.info("%s %d/%d. EPE %.4f D1 %.4f", name, i + 1, n,
                 image_epe, image_bad)
        epe_list.append(image_epe)
        out_list.append(bad[val] if pixel_pool_d1 else image_bad)

    epe = float(np.mean(epe_list))
    d1 = 100 * float(np.mean(np.concatenate(out_list) if pixel_pool_d1
                             else np.asarray(out_list)))
    result = {f"{name}-epe": epe, f"{name}-d1": d1}
    if timed and elapsed:
        mean_rt = float(np.mean(elapsed))
        result[f"{name}-fps"] = 1.0 / mean_rt
        print(f"Validation {name}: EPE {epe}, D1 {d1}, "
              f"{1.0 / mean_rt:.2f}-FPS ({mean_rt:.3f}s)")
    else:
        print(f"Validation {name}: EPE {epe}, D1 {d1}")
    return result


def make_validation_fn(model_cfg, train_cfg, data_root: str = "datasets",
                       datasets: tuple = ("things",),
                       max_images: Optional[int] = None):
    """Periodic-validation hook for ``training.train_loop.train``.

    Returns ``validate_fn(variables, model_cfg=None) -> dict`` running the named validators
    every ``train_cfg.validation_frequency`` steps — the reference's
    every-10k ``validate_things`` regression check
    (reference: train_stereo.py:183-193), generalized to any subset of the
    four benchmarks.  One InferenceRunner is reused across calls (variables
    are a call argument of its jitted forward, so swapping them does not
    recompile)."""
    dispatch = {
        "things": lambda r: validate_things(r, root=data_root,
                                            max_images=max_images),
        "kitti": lambda r: validate_kitti(
            r, root=os.path.join(data_root, "KITTI"), max_images=max_images),
        "eth3d": lambda r: validate_eth3d(
            r, root=os.path.join(data_root, "ETH3D"), max_images=max_images),
        "middlebury": lambda r: validate_middlebury(
            r, root=os.path.join(data_root, "Middlebury"), split="H",
            max_images=max_images),
    }
    unknown = set(datasets) - set(dispatch)
    if unknown:
        raise ValueError(f"unknown validation datasets {sorted(unknown)}; "
                         f"choose from {sorted(dispatch)}")
    runner = None

    captured_cfg = model_cfg

    def validate_fn(variables, model_cfg=None):
        # model_cfg=None -> the config captured at construction; train()
        # passes the authoritative one (a --restore_ckpt re-derives the
        # architecture, so the CLI-time config can be stale).
        cfg = single_device_cfg(captured_cfg if model_cfg is None
                                else model_cfg)
        nonlocal runner
        if runner is None or runner.config != cfg:
            runner = InferenceRunner(cfg, variables,
                                     iters=train_cfg.valid_iters)
        else:
            runner.variables = variables
        results = {}
        for name in datasets:
            results.update(dispatch[name](runner))
        return results

    return validate_fn


def sequence_drift(runner: InferenceRunner, dataset, name: str,
                   max_images: Optional[int] = None) -> Dict[str, float]:
    """Warm-start drift harness (round 14 streaming sessions): run the
    dataset's frames IN ORDER twice — cold (every frame zero-init, the
    reference per-frame protocol) and warm (each frame's GRU seeded from
    the previous frame's low-res disparity, ``InferenceRunner.run_stream``)
    — and report the EPE cost of chaining: ``<name>-warm-drift-epe`` =
    warm EPE − cold EPE on the ``valid >= 0.5`` mask.

    On a real video sequence the drift should be ~0 (the warm init is
    already close to the answer); on shuffled/unrelated frames it measures how
    robustly the GRU escapes a WRONG init — the bound the streaming
    scene-cut fallback exists to protect.  With early exit configured the
    per-pass mean ``iters_used`` and FPS quantify the warm win."""
    n = len(dataset) if max_images is None else min(len(dataset),
                                                   max_images)

    def _epe(flow_pr, flow_gt, valid_gt) -> float:
        err = np.abs(flow_pr - flow_gt).ravel()
        # Known-GT pixels only: Middlebury marks unknown GT with ±inf
        # (its validator masks `flow > -1000` on top of the nocc mask —
        # eval/validate.validate_middlebury), and its valid array
        # encodes occlusion rather than GT validity, so fall back to
        # the known-GT mask when the 0.5 cut selects nothing.
        gt = flow_gt.ravel()
        known = np.isfinite(gt) & (gt > -1000)
        mask = (valid_gt.ravel() >= 0.5) & known
        if not mask.any():
            mask = known
        return float(err[mask].mean())

    out: Dict[str, float] = {}
    for mode in ("cold", "warm"):
        runner.reset_iters_used()
        state = None
        epes, secs, iters = [], [], []
        for i in range(n):
            sample = dataset[i]
            frame = runner.run_stream(
                sample["image1"], sample["image2"],
                prev_flow_low=state if mode == "warm" else None)
            if mode == "warm":
                state = frame.flow_low
            # Frame 0 pays the cold compile; the warm pass's frame 1
            # additionally pays the warm-program compile — drop both
            # from the FPS clock.
            if i > (1 if mode == "warm" else 0):
                secs.append(frame.seconds)
            if frame.iters_used is not None:
                iters.append(frame.iters_used)
            epes.append(_epe(frame.flow, sample["flow"], sample["valid"]))
        out[f"{name}-epe-{mode}"] = float(np.mean(epes))
        if secs:
            out[f"{name}-fps-{mode}"] = float(1.0 / np.mean(secs))
        if iters:
            out[f"{name}-iters-{mode}-mean"] = float(np.mean(iters))
    out[f"{name}-warm-drift-epe"] = (out[f"{name}-epe-warm"]
                                     - out[f"{name}-epe-cold"])
    print(f"Sequence {name}: cold EPE {out[f'{name}-epe-cold']:.4f}, "
          f"warm EPE {out[f'{name}-epe-warm']:.4f}, drift "
          f"{out[f'{name}-warm-drift-epe']:+.4f}")
    return out


def validate_eth3d(runner: InferenceRunner, root: str = "datasets/ETH3D",
                   max_images: Optional[int] = None) -> Dict[str, float]:
    """ETH3D two-view training split (reference: evaluate_stereo.py:19-57)."""
    return _validate(runner, ds.ETH3D(root=root), "eth3d", 1.0,
                     lambda v, f: v >= 0.5, pixel_pool_d1=False,
                     max_images=max_images)


def validate_kitti(runner: InferenceRunner, root: str = "datasets/KITTI",
                   max_images: Optional[int] = None) -> Dict[str, float]:
    """KITTI-2015 training split; also the FPS harness
    (reference: evaluate_stereo.py:60-109)."""
    return _validate(runner, ds.KITTI(root=root), "kitti", 3.0,
                     lambda v, f: v >= 0.5, pixel_pool_d1=True, timed=True,
                     max_images=max_images)


def validate_things(runner: InferenceRunner, root: str = "datasets",
                    dstype: str = "frames_finalpass",
                    max_images: Optional[int] = None) -> Dict[str, float]:
    """FlyingThings3D TEST subset (reference: evaluate_stereo.py:112-147)."""
    return _validate(
        runner, ds.SceneFlow(root=root, dstype=dstype, things_test=True),
        "things", 1.0,
        lambda v, f: (v >= 0.5) & (np.abs(f) < 192),
        pixel_pool_d1=True, max_images=max_images)


def validate_middlebury(runner: InferenceRunner,
                        root: str = "datasets/Middlebury", split: str = "F",
                        max_images: Optional[int] = None) -> Dict[str, float]:
    """MiddEval3 training set; the valid mask keeps OCCLUDED pixels
    (valid >= -0.5 passes the 0/1 nocc mask entirely) and drops only
    unknown-GT pixels (flow > -1000) — reference: evaluate_stereo.py:173-175."""
    return _validate(
        runner, ds.Middlebury(root=root, split=split),
        f"middlebury{split}", 2.0,
        lambda v, f: (v >= -0.5) & (f > -1000),
        pixel_pool_d1=False, max_images=max_images)
