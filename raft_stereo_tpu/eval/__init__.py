from raft_stereo_tpu.eval.runner import InferenceRunner, StreamFrame
from raft_stereo_tpu.eval.validate import (sequence_drift, validate_eth3d,
                                           validate_kitti,
                                           validate_middlebury,
                                           validate_things)
