from raft_stereo_tpu.eval.runner import InferenceRunner
from raft_stereo_tpu.eval.validate import (validate_eth3d, validate_kitti,
                                           validate_middlebury,
                                           validate_things)
