"""Post-training int8 quantization: the low-precision inference tier
behind the measured-drift gate (ROADMAP open item 2).

``core`` holds the per-channel symmetric weight quantization and the
pyramid/feature activation quantizers; ``calibrate`` runs the
in-distribution calibration pass and owns the checkpoint-adjacent scale
file.  See docs/architecture.md §Quantization for the tier ladder
placement and the drift-gate policy (tools/quant_drift.py)."""

from raft_stereo_tpu.quant.calibrate import (DEFAULT_PERCENTILE,
                                             SCALES_VERSION, calibrate,
                                             conv_input_scales, corr_scales,
                                             load_scales, save_scales)
from raft_stereo_tpu.quant.matmul import (QuantConv, int8_matmul_report,
                                          quantized_conv_apply)
from raft_stereo_tpu.quant.core import (QUANT_MODES, clipped_scale,
                                        dequantize_array,
                                        dequantize_variables,
                                        dynamic_scale, is_quantized_leaf,
                                        quantize_array,
                                        quantize_symmetric,
                                        quantize_variables,
                                        quantized_param_bytes,
                                        tree_is_quantized)

__all__ = ["DEFAULT_PERCENTILE", "QUANT_MODES", "QuantConv",
           "SCALES_VERSION", "calibrate", "clipped_scale",
           "conv_input_scales", "corr_scales", "dequantize_array",
           "dequantize_variables", "dynamic_scale", "int8_matmul_report",
           "is_quantized_leaf", "load_scales", "quantize_array",
           "quantize_symmetric", "quantize_variables",
           "quantized_conv_apply", "quantized_param_bytes", "save_scales",
           "tree_is_quantized"]
