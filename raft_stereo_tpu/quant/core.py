"""Post-training int8 weight quantization for the inference tier.

The cost report's roofline split classifies the encoders as the dominant
per-frame cost at streaming shapes and the correlation lookup as
memory-bound (COST_REPORT_r10.json), so the bytes a program MOVES — not
the flops it runs — bound the turbo tier's throughput.  This module
implements the weight half of the int8 story:

* **Per-channel symmetric quantization** (Wu et al. 2020, "Integer
  Quantization for Deep Learning Inference" §4: per-output-channel scales
  hold conv-backbone accuracy where per-tensor scales do not): each
  encoder conv kernel is stored int8 with one fp32 scale per OUTPUT
  channel, ``q = clip(round(w / s), -127, 127)``, ``s = absmax_c / 127``.
* **Dequant in-register**: quantization happens on the HOST once per
  process (``quantize_variables``); the jitted program receives the int8
  tree and dequantizes at trace time (``dequantize_variables`` inside
  ``eval/runner.make_forward``), so the checkpoint on disk stays fp32,
  the host->device upload and the executable's parameter residency carry
  int8, and XLA upcasts next to the consuming conv.
* **Scope**: the feature/context encoders only — ``fnet`` / ``cnet`` /
  the shared-backbone projection (``conv2_res``/``conv2_out``) and the
  per-level ``context_zqr_conv*`` biases.  They run ONCE per frame and
  are pure conv stacks (the setting the PTQ literature validates); the
  GRU update block runs ``iters`` times over its own state and stays in
  the compute dtype — quantization error there would compound per
  iteration, which is exactly the failure mode the BF16_DRIFT series
  measured for low-precision correlation at depth.

``config.quant == "off"`` never calls anything here; the compiled
program is bitwise-identical to the pre-quant build (pinned by
tests/test_quant.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

QUANT_MODES = ("off", "int8", "int8_mxu")

# A quantized leaf is the fp32 kernel array replaced by a dict
# {"q8": int8[HWIO], "qscale": f32[1,1,1,O]} — a plain all-array pytree
# (jax.device_put / tree_map / jit all handle it; a string marker would
# not trace).  The key set IS the marker: no flax module in this model
# names parameters "q8"/"qscale".  The "int8_mxu" compute path adds an
# optional third member, {"ascale": f32[]} — the calibrated static
# activation scale for the conv's INPUT (quant/calibrate.py
# conv_input_scales); packs without it fall back to a dynamic in-graph
# max-abs scale (quant/matmul.py).

# Top-level param modules whose conv kernels quantize (the encoder
# surface; see module docstring for why the update block is excluded).
# ``context_zqr_conv*`` is matched by prefix — one conv per GRU level.
_ENCODER_MODULES = ("fnet", "cnet", "conv2_res", "conv2_out")
_ENCODER_PREFIXES = ("context_zqr_conv",)


_PACK_KEYS = frozenset(("q8", "qscale"))
_PACK_KEYS_ASCALE = frozenset(("q8", "qscale", "ascale"))


def is_quantized_leaf(x: Any) -> bool:
    """True for the {q8, qscale[, ascale]} pack ``quantize_variables``
    produces."""
    if not isinstance(x, dict):
        return False
    keys = frozenset(x.keys())
    return keys == _PACK_KEYS or keys == _PACK_KEYS_ASCALE


def _quantizable_module(name: str) -> bool:
    return name in _ENCODER_MODULES or any(
        name.startswith(p) for p in _ENCODER_PREFIXES)


def quantize_array(w: np.ndarray, axis: int = -1
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel symmetric int8 quantization of one conv kernel:
    ``(q int8, scale f32)`` with ``scale`` broadcastable against ``w``
    (kept dims).  ``axis`` is the channel axis the scales live on —
    the OUTPUT channel (-1 in HWIO).  All-zero channels get a scale of 1
    so dequant reproduces the zeros exactly instead of dividing by 0."""
    w = np.asarray(w, dtype=np.float32)
    absmax = np.max(np.abs(w), axis=tuple(
        a for a in range(w.ndim) if a != axis % w.ndim), keepdims=True)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_array(q, scale):
    """``q * scale`` in fp32 — works on NumPy and (inside jit) on traced
    arrays; the in-jit use is the in-register dequant."""
    import jax.numpy as jnp

    if isinstance(q, np.ndarray):
        return q.astype(np.float32) * scale
    return q.astype(jnp.float32) * scale


def quantize_variables(variables: Dict, config=None,
                       act_scales: Optional[Dict[str, float]] = None
                       ) -> Dict:
    """The int8 inference tree: every encoder conv kernel in
    ``variables["params"]`` replaced by its {q, scale} pack; everything
    else (biases, norms, the update block, batch_stats) passes through
    untouched.  Host-side NumPy — runs once per process; the result is
    what ``eval/runner.make_forward`` programs with ``quant != "off"``
    take as their ``variables`` argument.  ``config`` is accepted for
    signature symmetry/forward evolution and currently unused (the
    quantized surface is architectural, not knob-dependent).

    ``act_scales`` maps "/"-joined module paths (e.g.
    ``"fnet/trunk/conv1"`` — the keys ``quant/calibrate.py
    conv_input_scales`` returns) to calibrated int8 scales for the
    conv's input; matching packs gain an ``ascale`` member so the
    int8_mxu compute path quantizes activations with static constants
    instead of in-graph max-abs reductions."""
    del config
    act_scales = act_scales or {}

    def walk(tree, under_encoder: bool, prefix: str):
        if not isinstance(tree, dict) or is_quantized_leaf(tree):
            return tree
        out = {}
        for name, sub in tree.items():
            in_scope = under_encoder or _quantizable_module(name)
            if (in_scope and name == "kernel"
                    and getattr(sub, "ndim", 0) == 4):
                q, scale = quantize_array(np.asarray(sub))
                pack = {"q8": q, "qscale": scale}
                ascale = act_scales.get(prefix)
                if ascale is not None:
                    pack["ascale"] = np.float32(ascale)
                out[name] = pack
            else:
                out[name] = walk(
                    sub, in_scope,
                    f"{prefix}/{name}" if prefix else name)
        return out

    out = dict(variables)
    if "params" in out:
        out["params"] = walk(dict(out["params"]), False, "")
    return out


def dequantize_variables(variables: Dict) -> Dict:
    """Invert ``quantize_variables`` structurally: every {q, scale} pack
    becomes the fp32 kernel again.  Called INSIDE the jitted forward —
    the int8 arrays are the program inputs, the multiply is fused next
    to the consuming conv, and the fp32 materialization is an XLA
    temporary rather than resident parameter state."""
    def walk(tree):
        if is_quantized_leaf(tree):
            return dequantize_array(tree["q8"], tree["qscale"])
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(dict(variables))


def tree_is_quantized(variables: Dict) -> bool:
    """True when ``variables`` contains at least one quantized pack."""
    found = [False]

    def walk(tree):
        if found[0]:
            return
        if is_quantized_leaf(tree):
            found[0] = True
            return
        if isinstance(tree, dict):
            for v in tree.values():
                walk(v)

    walk(variables)
    return found[0]


def quantized_param_bytes(variables: Dict) -> Dict[str, int]:
    """Byte accounting of one quantized tree: ``{"int8": n, "fp32": n,
    "scales": n}`` — what the drift/bench tools report as the moved-bytes
    win next to the measured FPS."""
    acc = {"int8": 0, "fp32": 0, "scales": 0}

    def walk(tree):
        if is_quantized_leaf(tree):
            acc["int8"] += int(np.asarray(tree["q8"]).nbytes)
            acc["scales"] += int(np.asarray(tree["qscale"]).nbytes)
            if "ascale" in tree:
                acc["scales"] += int(np.asarray(tree["ascale"]).nbytes)
            return
        if isinstance(tree, dict):
            for v in tree.values():
                walk(v)
            return
        arr = np.asarray(tree)
        if arr.dtype.kind == "f":
            acc["fp32"] += int(arr.nbytes)

    walk(variables)
    return acc


# --------------------------------------------------------- corr pyramid
def quantize_symmetric(x, scale):
    """Traced int8 quantization of one activation tensor given its
    (static or traced) scale — the correlation-pyramid path
    (models/corr.py).  Callers wrap the surrounding computation in
    ``stop_gradient``: the int8 tier is inference-only."""
    import jax.numpy as jnp

    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)


def dynamic_scale(x, eps: float = 1e-12, qmax: float = 127.0):
    """In-graph per-tensor symmetric scale: ``max|x| / qmax`` — the
    fallback when no calibrated scale file is configured.  One reduction
    per tensor per forward; deterministic for a given input.  ``qmax``
    is the grid's largest representable magnitude: 127 for int8 (the
    default), ``FP8_QMAX`` for the float8_e4m3 correlation entries."""
    import jax.numpy as jnp

    return jnp.maximum(jnp.max(jnp.abs(x)), eps) / qmax


# float8_e4m3's largest finite magnitude (1.75 · 2^8): the fp8 analogue
# of int8's 127 for symmetric scale construction.
FP8_QMAX = 448.0


def quantize_fp8(x, scale, dtype):
    """Traced fp8 quantization of one activation tensor: clip to the
    finite e4m3 range first (the cast saturates NaN/inf semantics vary
    by backend — an explicit clip keeps the grid deterministic), then
    cast.  Dequant is ``q.astype(f32) * scale``, same as int8."""
    import jax.numpy as jnp

    return jnp.clip(x / scale, -FP8_QMAX, FP8_QMAX).astype(dtype)


def clipped_scale(absmax_percentile: float) -> float:
    """A calibrated percentile-clipped range to its int8 scale."""
    return max(float(absmax_percentile), 1e-12) / 127.0
