"""Quantized compute core: int8×int8→int32 convolution on the MXU.

The r15 int8 tier moved BYTES — weights ship int8 but
``dequantize_variables`` upcasts at trace time, so every matmul still
runs fp32 and CPU measured parity-within-noise (BENCH_SERVE_r15.json:
turbo 0.95x balanced).  This module converts the bytes win into a flops
win (AQT-style, ROADMAP open item 1): the conv itself multiplies
int8×int8 and accumulates int32 (``preferred_element_type=jnp.int32``
— the MXU's native low-precision mode on TPU; XLA:CPU lowers the same
program to int8 GEMMs), and the per-output-channel rescale to fp32
happens ONCE, *after* accumulation:

    y = conv_i8(q(x), q8) · (ascale · qscale) + bias

* **Rescale-after-accumulate contract**: the int32 accumulator is
  exact (no rounding between taps), so the only error sources are the
  two quantizations — the same error budget the r15 weights-only mode
  measured, plus the activation quantization the drift gate re-measures
  (tools/quant_drift.py int8_mxu rows).  Accumulator headroom: the
  widest conv here reduces K = 3·3·128 = 1152 int8 products,
  1152 · 127² ≈ 1.86e7 « 2³¹ — overflow-free by 2 orders of magnitude.
* **Activation scales**: static per-conv scales calibrated by
  ``quant/calibrate.py`` (percentile-clipped, carried in the variables
  pack as ``ascale``); packs without one fall back to a dynamic
  per-tensor max-abs scale computed in-graph (one extra reduction —
  the ``context_zqr`` convs take this path, they are outside the
  calibration passes' capture surface).
* **Routing is data-driven**: ``QuantConv`` subclasses ``nn.Conv`` and
  switches on what the variables tree carries.  A plain fp kernel (the
  ``quant="off"`` and weights-only ``"int8"`` paths — the latter
  dequantizes the tree before apply) delegates to ``nn.Conv.__call__``
  unchanged, keeping the jaxpr-level zero-int8-ops pin for ``"off"``
  bitwise intact; a {q8, qscale[, ascale]} pack (the ``"int8_mxu"``
  path — eval/runner passes packs THROUGH to the traced program) takes
  the quantized-compute branch.  Inference-only, like every quant mode.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_stereo_tpu.quant.core import (dynamic_scale, is_quantized_leaf,
                                        quantize_symmetric)

_NHWC_HWIO = ("NHWC", "HWIO", "NHWC")


def _as_tuple(v, rank: int) -> Tuple[int, ...]:
    if isinstance(v, int):
        return (v,) * rank
    return tuple(v)


def int8_conv_int32(x_q, w_q, *, strides: Sequence[int],
                    padding: Union[str, Sequence[Tuple[int, int]]],
                    dimension_numbers=_NHWC_HWIO):
    """The quantized conv primitive: int8 activations × int8 weights →
    int32 accumulator in ONE op (``preferred_element_type``) — no fp32
    materialization of either operand feeds the conv (the jaxpr pin
    tests/test_quant.py asserts).  Explicit zero padding commutes with
    symmetric quantization (0 → 0), so padding the int8 tensor is exact."""
    return jax.lax.conv_general_dilated(
        x_q, w_q, window_strides=tuple(strides), padding=padding,
        dimension_numbers=dimension_numbers,
        preferred_element_type=jnp.int32)


def int8_dot_int32(x_q, w_q, dimension_numbers=None):
    """int8×int8→int32 ``dot_general`` — the matmul twin of
    ``int8_conv_int32`` (1×1 convs lowered as GEMMs, and the building
    block a future quantized GRU extension would use).  Defaults to a
    plain last-dim × first-dim contraction."""
    if dimension_numbers is None:
        dimension_numbers = (((x_q.ndim - 1,), (0,)), ((), ()))
    return jax.lax.dot_general(x_q, w_q, dimension_numbers,
                               preferred_element_type=jnp.int32)


def quantize_activation(x, ascale=None):
    """One activation tensor to (int8, fp32 scale): the calibrated
    static ``ascale`` when the pack carries one, else the dynamic
    per-tensor max-abs fallback (quant/core.dynamic_scale)."""
    if ascale is None:
        ascale = dynamic_scale(x)
    ascale = jnp.asarray(ascale, jnp.float32)
    return quantize_symmetric(x.astype(jnp.float32), ascale), ascale


def quantized_conv_apply(x, pack, bias, *, strides, padding, out_dtype):
    """The full quantized conv: quantize input → int8 conv (int32
    accumulate) → per-output-channel rescale to fp32 AFTER accumulation
    → bias add → cast to the module compute dtype."""
    x_q, ascale = quantize_activation(x, pack.get("ascale"))
    acc = int8_conv_int32(x_q, pack["q8"], strides=strides,
                          padding=padding)
    # qscale is f32[1,1,1,O] (kept dims from quantize_array); the
    # combined factor stays a rank-4 broadcast against NHWC output.
    y = acc.astype(jnp.float32) * (ascale
                                   * jnp.asarray(pack["qscale"],
                                                 jnp.float32))
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(out_dtype)


def int8_matmul_report(closed) -> dict:
    """Walk a jaxpr (recursively through sub-jaxprs: pjit/scan/while
    bodies, custom_jvp calls) and classify its matmuls — the shared
    inspection behind the int8_mxu jaxpr pin (tests/test_quant.py,
    scripts/quant_smoke.py):

    * ``int8_convs`` / ``int8_dots``: int8 × int8 → int32 (the MXU path
      — must be ≥ 1 under ``quant="int8_mxu"``);
    * ``other_matmuls``: everything else (fp convs/dots — the GRU and
      non-extractor surface, legitimately fp under every mode);
    * ``dequant_fed_matmuls``: convs/dots consuming an fp32 tensor
      produced DIRECTLY by an int8 → fp32 convert — the
      dequantize-then-fp32 anti-pattern the rescale-after-accumulate
      contract forbids (must be 0)."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    stats = {"int8_convs": 0, "int8_dots": 0, "other_matmuls": 0,
             "dequant_fed_matmuls": 0}

    def subjaxprs(p):
        if hasattr(p, "eqns"):                      # core.Jaxpr
            yield p
        elif hasattr(p, "jaxpr"):                   # core.ClosedJaxpr
            yield p.jaxpr
        elif isinstance(p, (list, tuple)):
            for item in p:
                yield from subjaxprs(item)

    def walk(jxp):
        dequant_outs = set()
        for eqn in jxp.eqns:
            prim = eqn.primitive.name
            if prim == "convert_element_type":
                src, dst = eqn.invars[0], eqn.outvars[0]
                if (getattr(src, "aval", None) is not None
                        and src.aval.dtype == jnp.int8
                        and dst.aval.dtype == jnp.float32):
                    dequant_outs.add(dst)
            elif prim in ("conv_general_dilated", "dot_general"):
                in_dt = [v.aval.dtype for v in eqn.invars[:2]]
                out_dt = eqn.outvars[0].aval.dtype
                if (all(d == jnp.int8 for d in in_dt)
                        and out_dt == jnp.int32):
                    key = ("int8_convs" if prim == "conv_general_dilated"
                           else "int8_dots")
                    stats[key] += 1
                else:
                    stats["other_matmuls"] += 1
                if any(v in dequant_outs for v in eqn.invars
                       if not isinstance(v, jax.core.Literal)):
                    stats["dequant_fed_matmuls"] += 1
            for sub in eqn.params.values():
                for j in subjaxprs(sub):
                    walk(j)

    walk(jaxpr)
    return stats


class QuantConv(nn.Conv):
    """``nn.Conv`` that runs the int8 MXU path when its kernel arrives
    as a {q8, qscale[, ascale]} pack.

    * init / fp apply: identical to ``nn.Conv`` (same param tree, same
      program — the ``quant="off"`` bitwise pin rides on this).
    * calibration: sows its INPUT under ``intermediates/<path>/qin`` so
      the existing ``quant/calibrate.py`` capture passes collect conv
      input ranges with zero calibration-side model knowledge (conv
      inputs are mostly relu/norm outputs, which the automatic
      ``__call__``-output capture never sees).
    * pack apply: ``quantized_conv_apply`` — the variables tree decides
      the path, not a module attribute, so ONE module class serves
      every quant mode and executables differ only by their inputs."""

    @nn.compact
    def __call__(self, x):
        if not self.is_initializing():
            # No-op unless "intermediates" is mutable (the calibration
            # apply); skipped at init so variable trees stay pristine.
            self.sow("intermediates", "qin", x)
        kernel = self.get_variable("params", "kernel")
        if not is_quantized_leaf(kernel):
            return super().__call__(x)
        if self.feature_group_count != 1:
            raise NotImplementedError(
                "QuantConv int8 path supports feature_group_count=1 "
                "only (the encoder surface)")
        bias = (self.get_variable("params", "bias")
                if self.use_bias else None)
        rank = len(self.kernel_size)
        return quantized_conv_apply(
            x, kernel, bias,
            strides=_as_tuple(self.strides or 1, rank),
            padding=self.padding,
            out_dtype=self.dtype or x.dtype)
