"""Post-training calibration: per-layer activation ranges for the int8
inference tier, collected by running the model on in-distribution pairs.

The BF16_DRIFT_r03-r05 series established this repo's rule for precision
claims: measure the drift in-distribution on the trained checkpoint, not
on paper.  Calibration is the collection half of that rule for int8 —
run the REAL forward (same padding semantics as ``eval/runner``, same
compute dtypes) over a handful of representative pairs and record, per
site, the percentile-clipped |activation| range that becomes the int8
scale:

* **Correlation pyramid levels** (``corr_levels`` entries) — the scales
  the int8 pyramid path uses (models/corr.py); computed from the exact
  fp32 volume math the ``reg``/``reg_fused`` backends run.
* **Feature maps** (``fmap1`` + the W-pooled ``fmap2`` pyramid) — the
  scales the no-volume ``alt`` kernel path uses.
* **Encoder layer outputs** — every fnet/cnet intermediate's range
  (Flax ``capture_intermediates``), recorded for the drift report and
  any future activation-quantized matmul path.

Percentile clipping (default 99.9) follows the PTQ literature (Wu et
al. 2020 §5): a handful of outlier correlation peaks would otherwise
blow the scale up and crush the resolution of the 99.9% of values that
carry the signal.

The result is a CHECKPOINT-ADJACENT JSON file (``save_scales`` /
``load_scales``): parameters on disk stay fp32, and the scale file rides
next to the checkpoint the way the config JSON already does.  Same
pairs in => byte-identical scale file out (pinned by
tests/test_quant.py — the calibration determinism contract).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

SCALES_VERSION = 1
DEFAULT_PERCENTILE = 99.9


def _percentile_absmax(values: List[np.ndarray], percentile: float) -> float:
    flat = np.concatenate([np.abs(np.asarray(v, np.float32)).ravel()
                           for v in values])
    return float(np.percentile(flat, percentile))


def calibrate(config, variables, pairs: Iterable[Tuple[np.ndarray,
                                                       np.ndarray]],
              percentile: float = DEFAULT_PERCENTILE,
              divis_by: int = 32) -> Dict:
    """Collect activation ranges over ``pairs`` of (left, right) HxWx3
    images and return the scale record (see module docstring).

    Runs the UNQUANTIZED forward — calibration measures the fp32/bf16
    distribution the int8 grid must cover, so ``config.quant`` is forced
    off for the pass; the pyramid is rebuilt here with the same
    ``build_corr_volume``/``build_corr_pyramid`` math the backends use.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.models.corr import (build_corr_pyramid,
                                             build_corr_volume, pool_axis)
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.ops.padding import InputPadder

    cfg = dataclasses.replace(config, quant="off")
    model = RAFTStereo(cfg)
    dtype = jnp.bfloat16 if cfg.mixed_precision else jnp.float32

    level_vals: List[List[np.ndarray]] = [[] for _ in range(cfg.corr_levels)]
    f1_vals: List[np.ndarray] = []
    f2_level_vals: List[List[np.ndarray]] = [[] for _ in
                                             range(cfg.corr_levels)]
    act_vals: Dict[str, List[np.ndarray]] = {}

    def fmaps(img1, img2):
        """The feature maps the correlation backend consumes, via the
        model's own encoder params (mirrors models/raft_stereo.py)."""
        x1 = (2 * (img1 / 255.0) - 1.0).astype(dtype)
        x2 = (2 * (img2 / 255.0) - 1.0).astype(dtype)
        if cfg.shared_backbone:
            both = jnp.concatenate([x1, x2], axis=0)

            def shared_fmap(m, b):
                _levels, v = m.cnet(b)
                return m.conv2_out(m.conv2_res(v))

            fmap, inter = model.apply(variables, both, method=shared_fmap,
                                      capture_intermediates=True)
            f1, f2 = jnp.split(fmap, 2, axis=0)
        else:
            both = jnp.concatenate([x1, x2], axis=0)
            fmap, inter = model.apply(
                variables, both, method=lambda m, b: m.fnet(b),
                capture_intermediates=True)
            f1, f2 = jnp.split(fmap, 2, axis=0)
            # cnet ranges ride the same record (context encoder layers).
            _, inter_c = model.apply(
                variables, x1, method=lambda m, i: m.cnet(i),
                capture_intermediates=True)
            _merge_intermediates(act_vals, inter_c.get("intermediates", {}),
                                 prefix="cnet")
        _merge_intermediates(act_vals, inter.get("intermediates", {}),
                             prefix="fnet" if not cfg.shared_backbone
                             else "cnet")
        return f1, f2

    n_pairs = 0
    for left, right in pairs:
        left = np.asarray(left)
        right = np.asarray(right)
        padder = InputPadder((1,) + left.shape, divis_by=divis_by)
        pl_, pr_, pt, pb = padder.pads
        spec = ((pt, pb), (pl_, pr_), (0, 0))
        p1 = jnp.asarray(np.pad(left, spec, mode="edge")[None],
                         jnp.float32)
        p2 = jnp.asarray(np.pad(right, spec, mode="edge")[None],
                         jnp.float32)
        f1, f2 = fmaps(p1, p2)
        f1_vals.append(np.asarray(f1, np.float32))
        # The reg volume math, exactly as make_corr_fn_reg* builds it.
        pyramid = build_corr_pyramid(
            build_corr_volume(f1.astype(jnp.float32),
                              f2.astype(jnp.float32)), cfg.corr_levels)
        f2_lvl = f2
        for i, vol in enumerate(pyramid):
            level_vals[i].append(np.asarray(vol, np.float32))
            f2_level_vals[i].append(np.asarray(f2_lvl, np.float32))
            if i + 1 < cfg.corr_levels:
                f2_lvl = pool_axis(f2_lvl, axis=2)
        n_pairs += 1
    if n_pairs == 0:
        raise ValueError("calibration needs at least one (left, right) "
                         "pair")

    record = {
        "version": SCALES_VERSION,
        "mode": "int8",
        "percentile": percentile,
        "n_pairs": n_pairs,
        "config": json.loads(cfg.to_json()),
        "corr_levels": [
            round(_percentile_absmax(vals, percentile), 8)
            for vals in level_vals],
        "features": {
            "fmap1": round(_percentile_absmax(f1_vals, percentile), 8),
            "fmap2_levels": [
                round(_percentile_absmax(vals, percentile), 8)
                for vals in f2_level_vals]},
        "activations": {
            site: {"absmax_clipped":
                   round(_percentile_absmax(vals, percentile), 8)}
            for site, vals in sorted(act_vals.items())},
    }
    del jax  # imported for the side effects of backend init ordering
    return record


def _merge_intermediates(acc: Dict[str, List[np.ndarray]], tree,
                         prefix: str) -> None:
    """Flatten a Flax ``capture_intermediates`` tree into
    ``acc["prefix/module/path"]`` value lists."""
    if isinstance(tree, (tuple, list)):
        for v in tree:
            _merge_intermediates(acc, v, prefix)
        return
    if isinstance(tree, dict):
        for name, sub in tree.items():
            key = prefix if name == "__call__" else f"{prefix}/{name}"
            _merge_intermediates(acc, sub, key)
        return
    acc.setdefault(prefix, []).append(np.asarray(tree, np.float32))


def conv_input_scales(record: Dict) -> Dict[str, float]:
    """The per-conv activation scales of one calibration record, keyed
    by "/"-joined PARAM-tree module paths (``"fnet/trunk/conv1"``) — the
    ``act_scales`` argument of ``quant/core.quantize_variables`` for the
    int8_mxu compute path.

    Sites come from ``QuantConv``'s ``qin`` sow (the conv's INPUT —
    mostly relu/norm outputs the automatic ``__call__`` capture never
    sees).  Record keys carry the calibration pass's merge prefix as
    their first component (``"fnet/fnet/trunk/conv1/qin"``); strip it
    and the ``/qin`` suffix to recover the module path.  A path seen by
    more than one pass keeps the widest range (conservative).  Records
    from builds predating the qin sow simply yield {} — callers fall
    back to dynamic in-graph scales."""
    from raft_stereo_tpu.quant.core import clipped_scale

    out: Dict[str, float] = {}
    absmax: Dict[str, float] = {}
    for site, entry in record.get("activations", {}).items():
        parts = site.split("/")
        if parts[-1] != "qin" or len(parts) < 3:
            continue
        path = "/".join(parts[1:-1])
        v = float(entry["absmax_clipped"])
        absmax[path] = max(absmax.get(path, 0.0), v)
    for path, v in absmax.items():
        out[path] = clipped_scale(v)
    return out


def corr_scales(record: Dict) -> Tuple[float, ...]:
    """The per-level int8 volume scales of one calibration record — what
    ``RaftStereoConfig.quant_corr_scales`` carries into the compiled
    program (models/corr.py)."""
    from raft_stereo_tpu.quant.core import clipped_scale

    return tuple(clipped_scale(v) for v in record["corr_levels"])


def save_scales(path: str, record: Dict) -> str:
    """Write the checkpoint-adjacent scale file (atomic; stable key
    order so identical calibrations are byte-identical files)."""
    blob = json.dumps(record, indent=1, sort_keys=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(blob + "\n")
    os.replace(tmp, path)
    return path


def load_scales(path: str) -> Dict:
    with open(path) as f:
        record = json.load(f)
    if record.get("version") != SCALES_VERSION:
        raise ValueError(
            f"scale file {path}: version {record.get('version')!r} != "
            f"{SCALES_VERSION} (recalibrate with this build)")
    if record.get("mode") != "int8":
        raise ValueError(f"scale file {path}: mode "
                         f"{record.get('mode')!r} is not 'int8'")
    return record
