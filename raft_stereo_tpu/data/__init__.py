from raft_stereo_tpu.data.datasets import (DATASETS, StereoDataset,
                                           build_training_mixture)
from raft_stereo_tpu.data.loader import StereoLoader
