"""Host-side batch loader: shuffled, threaded, prefetching.

TPU-native replacement for the reference's ``torch.utils.data.DataLoader``
(reference: core/stereo_datasets.py:311-312): decode + augment run on host
CPU threads while the device steps; batches are stacked NHWC NumPy dicts
ready for ``shard_batch``.  Threads (not processes) because the decode path
is NumPy/cv2 releasing the GIL; the native C++ decode path slots in below.

Determinism: the epoch-``e`` permutation comes from ``seed + e`` and each
sample's augmentation RNG from ``(seed, epoch, index)`` (see datasets.py), so
a (seed, step) pair maps to one exact batch regardless of thread scheduling.

Round 20 (divergence-proof training) adds two production contracts:

* **Fault isolation** — a sample whose decode RAISES is retried once
  (transient I/O) and then QUARANTINED: a deterministic substitute sample
  fills its batch slot, the index joins a persisted quarantine list
  (``quarantine_path``), and typed counters (``stats``) expose every
  decision.  A dead process worker (OOM-killed, segfaulted decoder) is
  respawned and its in-flight batches resubmitted — one corrupt shard or
  one killed worker no longer ends a week-long run.
* **Exact-resume state** — ``state()``/``set_state()`` round-trip the
  loader position as a flat batch OFFSET (``epoch * len(self) + batch``)
  plus the rewind reshuffle SALTS: a salt event ``(epoch, batch, salt)``
  re-permutes the REMAINDER of that epoch's order (consumed prefix
  untouched, no sample repeats), which is how a checkpoint rewind avoids
  deterministically replaying the poison batch.  Both live in the
  checkpoint runtime blob (training/checkpoint.py), making a preempted
  run's data order bitwise identical to an uninterrupted one.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import queue
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from raft_stereo_tpu.data.datasets import StereoDataset

log = logging.getLogger(__name__)

# One retry before quarantine: transient NFS hiccups succeed on the second
# read; a truly corrupt sample fails twice and is pulled from rotation.
SAMPLE_RETRIES = 1

# A worker pool that breaks this many times consecutively is not going to
# heal by respawning (e.g. the dataset itself segfaults every decode).
MAX_POOL_RESPAWNS = 3


class LoaderBroken(RuntimeError):
    """Typed terminal loader failure: the worker pool kept dying after
    ``MAX_POOL_RESPAWNS`` consecutive respawns — respawning is not going
    to converge, a human needs to look at the dataset/host."""


def sample_content_key(dataset, index: int) -> Optional[str]:
    """Stable identity of a sample: SHA-256 over its file paths + sizes.

    Quarantine entries persist under THIS key, not the raw index — a
    re-listed dataset (files added/removed, indices shifted) keeps its
    quarantine aimed at the same bad files, and a REPLACED file (a
    re-downloaded fixed shard: different size) stops matching and leaves
    quarantine automatically.  None when the dataset exposes no
    ``sample_paths`` (synthetic/test datasets) — those entries fall back
    to index identity.
    """
    paths_fn = getattr(dataset, "sample_paths", None)
    if paths_fn is None:
        return None
    try:
        paths = paths_fn(int(index))
    except Exception:
        return None
    h = hashlib.sha256()
    for p in paths:
        try:
            size = os.path.getsize(p)
        except OSError:
            size = -1      # missing file is still a stable identity
        h.update(f"{p}\x00{size}\x00".encode())
    return h.hexdigest()


def _collate(dataset: StereoDataset, epoch: int, indices
             ) -> Dict[str, np.ndarray]:
    """THE batch-assembly contract — every worker flavor (sync, thread,
    process) builds batches through this one function."""
    samples = [dataset.__getitem__(int(i), epoch) for i in indices]
    return {k: np.stack([s[k] for s in samples]) for k in samples[0]}


def _substitute_index(i: int, n: int, quarantined) -> int:
    """Deterministic replacement for a quarantined sample: the next
    non-quarantined index (wrapping).  Pure function of (i, n, quarantine
    set), so every worker flavor picks the same substitute."""
    for k in range(1, n):
        j = (i + k) % n
        if j not in quarantined:
            return j
    raise LoaderBroken(f"all {n} dataset samples quarantined")


def _collate_isolated(dataset: StereoDataset, epoch: int, indices,
                      quarantined=frozenset(),
                      retries: int = SAMPLE_RETRIES
                      ) -> Tuple[Dict[str, np.ndarray], List[Dict]]:
    """``_collate`` with per-sample fault isolation.

    Returns ``(batch, events)``: each raising sample is retried
    ``retries`` times, then replaced by its deterministic substitute and
    reported as a ``quarantined`` event (a retry that SUCCEEDS reports
    ``retried``).  Already-quarantined indices substitute immediately.
    Events flow back to the owning loader (any worker flavor), which
    merges them into the shared quarantine set + typed counters.
    """
    events: List[Dict] = []
    samples = []
    n = len(dataset)
    for i in indices:
        i = int(i)
        use = i
        if use in quarantined:
            use = _substitute_index(use, n, quarantined)
        sample = None
        local_quarantine = set(quarantined)
        while sample is None:
            try:
                sample = dataset.__getitem__(use, epoch)
            except Exception as e:
                retried = False
                for _ in range(retries):
                    try:
                        sample = dataset.__getitem__(use, epoch)
                        retried = True
                        break
                    except Exception:
                        continue
                if retried:
                    events.append({"kind": "retried", "index": use,
                                   "error": repr(e)})
                    break
                events.append({"kind": "quarantined", "index": use,
                               "error": repr(e)})
                local_quarantine.add(use)
                use = _substitute_index(use, n, local_quarantine)
        samples.append(sample)
    return ({k: np.stack([s[k] for s in samples]) for k in samples[0]},
            events)


# --------------------------------------------------- process-worker plumbing
# Module-level so child processes (spawn) can import it; the dataset is
# shipped once via the pool initializer, not per task.
_WORKER_DATASET: Optional[StereoDataset] = None
_WORKER_QUARANTINE: set = set()


def _process_worker_init(ds_bytes: bytes, quarantined=()) -> None:
    global _WORKER_DATASET, _WORKER_QUARANTINE
    _WORKER_DATASET = pickle.loads(ds_bytes)
    _WORKER_QUARANTINE = set(quarantined)


def _process_make_batch(args):
    epoch, indices = args
    batch, events = _collate_isolated(_WORKER_DATASET, epoch, indices,
                                      quarantined=_WORKER_QUARANTINE)
    # Keep the worker-local view current so later batches in THIS worker
    # substitute immediately; the parent merges events into the shared
    # set and ships it to fresh workers at (re)spawn.
    for ev in events:
        if ev["kind"] == "quarantined":
            _WORKER_QUARANTINE.add(ev["index"])
    return batch, events


class StereoLoader:
    """Iterate device-ready batches forever (training) or one epoch (eval).

    Args:
      dataset: a ``StereoDataset`` (samples must share one crop size).
      batch_size: GLOBAL batch size; ``drop_last`` semantics always on.
      shuffle: re-permute every epoch with ``seed + epoch``.
      num_workers: decode threads; 0 = synchronous in-caller decode.
      prefetch: max ready batches buffered ahead.
      epochs: None = loop forever.
      process_index/process_count: multi-host data sharding — every process
        draws the same seeded permutation but decodes only its contiguous
        slice of each global batch (``parallel.distributed`` supplies these;
        ``mesh.shard_batch`` reassembles the global array).  Yielded batches
        then have ``batch_size // process_count`` samples.
      quarantine_path: JSON file persisting quarantined sample indices
        across restarts (None = in-memory only); loaded at construction,
        rewritten on every new quarantine.
      fault_isolation: retry-once-then-quarantine raising samples and
        respawn dead process workers (default on).  Off = a raising
        sample propagates to the consumer (the pre-round-20 behavior).
    """

    def __init__(self, dataset: StereoDataset, batch_size: int,
                 shuffle: bool = True, num_workers: int = 4,
                 prefetch: int = 2, seed: int = 1234,
                 epochs: Optional[int] = None,
                 process_index: int = 0, process_count: int = 1,
                 worker_type: str = "thread",
                 quarantine_path: Optional[str] = None,
                 fault_isolation: bool = True):
        if len(dataset) < batch_size:
            raise ValueError(
                f"dataset has {len(dataset)} samples < batch_size={batch_size}")
        if batch_size % process_count:
            raise ValueError(f"batch_size={batch_size} not divisible by "
                             f"process_count={process_count}")
        if not (0 <= process_index < process_count):
            raise ValueError(f"process_index={process_index} out of range "
                             f"for process_count={process_count}")
        if worker_type not in ("thread", "process"):
            raise ValueError(f"worker_type={worker_type!r} not in "
                             f"('thread', 'process')")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = num_workers
        self.prefetch = prefetch
        self.seed = seed
        self.epochs = epochs
        self.process_index = process_index
        self.process_count = process_count
        # "process": decode+augment in spawned worker PROCESSES — sidesteps
        # the GIL entirely where thread workers only overlap the
        # GIL-releasing segments (native decode, cv2).  Costs one extra
        # batch copy (pickle over the pipe) per batch, so it pays off on
        # multi-core hosts where augment's pure-NumPy Python dominates.
        # Determinism is identical: a batch is a pure function of
        # (seed, epoch, indices) regardless of which worker builds it.
        # NOTE: like any spawn-based pool (torch DataLoader included), the
        # launching script must be import-safe — iteration from a script
        # without an ``if __name__ == "__main__"`` guard re-executes that
        # script in every worker.
        self.worker_type = worker_type
        self.fault_isolation = fault_isolation
        self.quarantine_path = quarantine_path
        # Shared fault state: guarded by _fault_lock (thread workers write
        # concurrently); counters are the typed telemetry surface the
        # train loop mirrors into train_loader_* instruments.
        self._fault_lock = threading.Lock()
        self.quarantined: set = set()
        # index -> content key (sample_content_key; None for datasets
        # without file identity).  The persisted file stores the KEYS —
        # the index is just a verification hint for the fast reload path.
        self._quarantine_keys: Dict[int, Optional[str]] = {}
        self.stats: Dict[str, int] = {"retried": 0, "quarantined": 0,
                                      "worker_respawns": 0}
        if quarantine_path and os.path.exists(quarantine_path):
            try:
                with open(quarantine_path) as f:
                    payload = json.load(f)
                self._load_quarantine(payload)
                log.info("loaded %d quarantined samples from %s",
                         len(self.quarantined), quarantine_path)
            except (OSError, ValueError, TypeError, KeyError):
                log.warning("unreadable quarantine file %s; starting empty",
                            quarantine_path)
        # Exact-resume position: the NEXT batch yielded by a fresh
        # iterator is global batch offset ``start_offset`` (epoch =
        # offset // len(self), batch = offset % len(self)); ``salts``
        # are the rewind reshuffle events (epoch, batch, salt).
        self.start_offset = 0
        self.salts: Tuple[Tuple[int, int, int], ...] = ()

    # --------------------------------------------------- quarantine persist
    def _load_quarantine(self, payload: Dict) -> None:
        """Rebuild the quarantine set from a persisted payload.

        v2 format (``{"version": 2, "samples": [{"key", "index"}, ...]}``)
        stores content keys with the index as a verification hint: a key
        that still matches its recorded index adopts it directly; a
        mismatch (re-listed dataset) triggers ONE full relocation scan; a
        key found nowhere is dropped — the bad file was replaced or
        removed, so the sample re-earns its quarantine or rejoins
        rotation.  The legacy v1 format (``{"indices": [...]}``) is
        migrated in place: indices adopt as-is, their keys are computed
        now, and the next persist rewrites the file as v2.
        """
        n = len(self.dataset)
        if payload.get("version") == 2:
            relocate: List[str] = []
            for ent in payload.get("samples", ()):
                key, idx = ent.get("key"), ent.get("index")
                if key is None:
                    # No file identity when persisted — index is all we have.
                    if isinstance(idx, int) and 0 <= idx < n:
                        self.quarantined.add(idx)
                        self._quarantine_keys[idx] = None
                    continue
                if (isinstance(idx, int) and 0 <= idx < n
                        and sample_content_key(self.dataset, idx) == key):
                    self.quarantined.add(idx)
                    self._quarantine_keys[idx] = key
                else:
                    relocate.append(key)
            if relocate:
                wanted = set(relocate)
                for i in range(n):
                    k = sample_content_key(self.dataset, i)
                    if k in wanted:
                        self.quarantined.add(i)
                        self._quarantine_keys[i] = k
                        wanted.discard(k)
                        if not wanted:
                            break
                log.warning(
                    "quarantine relocation: %d/%d shifted samples "
                    "re-matched by content key, %d dropped (file "
                    "replaced/removed)", len(relocate) - len(wanted),
                    len(relocate), len(wanted))
        else:   # legacy v1: raw indices — adopt, compute keys, migrate
            for i in payload.get("indices", ()):
                i = int(i)
                if 0 <= i < n:
                    self.quarantined.add(i)
                    self._quarantine_keys[i] = sample_content_key(
                        self.dataset, i)
            if self.quarantined:
                log.info("migrating legacy index-keyed quarantine file "
                         "(%d entries) to content-hash keys",
                         len(self.quarantined))
                self._write_quarantine(
                    [{"index": i, "key": self._quarantine_keys.get(i)}
                     for i in sorted(self.quarantined)])

    def _write_quarantine(self, entries: List[Dict]) -> None:
        if not self.quarantine_path:
            return
        try:
            tmp = f"{self.quarantine_path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"version": 2, "samples": entries}, f)
                f.write("\n")
            os.replace(tmp, self.quarantine_path)
        except OSError:  # pragma: no cover - unwritable quarantine dir
            log.warning("could not persist quarantine list to %s",
                        self.quarantine_path)

    def __len__(self) -> int:
        return len(self.dataset) // self.batch_size  # drop_last

    # ------------------------------------------------------- resume state
    def state(self, consumed: int = 0) -> Dict[str, Any]:
        """Serializable position after ``consumed`` batches of the current
        iterator: feed to ``set_state`` (or the checkpoint runtime blob)
        to resume with a bitwise-identical data order."""
        return {"offset": self.start_offset + consumed,
                "salts": [list(s) for s in self.salts]}

    def set_state(self, state: Dict[str, Any]) -> None:
        """Position the NEXT ``iter()`` at ``state`` (a ``state()`` dict).
        Live iterators are unaffected — the train loop closes its
        prefetcher and re-iterates after calling this."""
        self.start_offset = int(state.get("offset", 0))
        self.salts = tuple((int(e), int(b), int(s))
                           for e, b, s in state.get("salts", ()))

    def add_salt(self, epoch: int, batch: int, salt: int) -> None:
        """Append a rewind reshuffle event: the order of epoch ``epoch``
        from batch ``batch`` on is re-permuted with ``salt`` (consumed
        prefix untouched, still no within-epoch sample repeats) — the
        poison batch that triggered the rewind lands somewhere else."""
        self.salts = self.salts + ((int(epoch), int(batch), int(salt)),)

    # -------------------------------------------------------- batch order
    def _epoch_order(self, epoch: int) -> np.ndarray:
        if self.shuffle:
            order = np.random.default_rng(self.seed + epoch).permutation(
                len(self.dataset))
        else:
            order = np.arange(len(self.dataset))
        # Salt events apply in arrival order even with shuffle off — a
        # rewind must perturb the order either way, that is its point.
        for e, b, s in self.salts:
            if e != epoch:
                continue
            cut = b * self.batch_size
            rng = np.random.default_rng([self.seed, epoch, b, s])
            order = np.concatenate([order[:cut],
                                    rng.permutation(order[cut:])])
        return order

    def _make_batch(self, epoch: int, indices: np.ndarray
                    ) -> Dict[str, np.ndarray]:
        if not self.fault_isolation:
            return _collate(self.dataset, epoch, indices)
        with self._fault_lock:
            quarantined = frozenset(self.quarantined)
        batch, events = _collate_isolated(self.dataset, epoch, indices,
                                          quarantined=quarantined)
        self._note_fault_events(events)
        return batch

    def _note_fault_events(self, events: Sequence[Dict]) -> None:
        if not events:
            return
        dirty = False
        with self._fault_lock:
            for ev in events:
                if ev["kind"] == "retried":
                    self.stats["retried"] += 1
                    log.warning("sample %s raised once and succeeded on "
                                "retry: %s", ev["index"], ev["error"])
                elif ev["kind"] == "quarantined":
                    if ev["index"] not in self.quarantined:
                        self.quarantined.add(ev["index"])
                        self._quarantine_keys[ev["index"]] = (
                            sample_content_key(self.dataset, ev["index"]))
                        self.stats["quarantined"] += 1
                        dirty = True
                    log.warning("sample %s quarantined after retry: %s",
                                ev["index"], ev["error"])
            snapshot = [{"index": i, "key": self._quarantine_keys.get(i)}
                        for i in sorted(self.quarantined)]
        if dirty:
            self._write_quarantine(snapshot)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self.num_workers <= 0:
            yield from self._iter_sync()
        elif self.worker_type == "process":
            yield from self._iter_process()
        else:
            yield from self._iter_threaded()

    def _batch_indices(self):
        local = self.batch_size // self.process_count
        lo = self.process_index * local
        epoch, start_batch = divmod(self.start_offset, max(1, len(self)))
        while self.epochs is None or epoch < self.epochs:
            order = self._epoch_order(epoch)
            for i in range(start_batch, len(self)):
                global_slice = order[i * self.batch_size:
                                     (i + 1) * self.batch_size]
                yield epoch, global_slice[lo:lo + local]
            start_batch = 0
            epoch += 1

    def _iter_sync(self):
        for epoch, idx in self._batch_indices():
            yield self._make_batch(epoch, idx)

    def _spawn_pool(self):
        import concurrent.futures as cf
        import multiprocessing as mp

        # spawn, not fork: the parent holds a live JAX runtime whose
        # internal threads/locks must not be duplicated into children
        ctx = mp.get_context("spawn")
        ds_bytes = pickle.dumps(self.dataset)
        with self._fault_lock:
            quarantined = tuple(sorted(self.quarantined))
        return cf.ProcessPoolExecutor(self.num_workers, mp_context=ctx,
                                      initializer=_process_worker_init,
                                      initargs=(ds_bytes, quarantined))

    def _iter_process(self):
        """Spawned worker processes; submission order = yield order (an
        ordered deque of futures doubles as the reorder buffer), with at
        most ``prefetch + num_workers`` batches in flight.

        A BROKEN pool (a worker process died: OOM kill, native decoder
        segfault) is respawned with the current quarantine view and every
        in-flight batch resubmitted in order — the consumer never sees
        the death, only the ``worker_respawns`` counter moving.  After
        ``MAX_POOL_RESPAWNS`` consecutive breakages the loader raises the
        typed ``LoaderBroken`` instead of respawn-looping forever."""
        import collections

        max_ahead = self.prefetch + self.num_workers
        pool = self._spawn_pool()
        try:
            gen = self._batch_indices()
            # Each entry rides (future, args) so a broken pool can
            # resubmit the exact same work to the fresh one.
            inflight: "collections.deque" = collections.deque()
            exhausted = False
            respawns_in_a_row = 0
            while True:
                while not exhausted and len(inflight) < max_ahead:
                    try:
                        epoch, idx = next(gen)
                    except StopIteration:
                        exhausted = True
                        break
                    args = (epoch, idx)
                    inflight.append(
                        (pool.submit(_process_make_batch, args), args))
                if not inflight:
                    return
                fut, args = inflight.popleft()
                try:
                    result = fut.result()
                except BaseException as e:
                    if not (self.fault_isolation
                            and _is_broken_pool_error(e)):
                        raise
                    respawns_in_a_row += 1
                    with self._fault_lock:
                        self.stats["worker_respawns"] += 1
                    log.warning(
                        "loader worker pool died (%r); respawn %d/%d and "
                        "resubmitting %d in-flight batches", e,
                        respawns_in_a_row, MAX_POOL_RESPAWNS,
                        len(inflight) + 1)
                    if respawns_in_a_row > MAX_POOL_RESPAWNS:
                        raise LoaderBroken(
                            f"worker pool died {respawns_in_a_row} times "
                            f"in a row; last error: {e!r}") from e
                    pool.shutdown(wait=False, cancel_futures=True)
                    pool = self._spawn_pool()
                    redo = [args] + [a for _, a in inflight]
                    inflight.clear()
                    for a in redo:
                        inflight.append(
                            (pool.submit(_process_make_batch, a), a))
                    continue
                respawns_in_a_row = 0
                if (isinstance(result, tuple) and len(result) == 2
                        and isinstance(result[1], list)):
                    batch, events = result
                    self._note_fault_events(events)
                else:   # fault_isolation=False workers return bare batches
                    batch = result
                yield batch
        finally:
            # Early close (consumer break / GeneratorExit) must not sit
            # through prefetch+num_workers queued full-frame batches — drop
            # the queue and leave only the in-flight task per worker to
            # drain in the background (e.g. a SIGTERM-triggered checkpoint
            # would otherwise stall multiple seconds here).
            pool.shutdown(wait=False, cancel_futures=True)

    def _iter_threaded(self):
        """Workers claim batch slots from a ticket queue and publish into a
        bounded reorder buffer, so batch order stays deterministic while
        decode runs ahead."""
        tickets: "queue.Queue" = queue.Queue()
        done = threading.Event()
        results: Dict[int, Dict[str, np.ndarray]] = {}
        results_lock = threading.Condition()
        max_ahead = self.prefetch + self.num_workers

        def worker():
            while not done.is_set():
                try:
                    seq, epoch, idx = tickets.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    batch = self._make_batch(epoch, idx)
                except Exception as e:  # surface decode errors to the consumer
                    batch = e
                with results_lock:
                    results[seq] = batch
                    results_lock.notify_all()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.num_workers)]
        for t in threads:
            t.start()

        try:
            gen = self._batch_indices()
            issued = 0
            consumed = 0
            exhausted = False
            while True:
                while not exhausted and issued < consumed + max_ahead:
                    try:
                        epoch, idx = next(gen)
                    except StopIteration:
                        exhausted = True
                        break
                    tickets.put((issued, epoch, idx))
                    issued += 1
                if exhausted and consumed == issued:
                    return
                with results_lock:
                    while consumed not in results:
                        results_lock.wait(timeout=0.5)
                    batch = results.pop(consumed)
                consumed += 1
                if isinstance(batch, Exception):
                    raise batch
                yield batch
        finally:
            done.set()
            # Collect the workers (they poll `done` every 0.1 s): a daemon
            # thread still inside the native decoder at interpreter
            # teardown aborts the process ("terminate called without an
            # active exception"); bounded joins close that window without
            # risking a hang on a stuck decode.
            for t in threads:
                t.join(timeout=2.0)


def _is_broken_pool_error(e: BaseException) -> bool:
    """Whether an exception out of ``Future.result()`` means the POOL
    died (worker process killed) rather than the task raising.  Task
    exceptions cannot occur with fault isolation on — ``_collate_isolated``
    absorbs them — so a raising future is pool death by construction;
    the isinstance check keeps non-isolated semantics exact."""
    import concurrent.futures as cf

    broken = (getattr(cf.process, "BrokenProcessPool", None),
              cf.BrokenExecutor)
    return isinstance(e, tuple(b for b in broken if b is not None))
