"""Host-side batch loader: shuffled, threaded, prefetching.

TPU-native replacement for the reference's ``torch.utils.data.DataLoader``
(reference: core/stereo_datasets.py:311-312): decode + augment run on host
CPU threads while the device steps; batches are stacked NHWC NumPy dicts
ready for ``shard_batch``.  Threads (not processes) because the decode path
is NumPy/cv2 releasing the GIL; the native C++ decode path slots in below.

Determinism: the epoch-``e`` permutation comes from ``seed + e`` and each
sample's augmentation RNG from ``(seed, epoch, index)`` (see datasets.py), so
a (seed, step) pair maps to one exact batch regardless of thread scheduling.
"""

from __future__ import annotations

import pickle
import queue
import threading
from typing import Dict, Iterator, Optional

import numpy as np

from raft_stereo_tpu.data.datasets import StereoDataset

def _collate(dataset: StereoDataset, epoch: int, indices
             ) -> Dict[str, np.ndarray]:
    """THE batch-assembly contract — every worker flavor (sync, thread,
    process) builds batches through this one function."""
    samples = [dataset.__getitem__(int(i), epoch) for i in indices]
    return {k: np.stack([s[k] for s in samples]) for k in samples[0]}


# --------------------------------------------------- process-worker plumbing
# Module-level so child processes (spawn) can import it; the dataset is
# shipped once via the pool initializer, not per task.
_WORKER_DATASET: Optional[StereoDataset] = None


def _process_worker_init(ds_bytes: bytes) -> None:
    global _WORKER_DATASET
    _WORKER_DATASET = pickle.loads(ds_bytes)


def _process_make_batch(args):
    epoch, indices = args
    return _collate(_WORKER_DATASET, epoch, indices)


class StereoLoader:
    """Iterate device-ready batches forever (training) or one epoch (eval).

    Args:
      dataset: a ``StereoDataset`` (samples must share one crop size).
      batch_size: GLOBAL batch size; ``drop_last`` semantics always on.
      shuffle: re-permute every epoch with ``seed + epoch``.
      num_workers: decode threads; 0 = synchronous in-caller decode.
      prefetch: max ready batches buffered ahead.
      epochs: None = loop forever.
      process_index/process_count: multi-host data sharding — every process
        draws the same seeded permutation but decodes only its contiguous
        slice of each global batch (``parallel.distributed`` supplies these;
        ``mesh.shard_batch`` reassembles the global array).  Yielded batches
        then have ``batch_size // process_count`` samples.
    """

    def __init__(self, dataset: StereoDataset, batch_size: int,
                 shuffle: bool = True, num_workers: int = 4,
                 prefetch: int = 2, seed: int = 1234,
                 epochs: Optional[int] = None,
                 process_index: int = 0, process_count: int = 1,
                 worker_type: str = "thread"):
        if len(dataset) < batch_size:
            raise ValueError(
                f"dataset has {len(dataset)} samples < batch_size={batch_size}")
        if batch_size % process_count:
            raise ValueError(f"batch_size={batch_size} not divisible by "
                             f"process_count={process_count}")
        if not (0 <= process_index < process_count):
            raise ValueError(f"process_index={process_index} out of range "
                             f"for process_count={process_count}")
        if worker_type not in ("thread", "process"):
            raise ValueError(f"worker_type={worker_type!r} not in "
                             f"('thread', 'process')")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = num_workers
        self.prefetch = prefetch
        self.seed = seed
        self.epochs = epochs
        self.process_index = process_index
        self.process_count = process_count
        # "process": decode+augment in spawned worker PROCESSES — sidesteps
        # the GIL entirely where thread workers only overlap the
        # GIL-releasing segments (native decode, cv2).  Costs one extra
        # batch copy (pickle over the pipe) per batch, so it pays off on
        # multi-core hosts where augment's pure-NumPy Python dominates.
        # Determinism is identical: a batch is a pure function of
        # (seed, epoch, indices) regardless of which worker builds it.
        # NOTE: like any spawn-based pool (torch DataLoader included), the
        # launching script must be import-safe — iteration from a script
        # without an ``if __name__ == "__main__"`` guard re-executes that
        # script in every worker.
        self.worker_type = worker_type

    def __len__(self) -> int:
        return len(self.dataset) // self.batch_size  # drop_last

    def _epoch_order(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.dataset))
        return np.random.default_rng(self.seed + epoch).permutation(
            len(self.dataset))

    def _make_batch(self, epoch: int, indices: np.ndarray
                    ) -> Dict[str, np.ndarray]:
        return _collate(self.dataset, epoch, indices)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self.num_workers <= 0:
            yield from self._iter_sync()
        elif self.worker_type == "process":
            yield from self._iter_process()
        else:
            yield from self._iter_threaded()

    def _batch_indices(self):
        local = self.batch_size // self.process_count
        lo = self.process_index * local
        epoch = 0
        while self.epochs is None or epoch < self.epochs:
            order = self._epoch_order(epoch)
            for i in range(len(self)):
                global_slice = order[i * self.batch_size:
                                     (i + 1) * self.batch_size]
                yield epoch, global_slice[lo:lo + local]
            epoch += 1

    def _iter_sync(self):
        for epoch, idx in self._batch_indices():
            yield self._make_batch(epoch, idx)

    def _iter_process(self):
        """Spawned worker processes; submission order = yield order (an
        ordered deque of futures doubles as the reorder buffer), with at
        most ``prefetch + num_workers`` batches in flight."""
        import collections
        import concurrent.futures as cf
        import multiprocessing as mp

        # spawn, not fork: the parent holds a live JAX runtime whose
        # internal threads/locks must not be duplicated into children
        ctx = mp.get_context("spawn")
        ds_bytes = pickle.dumps(self.dataset)
        max_ahead = self.prefetch + self.num_workers
        pool = cf.ProcessPoolExecutor(self.num_workers, mp_context=ctx,
                                      initializer=_process_worker_init,
                                      initargs=(ds_bytes,))
        try:
            gen = self._batch_indices()
            inflight: "collections.deque" = collections.deque()
            exhausted = False
            while True:
                while not exhausted and len(inflight) < max_ahead:
                    try:
                        epoch, idx = next(gen)
                    except StopIteration:
                        exhausted = True
                        break
                    inflight.append(pool.submit(_process_make_batch,
                                                (epoch, idx)))
                if not inflight:
                    return
                yield inflight.popleft().result()
        finally:
            # Early close (consumer break / GeneratorExit) must not sit
            # through prefetch+num_workers queued full-frame batches — drop
            # the queue and leave only the in-flight task per worker to
            # drain in the background (e.g. a SIGTERM-triggered checkpoint
            # would otherwise stall multiple seconds here).
            pool.shutdown(wait=False, cancel_futures=True)

    def _iter_threaded(self):
        """Workers claim batch slots from a ticket queue and publish into a
        bounded reorder buffer, so batch order stays deterministic while
        decode runs ahead."""
        tickets: "queue.Queue" = queue.Queue()
        done = threading.Event()
        results: Dict[int, Dict[str, np.ndarray]] = {}
        results_lock = threading.Condition()
        max_ahead = self.prefetch + self.num_workers

        def worker():
            while not done.is_set():
                try:
                    seq, epoch, idx = tickets.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    batch = self._make_batch(epoch, idx)
                except Exception as e:  # surface decode errors to the consumer
                    batch = e
                with results_lock:
                    results[seq] = batch
                    results_lock.notify_all()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.num_workers)]
        for t in threads:
            t.start()

        try:
            gen = self._batch_indices()
            issued = 0
            consumed = 0
            exhausted = False
            while True:
                while not exhausted and issued < consumed + max_ahead:
                    try:
                        epoch, idx = next(gen)
                    except StopIteration:
                        exhausted = True
                        break
                    tickets.put((issued, epoch, idx))
                    issued += 1
                if exhausted and consumed == issued:
                    return
                with results_lock:
                    while consumed not in results:
                        results_lock.wait(timeout=0.5)
                    batch = results.pop(consumed)
                consumed += 1
                if isinstance(batch, Exception):
                    raise batch
                yield batch
        finally:
            done.set()
            # Collect the workers (they poll `done` every 0.1 s): a daemon
            # thread still inside the native decoder at interpreter
            # teardown aborts the process ("terminate called without an
            # active exception"); bounded joins close that window without
            # risking a hang on a stuck decode.
            for t in threads:
                t.join(timeout=2.0)
