"""On-device photometric augmentation (opt-in input-pipeline offload).

The host ``ColorJitter`` (data/augment.py) costs ~63 ms/sample at SceneFlow
frame sizes — 78% of the whole per-sample host budget on a one-core host
(measured, docs/TRAIN_PROFILE.md round 4) — while the chip absorbs the same
elementwise work in single-digit milliseconds inside the already
memory-bound train step.  This module replicates torchvision ColorJitter
semantics (reference: core/utils/augmentor.py:73-93 — brightness/contrast/
saturation blends + hue shift, ops in random order, symmetric-or-asymmetric
across the stereo pair, optional gamma) in pure ``jnp`` with per-sample
factors drawn from a step-folded JAX PRNG key, so the augmentation stream
is a deterministic function of (seed, step) and survives exact resume.

Documented deviations from the host path (the host path remains the
reference-faithful default; this mode trades bit-parity for host CPU):

* runs AFTER spatial crop/resize (inside the train step), so contrast/
  saturation reference means are over the crop, not the full frame;
* float32 throughout with a clip after each op — no uint8 rounding between
  ops, and hue shifts are not quantized to cv2's 1/180-turn grid;
* the occlusion eraser stays on the host (it is ~free there and needs
  pre-crop geometry).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class JitterParams:
    """Factor ranges, defaulting to the dense-augmentor profile
    (data/augment.py DenseAugmentor; reference: core/utils/augmentor.py:85)."""

    brightness: float = 0.4
    contrast: float = 0.4
    saturation: Tuple[float, float] = (0.6, 1.4)
    hue: float = 0.5 / 3.14
    # (gamma_min, gamma_max, gain_min, gain_max); (1,1,1,1) = off
    gamma: Tuple[float, float, float, float] = (1.0, 1.0, 1.0, 1.0)
    asymmetric_prob: float = 0.2


# ------------------------------------------------------------- fixed-factor ops
# Each mirrors its uint8 host twin in data/augment.py; factors are explicit
# so tests can compare host vs device op-by-op.  Images are float32 0..255.

def adjust_brightness(img: jnp.ndarray, factor) -> jnp.ndarray:
    return jnp.clip(img * factor, 0.0, 255.0)


def adjust_contrast(img: jnp.ndarray, factor, mean) -> jnp.ndarray:
    """``mean`` is the gray mean to blend toward — per-sample scalar,
    passed in because symmetric stereo jitter uses the PAIR's joint mean
    (host: jitter of the stacked pair, augment.py DenseAugmentor._color)."""
    return jnp.clip(img * factor + (1.0 - factor) * mean, 0.0, 255.0)


def adjust_saturation(img: jnp.ndarray, factor) -> jnp.ndarray:
    luma = img @ jnp.asarray([0.299, 0.587, 0.114], img.dtype)
    return jnp.clip(img * factor + (1.0 - factor) * luma[..., None],
                    0.0, 255.0)


def adjust_hue(img: jnp.ndarray, shift) -> jnp.ndarray:
    """``shift`` in turns of the hue circle, like the host op."""
    x = img * (1.0 / 255.0)
    mx = jnp.max(x, axis=-1)
    mn = jnp.min(x, axis=-1)
    c = mx - mn
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    safe_c = jnp.where(c > 0, c, 1.0)
    h = jnp.where(
        c <= 0, 0.0,
        jnp.where(mx == r, ((g - b) / safe_c) % 6.0,
                  jnp.where(mx == g, (b - r) / safe_c + 2.0,
                            (r - g) / safe_c + 4.0))) / 6.0
    h = (h + shift) % 1.0
    # HSV -> RGB with v = mx, s*v = c
    k = (jnp.stack([jnp.full_like(h, 5.0), jnp.full_like(h, 3.0),
                    jnp.full_like(h, 1.0)], axis=-1) + h[..., None] * 6.0) % 6.0
    out = mx[..., None] - c[..., None] * jnp.clip(
        jnp.minimum(k, 4.0 - k), 0.0, 1.0)
    return jnp.clip(out * 255.0, 0.0, 255.0)


def adjust_gamma(img: jnp.ndarray, gamma, gain) -> jnp.ndarray:
    x = img * (1.0 / 255.0)
    return jnp.clip(255.0 * gain * jnp.power(x, gamma), 0.0, 255.0)


def _gray_mean(img: jnp.ndarray) -> jnp.ndarray:
    """Per-sample scalar: mean over channels then pixels (host twin:
    augment.adjust_contrast's fp32 accumulation)."""
    return jnp.mean(img, axis=(-3, -2, -1))


# ----------------------------------------------------------------- pair jitter
def apply_photometric(img1: jnp.ndarray, img2: jnp.ndarray, key,
                      params: JitterParams
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Jitter a stereo batch: (B,H,W,3) uint8/float 0..255 -> float32.

    Per sample: draw factors + a random op order for view 1; with
    probability ``asymmetric_prob`` view 2 gets independent factors AND an
    independent order (host: two separate ``jitter()`` calls), otherwise it
    shares view 1's factors/order and the contrast op blends toward the
    JOINT mean of both views (host: jitter of the vertically stacked pair).
    """
    b = img1.shape[0]
    img1 = img1.astype(jnp.float32)
    img2 = img2.astype(jnp.float32)

    k_f1, k_f2, k_o1, k_o2, k_asym, k_gamma = jax.random.split(key, 6)

    def draw_factors(k):
        kb, kc, ks, kh = jax.random.split(k, 4)
        p = params
        return {
            "b": jax.random.uniform(kb, (b,), minval=max(0.0, 1 - p.brightness),
                                    maxval=1 + p.brightness),
            "c": jax.random.uniform(kc, (b,), minval=max(0.0, 1 - p.contrast),
                                    maxval=1 + p.contrast),
            "s": jax.random.uniform(ks, (b,), minval=p.saturation[0],
                                    maxval=p.saturation[1]),
            "h": jax.random.uniform(kh, (b,), minval=-p.hue, maxval=p.hue),
        }

    f1 = draw_factors(k_f1)
    f2i = draw_factors(k_f2)
    asym = jax.random.bernoulli(k_asym, params.asymmetric_prob, (b,))
    f2 = {k: jnp.where(asym, f2i[k], f1[k]) for k in f1}

    # op order: per-sample permutation of {brightness, contrast, saturation,
    # hue} via argsort of uniforms (torchvision: torch.randperm per call)
    perm1 = jnp.argsort(jax.random.uniform(k_o1, (b, 4)), axis=-1)
    perm2i = jnp.argsort(jax.random.uniform(k_o2, (b, 4)), axis=-1)
    perm2 = jnp.where(asym[:, None], perm2i, perm1)

    bc = lambda v: v[:, None, None, None]  # (B,) -> broadcast over H,W,C

    def position(img1, img2, k):
        """Apply the k-th op of each sample's order to both views.  All four
        ops are computed and selected per sample (the order is data-
        dependent); 4 positions x 4 ops = 16 elementwise passes, ~ms on
        chip vs 63 ms/sample on host."""
        op1 = perm1[:, k]
        op2 = perm2[:, k]
        m1 = _gray_mean(img1)
        m2 = _gray_mean(img2)
        joint = 0.5 * (m1 + m2)
        # symmetric pairs share op history, so the joint mean is exact
        cmean1 = jnp.where(asym, m1, joint)
        cmean2 = jnp.where(asym, m2, joint)

        def all_ops(img, f, cmean):
            return jnp.stack([
                adjust_brightness(img, bc(f["b"])),
                adjust_contrast(img, bc(f["c"]), bc(cmean)),
                adjust_saturation(img, bc(f["s"])),
                adjust_hue(img, f["h"][:, None, None]),
            ])

        sel1 = jnp.take_along_axis(
            all_ops(img1, f1, cmean1), op1[None, :, None, None, None],
            axis=0)[0]
        sel2 = jnp.take_along_axis(
            all_ops(img2, f2, cmean2), op2[None, :, None, None, None],
            axis=0)[0]
        return sel1, sel2

    for k in range(4):
        img1, img2 = position(img1, img2, k)

    gmin, gmax, gainmin, gainmax = params.gamma
    if (gmin, gmax, gainmin, gainmax) != (1.0, 1.0, 1.0, 1.0):
        kg1, kg2 = jax.random.split(k_gamma)
        g = jax.random.uniform(kg1, (b,), minval=gmin, maxval=gmax)
        gain = jax.random.uniform(kg2, (b,), minval=gainmin, maxval=gainmax)
        # gamma is drawn once per host jitter() call; symmetric pairs share
        # it (stacked-pair path), asymmetric pairs draw independently
        g2i = jax.random.uniform(jax.random.fold_in(kg1, 1), (b,),
                                 minval=gmin, maxval=gmax)
        gain2i = jax.random.uniform(jax.random.fold_in(kg2, 1), (b,),
                                    minval=gainmin, maxval=gainmax)
        img1 = adjust_gamma(img1, bc(g), bc(gain))
        img2 = adjust_gamma(img2, bc(jnp.where(asym, g2i, g)),
                            bc(jnp.where(asym, gain2i, gain)))
    return img1, img2


def params_for_datasets(train_datasets, saturation_range=None,
                        img_gamma=None) -> JitterParams:
    """Derive the jitter profile from the training mixture the way
    ``build_training_mixture`` parameterizes the host augmentors.

    Dense-GT families use the dense profile (0.4/0.4/(0.6,1.4)/0.5÷3.14),
    sparse-GT families the sparse one (0.3/0.3/(0.7,1.3)/0.3÷3.14) —
    data/augment.py Dense/SparseAugmentor defaults.  A mixture spanning
    both profiles cannot share one device-jitter parameterization: raise,
    keep host jitter there."""
    dense = {"sceneflow", "falling_things"}
    is_dense = [name in dense or name.startswith("tartan_air")
                for name in train_datasets]
    if all(is_dense):
        p = JitterParams()
    elif not any(is_dense):
        # sparse host jitter is ALWAYS symmetric (augment.py
        # SparseAugmentor.__call__ jitters the stacked pair
        # unconditionally), so asymmetric_prob must be 0 here
        p = JitterParams(brightness=0.3, contrast=0.3, saturation=(0.7, 1.3),
                         hue=0.3 / 3.14, asymmetric_prob=0.0)
    else:
        raise ValueError(
            f"device_photometric cannot serve a mixture of dense and "
            f"sparse jitter profiles ({list(train_datasets)}); train with "
            f"host-side augmentation there")
    if saturation_range is not None:
        p = dataclasses.replace(p, saturation=tuple(saturation_range))
    if img_gamma is not None:
        g = tuple(img_gamma)
        p = dataclasses.replace(
            p, gamma=g if len(g) == 4 else (g[0], g[1], 1.0, 1.0))
    return p
