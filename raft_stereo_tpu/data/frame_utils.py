"""Stereo file-format readers and writers (NumPy, host-side).

Covers every format the reference consumes (reference:
core/utils/frame_utils.py): PFM, Middlebury ``.flo``, KITTI 16-bit PNG
disparity, Sintel packed 3-channel disparity + occlusion masks, FallingThings
depth + camera JSON, TartanAir ``.npy`` depth, Middlebury GT + nocc mask.

Readers return either a plain ``(H, W)``/(H, W, C)`` array (dense GT) or a
``(disparity, valid)`` tuple (formats with an explicit validity channel).
All outputs are float32 / bool, HWC, never framework tensors.
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional, Tuple, Union

import numpy as np

try:
    import cv2
    cv2.setNumThreads(0)  # loader threads must not oversubscribe
    cv2.ocl.setUseOpenCL(False)
except ImportError:  # pragma: no cover
    cv2 = None

from PIL import Image

from raft_stereo_tpu import native

FLO_MAGIC = 202021.25


# ------------------------------------------------------------------ images
def read_image(path: str) -> np.ndarray:
    """Read an image as (H, W, 3) uint8; grayscale is replicated to 3ch.

    PNGs go through the native decoder when built (GIL-free in loader
    threads); other formats and fallback use PIL."""
    if native.available() and path.lower().endswith(".png"):
        try:
            return native.read_png_rgb8(path)
        except ValueError:
            pass  # odd sub-format — let PIL try
    img = np.asarray(Image.open(path))
    if img.dtype != np.uint8 and np.issubdtype(img.dtype, np.integer):
        # 16-bit sources: keep the high byte, matching the native decoder's
        # png_set_strip_16 (astype alone would keep the LOW byte).
        img = (img.astype(np.uint32) >> 8).astype(np.uint8)
    if img.ndim == 2:
        img = np.repeat(img[..., None], 3, axis=-1)
    return img[..., :3].astype(np.uint8)


# --------------------------------------------------------------------- PFM
def read_pfm(path: str) -> np.ndarray:
    """Portable Float Map: 'Pf' (1ch) / 'PF' (3ch), rows stored bottom-up,
    scale sign encodes endianness.  Native decoder when built; the pure-
    Python path below is the fallback and the semantics reference."""
    if native.available():
        try:
            return native.read_pfm(path)
        except ValueError:
            pass
    return _read_pfm_py(path)


def _read_pfm_py(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        header = f.readline().rstrip()
        if header == b"PF":
            channels = 3
        elif header == b"Pf":
            channels = 1
        else:
            raise ValueError(f"{path}: not a PFM file (header {header!r})")
        dims = f.readline()
        m = re.match(rb"^(\d+)\s+(\d+)\s*$", dims)
        if not m:
            raise ValueError(f"{path}: malformed PFM dimensions {dims!r}")
        width, height = int(m.group(1)), int(m.group(2))
        scale = float(f.readline().rstrip())
        dtype = "<f4" if scale < 0 else ">f4"
        data = np.fromfile(f, dtype, count=width * height * channels)
    shape = (height, width, 3) if channels == 3 else (height, width)
    return np.flipud(data.reshape(shape)).astype(np.float32)


def write_pfm(path: str, array: np.ndarray) -> None:
    assert array.ndim == 2, "write_pfm writes single-channel maps"
    with open(path, "wb") as f:
        h, w = array.shape
        f.write(b"Pf\n" + f"{w} {h}\n".encode() + b"-1\n")
        f.write(np.flipud(array).astype("<f4").tobytes())


# --------------------------------------------------------------------- flo
def read_flo(path: str) -> np.ndarray:
    """Middlebury .flo optical flow → (H, W, 2) float32."""
    with open(path, "rb") as f:
        magic = np.fromfile(f, "<f4", count=1)
        if magic.size == 0 or magic[0] != np.float32(FLO_MAGIC):
            raise ValueError(f"{path}: bad .flo magic {magic}")
        w = int(np.fromfile(f, "<i4", count=1)[0])
        h = int(np.fromfile(f, "<i4", count=1)[0])
        data = np.fromfile(f, "<f4", count=2 * w * h)
    return data.reshape(h, w, 2).astype(np.float32)


def write_flo(path: str, flow: np.ndarray) -> None:
    assert flow.ndim == 3 and flow.shape[2] == 2
    h, w = flow.shape[:2]
    with open(path, "wb") as f:
        np.array([FLO_MAGIC], "<f4").tofile(f)
        np.array([w, h], "<i4").tofile(f)
        flow.astype("<f4").tofile(f)


# ------------------------------------------------------------------- KITTI
def read_disp_kitti(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """KITTI 16-bit PNG: disparity*256, 0 = invalid
    (reference: core/utils/frame_utils.py:124-127)."""
    if native.available():
        try:
            disp = native.read_png_gray16(path).astype(np.float32) / 256.0
            return disp, disp > 0.0
        except ValueError:
            pass
    if cv2 is not None:
        raw = cv2.imread(path, cv2.IMREAD_ANYDEPTH)
    else:  # pragma: no cover
        raw = np.asarray(Image.open(path))
    disp = raw.astype(np.float32) / 256.0
    return disp, disp > 0.0


def write_disp_kitti(path: str, disp: np.ndarray) -> None:
    enc = np.clip(disp * 256.0, 0, 2**16 - 1).astype(np.uint16)
    Image.fromarray(enc).save(path)


# ------------------------------------------------------------------ Sintel
def read_disp_sintel(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Sintel packs disparity into RGB: R*4 + G/64 + B/16384; the sibling
    ``occlusions`` tree masks occluded pixels
    (reference: core/utils/frame_utils.py:130-136)."""
    a = np.asarray(Image.open(path)).astype(np.float32)
    disp = a[..., 0] * 4 + a[..., 1] / 64.0 + a[..., 2] / 16384.0
    occ = np.asarray(Image.open(path.replace("disparities", "occlusions")))
    return disp, (occ == 0) & (disp > 0)


# ----------------------------------------------------------- FallingThings
def read_disp_falling_things(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """FallingThings stores depth (mm*100 in 16-bit PNG); disparity =
    fx * baseline(6cm) * 100 / depth with fx from the scene's camera JSON
    (reference: core/utils/frame_utils.py:139-146)."""
    depth = np.asarray(Image.open(path)).astype(np.float32)
    cam_json = os.path.join(os.path.dirname(path), "_camera_settings.json")
    with open(cam_json) as f:
        intrinsics = json.load(f)
    fx = intrinsics["camera_settings"][0]["intrinsic_settings"]["fx"]
    with np.errstate(divide="ignore"):
        disp = (fx * 6.0 * 100) / depth
    return disp, disp > 0


# --------------------------------------------------------------- TartanAir
def read_disp_tartanair(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """TartanAir ``.npy`` depth; disparity = 80 / depth
    (reference: core/utils/frame_utils.py:149-153)."""
    depth = np.load(path)
    with np.errstate(divide="ignore"):
        disp = 80.0 / depth
    return disp.astype(np.float32), disp > 0


# -------------------------------------------------------------- Middlebury
def read_disp_middlebury(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """MiddEval3 GT: disp0GT.pfm + mask0nocc.png (255 = non-occluded)
    (reference: core/utils/frame_utils.py:156-164)."""
    assert os.path.basename(path) == "disp0GT.pfm", path
    disp = read_pfm(path)
    assert disp.ndim == 2, disp.shape
    nocc = np.asarray(Image.open(
        path.replace("disp0GT.pfm", "mask0nocc.png"))) == 255
    return disp, nocc


# ---------------------------------------------------------------- dispatch
ReaderResult = Union[np.ndarray, Tuple[np.ndarray, np.ndarray]]


def read_gen(path: str) -> ReaderResult:
    """Extension-dispatched read (reference: core/utils/frame_utils.py:173-187).
    PFM color maps drop the last channel like the reference does."""
    ext = os.path.splitext(path)[1].lower()
    if ext in (".png", ".jpeg", ".jpg", ".ppm"):
        return read_image(path)
    if ext in (".bin", ".raw", ".npy"):
        return np.load(path)
    if ext == ".flo":
        return read_flo(path)
    if ext == ".pfm":
        x = read_pfm(path)
        return x if x.ndim == 2 else x[..., :-1]
    raise ValueError(f"read_gen: unsupported extension {ext!r} ({path})")
