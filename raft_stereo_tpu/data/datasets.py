"""Stereo dataset registry (host-side, framework-free).

Covers the reference's seven dataset families and its training-mixture
recipe (reference: core/stereo_datasets.py).  Differences by design:

* Samples are plain dicts of NumPy arrays in NHWC-friendly HWC layout —
  the loader stacks them into device batches.
* Datasets are index-lists built eagerly at construction; replication for
  mixture weighting is ``dataset * k`` like the reference (:111-117).
* Augmentation RNG is derived per ``(seed, index)`` — reproducible under
  any worker scheduling (reference reseeds per torch worker, :55-61).
* The reference's ``fetch_dataloader`` crashes when training on KITTI
  (passes an unsupported ``split=`` kwarg — core/stereo_datasets.py:298);
  here KITTI is registered properly.
"""

from __future__ import annotations

import copy
import glob
import logging
import os
import re
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.data.augment import DenseAugmentor, SparseAugmentor

log = logging.getLogger(__name__)

MAX_FLOW_MAGNITUDE = 512.0  # dense-GT validity cutoff (stereo_datasets.py:97)


class StereoDataset:
    """Base dataset: image pair + disparity GT → training sample dict.

    ``__getitem__(i, epoch)`` returns
    ``{"image1", "image2"}: (H,W,3) uint8 0..255 (normalized on DEVICE,
      models/raft_stereo.py:89-90 — uint8 quarters the host->device batch
      transfer),
      "flow": (H,W) float32 x-flow (= -disparity),
      "valid": (H,W) float32 in {0,1}`` — cropped to ``crop_size`` when an
    augmentor is configured.
    """

    def __init__(self, aug_params: Optional[dict] = None, sparse: bool = False,
                 reader: Optional[Callable] = None, seed: int = 1234):
        self.sparse = sparse
        self.reader = reader or frame_utils.read_gen
        self.seed = seed
        self.augmentor = None
        self.img_pad = None
        if aug_params is not None:
            aug_params = dict(aug_params)
            self.img_pad = aug_params.pop("img_pad", None)
            if "crop_size" in aug_params:
                cls = SparseAugmentor if sparse else DenseAugmentor
                self.augmentor = cls(**aug_params)
        self.image_list: List[Tuple[str, str]] = []
        self.disparity_list: List[str] = []

    # -------------------------------------------------------------- protocol
    def __len__(self) -> int:
        return len(self.image_list)

    def __mul__(self, k: int) -> "StereoDataset":
        """Replicate the index for mixture weighting
        (reference: core/stereo_datasets.py:111-117)."""
        out = copy.copy(self)
        out.image_list = self.image_list * k
        out.disparity_list = self.disparity_list * k
        return out

    def __add__(self, other: "StereoDataset") -> "StereoDataset":
        out = ConcatStereoDataset([self, other])
        return out

    def sample_paths(self, index: int):
        left, right = self.image_list[index]
        return left, right, self.disparity_list[index]

    def __getitem__(self, index: int, epoch: int = 0) -> Dict[str, np.ndarray]:
        index = index % len(self.image_list)
        left_path, right_path = self.image_list[index]
        img1 = frame_utils.read_image(left_path)
        img2 = frame_utils.read_image(right_path)

        disp = self.reader(self.disparity_list[index])
        if isinstance(disp, tuple):
            disp, valid = disp
        else:
            valid = disp < MAX_FLOW_MAGNITUDE
        disp = np.asarray(disp, np.float32)
        # disparity → x-flow; left image's match lies to the LEFT in the
        # right image (reference: core/stereo_datasets.py:77)
        flow = np.stack([-disp, np.zeros_like(disp)], axis=-1)

        if self.augmentor is not None:
            rng = np.random.default_rng(
                np.random.SeedSequence([self.seed, epoch, index]))
            if self.sparse:
                img1, img2, flow, valid = self.augmentor(
                    img1, img2, flow, valid.astype(np.float32), rng)
            else:
                img1, img2, flow = self.augmentor(img1, img2, flow, rng)

        if self.sparse:
            valid = np.asarray(valid, np.float32)
        else:
            valid = ((np.abs(flow[..., 0]) < MAX_FLOW_MAGNITUDE)
                     & (np.abs(flow[..., 1]) < MAX_FLOW_MAGNITUDE)
                     ).astype(np.float32)

        if self.img_pad is not None:
            pad_h, pad_w = self.img_pad
            pad = ((pad_h, pad_h), (pad_w, pad_w), (0, 0))
            img1 = np.pad(img1, pad)
            img2 = np.pad(img2, pad)

        # Images stay uint8: the decode/augment chain is uint8 end-to-end
        # and the model normalizes on device (models/raft_stereo.py:89-90),
        # so a float cast here would only 4x the host->device batch
        # transfer (59 -> 26 MB/step at the SceneFlow config — measured to
        # matter behind a remote device tunnel, bench_loader.py).
        return {
            "image1": np.ascontiguousarray(img1),
            "image2": np.ascontiguousarray(img2),
            "flow": np.ascontiguousarray(flow[..., 0], np.float32),
            "valid": valid,
        }


class ConcatStereoDataset(StereoDataset):
    def __init__(self, parts: Sequence[StereoDataset]):
        super().__init__(aug_params=None)
        self.parts = []
        for p in parts:  # flatten nested concats
            self.parts.extend(p.parts if isinstance(p, ConcatStereoDataset)
                              else [p])
        self._lengths = [len(p) for p in self.parts]
        self._offsets = np.cumsum([0] + self._lengths)

    def __len__(self):
        return int(self._offsets[-1])

    def _locate(self, index: int):
        index = index % len(self)
        part = int(np.searchsorted(self._offsets, index, side="right") - 1)
        return self.parts[part], index - int(self._offsets[part])

    def sample_paths(self, index: int):
        part, local = self._locate(index)
        return part.sample_paths(local)

    def __getitem__(self, index: int, epoch: int = 0):
        part, local = self._locate(index)
        return part.__getitem__(local, epoch)


# ------------------------------------------------------------------ datasets
class SceneFlow(StereoDataset):
    """FlyingThings3D + Monkaa + Driving (reference:
    core/stereo_datasets.py:123-184).  TEST split keeps the fixed-seed-1000
    400-image validation subset."""

    VAL_SUBSET_SEED = 1000
    VAL_SUBSET_SIZE = 400

    def __init__(self, aug_params=None, root="datasets",
                 dstype="frames_cleanpass", things_test=False, seed=1234):
        super().__init__(aug_params, seed=seed)
        self.root = root
        self.dstype = dstype
        if things_test:
            self._add_things("TEST")
        else:
            self._add_things("TRAIN")
            self._add_monkaa()
            self._add_driving()

    def _pairs(self, left_images):
        right = [p.replace("left", "right") for p in left_images]
        disp = [p.replace(self.dstype, "disparity").replace(".png", ".pfm")
                for p in left_images]
        return right, disp

    def _add_things(self, split):
        before = len(self)
        root = os.path.join(self.root, "FlyingThings3D")
        left = sorted(glob.glob(
            os.path.join(root, self.dstype, split, "*/*/left/*.png")))
        right, disp = self._pairs(left)
        # fixed validation subset, independent of global RNG state
        val_idxs = set()
        if split == "TEST":
            rng = np.random.RandomState(self.VAL_SUBSET_SEED)
            val_idxs = set(rng.permutation(len(left))[:self.VAL_SUBSET_SIZE])
        for i, (l, r, d) in enumerate(zip(left, right, disp)):
            if split == "TRAIN" or i in val_idxs:
                self.image_list.append((l, r))
                self.disparity_list.append(d)
        log.info("Added %d from FlyingThings %s", len(self) - before,
                 self.dstype)

    def _add_monkaa(self):
        before = len(self)
        root = os.path.join(self.root, "Monkaa")
        left = sorted(glob.glob(os.path.join(root, self.dstype,
                                             "*/left/*.png")))
        right, disp = self._pairs(left)
        self.image_list += list(zip(left, right))
        self.disparity_list += disp
        log.info("Added %d from Monkaa %s", len(self) - before, self.dstype)

    def _add_driving(self):
        before = len(self)
        root = os.path.join(self.root, "Driving")
        left = sorted(glob.glob(os.path.join(root, self.dstype,
                                             "*/*/*/left/*.png")))
        right, disp = self._pairs(left)
        self.image_list += list(zip(left, right))
        self.disparity_list += disp
        log.info("Added %d from Driving %s", len(self) - before, self.dstype)


class ETH3D(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/ETH3D",
                 split="training", seed=1234):
        super().__init__(aug_params, sparse=True, seed=seed)
        left = sorted(glob.glob(os.path.join(root, f"two_view_{split}/*/im0.png")))
        right = sorted(glob.glob(os.path.join(root, f"two_view_{split}/*/im1.png")))
        if split == "training":
            disp = sorted(glob.glob(
                os.path.join(root, "two_view_training_gt/*/disp0GT.pfm")))
        else:  # test split has no GT; reference substitutes a fixed file
            disp = [os.path.join(root, "two_view_training_gt/playground_1l/"
                                 "disp0GT.pfm")] * len(left)
        # default read_gen reader: PFM, valid = disp < 512 (inf GT → invalid)
        self.image_list = list(zip(left, right))
        self.disparity_list = disp


class SintelStereo(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/SintelStereo",
                 seed=1234):
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_sintel, seed=seed)
        left = sorted(glob.glob(
            os.path.join(root, "training/*_left/*/frame_*.png")))
        right = sorted(glob.glob(
            os.path.join(root, "training/*_right/*/frame_*.png")))
        # one disparity tree serves both the clean and final passes
        disp = sorted(glob.glob(
            os.path.join(root, "training/disparities/*/frame_*.png"))) * 2
        for l, r, d in zip(left, right, disp):
            assert (l.split(os.sep)[-2:] == d.split(os.sep)[-2:]), (l, d)
            self.image_list.append((l, r))
            self.disparity_list.append(d)


class FallingThings(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/FallingThings",
                 seed=1234):
        super().__init__(aug_params,
                         reader=frame_utils.read_disp_falling_things,
                         seed=seed)
        with open(os.path.join(root, "filenames.txt")) as f:
            names = sorted(f.read().splitlines())
        for e in names:
            self.image_list.append((
                os.path.join(root, e),
                os.path.join(root, e.replace("left.jpg", "right.jpg"))))
            self.disparity_list.append(
                os.path.join(root, e.replace("left.jpg", "left.depth.png")))


class TartanAir(StereoDataset):
    def __init__(self, aug_params=None, root="datasets", keywords=(),
                 seed=1234):
        super().__init__(aug_params, reader=frame_utils.read_disp_tartanair,
                         seed=seed)
        with open(os.path.join(root, "tartanair_filenames.txt")) as f:
            names = [s for s in f.read().splitlines()
                     if "seasonsforest_winter/Easy" not in s]
        for kw in keywords:
            names = [s for s in names if kw in s.lower()]
        for e in sorted(names):
            self.image_list.append((
                os.path.join(root, e),
                os.path.join(root, e.replace("_left", "_right"))))
            self.disparity_list.append(os.path.join(
                root, e.replace("image_left", "depth_left")
                       .replace("left.png", "left_depth.npy")))


class KITTI(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/KITTI",
                 image_set="training", seed=1234):
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_kitti, seed=seed)
        left = sorted(glob.glob(os.path.join(root, image_set,
                                             "image_2/*_10.png")))
        right = sorted(glob.glob(os.path.join(root, image_set,
                                              "image_3/*_10.png")))
        if image_set == "training":
            disp = sorted(glob.glob(os.path.join(root, "training",
                                                 "disp_occ_0/*_10.png")))
        else:  # no GT for the test set; fixed placeholder like the reference
            disp = [os.path.join(root, "training/disp_occ_0/000085_10.png")
                    ] * len(left)
        self.image_list = list(zip(left, right))
        self.disparity_list = disp


class Middlebury(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/Middlebury",
                 split="F", seed=1234):
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_middlebury, seed=seed)
        assert split in ("F", "H", "Q"), split
        official = Path(os.path.join(
            root, "MiddEval3/official_train.txt")).read_text().splitlines()
        scenes = [os.path.basename(p) for p in
                  glob.glob(os.path.join(root, "MiddEval3/trainingF/*"))]
        scenes = sorted(s for s in scenes if s in official)
        base = os.path.join(root, "MiddEval3", f"training{split}")
        for name in scenes:
            self.image_list.append((os.path.join(base, name, "im0.png"),
                                    os.path.join(base, name, "im1.png")))
            self.disparity_list.append(
                os.path.join(base, name, "disp0GT.pfm"))
        assert len(self.image_list) > 0, (root, split)


DATASETS = {
    "sceneflow": SceneFlow,
    "eth3d": ETH3D,
    "sintel_stereo": SintelStereo,
    "falling_things": FallingThings,
    "tartan_air": TartanAir,
    "kitti": KITTI,
    "middlebury": Middlebury,
}


# ------------------------------------------------------------------ mixtures
def build_training_mixture(train_cfg, data_root: str = "datasets"
                           ) -> StereoDataset:
    """Assemble the training mixture from ``TrainConfig``
    (reference: core/stereo_datasets.py:277-309 ``fetch_dataloader``)."""
    aug_params = {
        "crop_size": tuple(train_cfg.image_size),
        "min_scale": train_cfg.spatial_scale[0],
        "max_scale": train_cfg.spatial_scale[1],
        "do_flip": train_cfg.do_flip,
        "yjitter": not train_cfg.noyjitter,
        # device_photometric moves ColorJitter into the jitted train step
        # (data/device_jitter.py); the host augmentor then skips it
        "photometric": not train_cfg.device_photometric,
    }
    if train_cfg.saturation_range is not None:
        aug_params["saturation_range"] = tuple(train_cfg.saturation_range)
    if train_cfg.img_gamma is not None:
        aug_params["gamma"] = tuple(train_cfg.img_gamma)

    seed = train_cfg.seed
    mixture = None
    for name in train_cfg.train_datasets:
        if re.fullmatch(r"middlebury_.*", name):
            ds = Middlebury(aug_params, root=os.path.join(data_root,
                                                          "Middlebury"),
                            split=name.removeprefix("middlebury_"), seed=seed)
        elif name == "sceneflow":
            # 4× clean + 4× final (reference: core/stereo_datasets.py:292-296)
            clean = SceneFlow(aug_params, root=data_root,
                              dstype="frames_cleanpass", seed=seed)
            final = SceneFlow(aug_params, root=data_root,
                              dstype="frames_finalpass", seed=seed)
            ds = (clean * 4) + (final * 4)
        elif "kitti" in name:
            ds = KITTI(aug_params, root=os.path.join(data_root, "KITTI"),
                       seed=seed)
        elif name == "sintel_stereo":
            ds = SintelStereo(aug_params,
                              root=os.path.join(data_root, "SintelStereo"),
                              seed=seed) * 140
        elif name == "falling_things":
            ds = FallingThings(aug_params,
                               root=os.path.join(data_root, "FallingThings"),
                               seed=seed) * 5
        elif name.startswith("tartan_air"):
            ds = TartanAir(aug_params, root=data_root,
                           keywords=name.split("_")[2:], seed=seed)
        else:
            raise ValueError(f"unknown training dataset {name!r}")
        log.info("Adding %d samples from %s", len(ds), name)
        mixture = ds if mixture is None else mixture + ds
    if mixture is None or len(mixture) == 0:
        raise ValueError(
            f"empty training mixture from {train_cfg.train_datasets} "
            f"under {data_root!r}")
    return mixture
