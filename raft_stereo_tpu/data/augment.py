"""Stereo training augmentation (host-side NumPy, framework-free).

Re-implements the reference's two augmentors (reference:
core/utils/augmentor.py:60-181 ``FlowAugmentor`` dense-GT path,
:184-316 ``SparseFlowAugmentor`` sparse-GT path) with one deliberate design
change: randomness comes from an explicit ``np.random.Generator`` passed per
call instead of process-global state, so a sample's augmentation is a pure
function of ``(seed, epoch, index)`` regardless of worker/thread scheduling.

Photometric jitter replicates torchvision ColorJitter semantics (brightness/
contrast/saturation blends, HSV hue shift, random op order) + gamma
adjustment, in uint8 NumPy.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

try:
    import cv2
    cv2.setNumThreads(0)
    cv2.ocl.setUseOpenCL(False)
except ImportError:  # pragma: no cover
    cv2 = None


# ----------------------------------------------------------- photometric ops
def _blend(a: np.ndarray, b, factor: float) -> np.ndarray:
    """``factor*a + (1-factor)*b`` clipped to uint8 — in-place fp32 ops (one
    temporary instead of four; the loader's per-sample cost is dominated by
    these full-frame blends, bench_loader.py)."""
    out = a.astype(np.float32)
    out *= np.float32(factor)
    bb = (1.0 - factor) * b
    if isinstance(bb, np.ndarray) or bb:  # brightness blends with 0: skip
        out += bb
    np.clip(out, 0, 255, out=out)
    return out.astype(np.uint8)


def adjust_brightness(img: np.ndarray, factor: float) -> np.ndarray:
    return _blend(img, np.float32(0.0), factor)


def adjust_contrast(img: np.ndarray, factor: float) -> np.ndarray:
    # mean(dtype=f32) accumulates uint8 in fp32 without materializing the
    # fp32 copy — same reduction order as .astype(f32).mean(-1).mean()
    gray_mean = img.mean(axis=-1, dtype=np.float32).mean(dtype=np.float32)
    return _blend(img, gray_mean, factor)


def adjust_saturation(img: np.ndarray, factor: float) -> np.ndarray:
    # ITU-R 601 luma, same weights torchvision uses for rgb_to_grayscale
    gray = (img.astype(np.float32) @ np.array([0.299, 0.587, 0.114],
                                              np.float32))[..., None]
    return _blend(img, gray, factor)


def adjust_hue(img: np.ndarray, shift: float) -> np.ndarray:
    """``shift`` in [-0.5, 0.5] turns of the hue circle."""
    if cv2 is None:  # pragma: no cover
        return img  # hue jitter needs cv2's HSV conversion; skip without it
    if int(round(shift * 180)) == 0:
        return img  # HSV round-trip is lossy on uint8 — skip the no-op
    hsv = cv2.cvtColor(img, cv2.COLOR_RGB2HSV)
    h = hsv[..., 0].astype(np.int32)  # OpenCV uint8 hue range: 0..179
    hsv[..., 0] = ((h + int(round(shift * 180))) % 180).astype(np.uint8)
    return cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB)


def adjust_gamma(img: np.ndarray, gamma: float, gain: float = 1.0) -> np.ndarray:
    x = img.astype(np.float32) / 255.0
    return np.clip(255.0 * gain * np.power(x, gamma), 0, 255).astype(np.uint8)


class ColorJitter:
    """torchvision-style jitter: factors drawn per call, ops in random order.

    ``brightness``/``contrast`` b give factors U[max(0,1-b), 1+b];
    ``saturation`` is an explicit (lo, hi) range; ``hue`` h gives a shift
    U[-h, h]; ``gamma`` is (gamma_min, gamma_max, gain_min, gain_max).
    """

    def __init__(self, brightness: float, contrast: float,
                 saturation: Tuple[float, float], hue: float,
                 gamma: Sequence[float] = (1, 1, 1, 1)):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue
        self.gamma = tuple(gamma)

    def __call__(self, img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        b = rng.uniform(max(0, 1 - self.brightness), 1 + self.brightness)
        c = rng.uniform(max(0, 1 - self.contrast), 1 + self.contrast)
        s = rng.uniform(*self.saturation)
        h = rng.uniform(-self.hue, self.hue)
        ops = [lambda x: adjust_brightness(x, b),
               lambda x: adjust_contrast(x, c),
               lambda x: adjust_saturation(x, s),
               lambda x: adjust_hue(x, h)]
        for i in rng.permutation(4):
            img = ops[i](img)
        gmin, gmax, gainmin, gainmax = self.gamma
        if (gmin, gmax, gainmin, gainmax) != (1, 1, 1, 1):
            img = adjust_gamma(img, rng.uniform(gmin, gmax),
                               rng.uniform(gainmin, gainmax))
        return img


# ------------------------------------------------------------ shared pieces
def _eraser(img2: np.ndarray, rng: np.random.Generator,
            prob: float = 0.5, bounds=(50, 100)) -> np.ndarray:
    """Occlusion augmentation: paint 1-2 random rectangles of img2 with its
    mean color (reference: core/utils/augmentor.py:98-111)."""
    ht, wd = img2.shape[:2]
    if rng.random() < prob:
        img2 = img2.copy()
        mean_color = img2.reshape(-1, 3).mean(axis=0)
        for _ in range(rng.integers(1, 3)):
            x0 = rng.integers(0, wd)
            y0 = rng.integers(0, ht)
            dx = rng.integers(bounds[0], bounds[1])
            dy = rng.integers(bounds[0], bounds[1])
            img2[y0:y0 + dy, x0:x0 + dx, :] = mean_color
    return img2


def _resize(img: np.ndarray, fx: float, fy: float,
            is_flow: bool = False) -> np.ndarray:
    out = cv2.resize(img, None, fx=fx, fy=fy,
                     interpolation=cv2.INTER_LINEAR)
    if is_flow:
        out = out * np.array([fx, fy], np.float32)
    return out


def _stereo_flips(img1, img2, flow, do_flip: Optional[str],
                  rng: np.random.Generator,
                  h_flip_prob=0.5, v_flip_prob=0.1):
    """The reference's three flip modes (core/utils/augmentor.py:137-151):
    'hf' plain h-flip (unreachable from its CLI), 'h' the stereo-correct
    swap-and-mirror, 'v' vertical."""
    if do_flip == "hf" and rng.random() < h_flip_prob:
        img1 = img1[:, ::-1]
        img2 = img2[:, ::-1]
        flow = flow[:, ::-1] * [-1.0, 1.0]
    if do_flip == "h" and rng.random() < h_flip_prob:
        img1, img2 = img2[:, ::-1], img1[:, ::-1]
    if do_flip == "v" and rng.random() < v_flip_prob:
        img1 = img1[::-1, :]
        img2 = img2[::-1, :]
        flow = flow[::-1, :] * [1.0, -1.0]
    return img1, img2, flow


# ---------------------------------------------------------- dense augmentor
class DenseAugmentor:
    """Augmentation for datasets with dense GT (SceneFlow/FallingThings/
    TartanAir).  Reference: core/utils/augmentor.py:60-181."""

    def __init__(self, crop_size: Tuple[int, int], min_scale=-0.2,
                 max_scale=0.5, do_flip: Optional[str] = None, yjitter=False,
                 saturation_range=(0.6, 1.4), gamma=(1, 1, 1, 1),
                 photometric=True):
        self.crop_size = tuple(crop_size)
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.do_flip = do_flip
        self.yjitter = yjitter
        self.stretch_prob = 0.8
        self.max_stretch = 0.2
        self.asymmetric_prob = 0.2
        # photometric=False: jitter runs on-device inside the train step
        # instead (data/device_jitter.py; TrainConfig.device_photometric)
        self.photometric = photometric
        self.jitter = ColorJitter(0.4, 0.4, saturation_range, 0.5 / 3.14,
                                  gamma)

    def _color(self, img1, img2, rng):
        if rng.random() < self.asymmetric_prob:
            return self.jitter(img1, rng), self.jitter(img2, rng)
        # symmetric: identical factors for both views — jitter the stacked
        # pair once (reference: core/utils/augmentor.py:89-93)
        stack = np.concatenate([img1, img2], axis=0)
        stack = self.jitter(stack, rng)
        return np.split(stack, 2, axis=0)

    def _spatial(self, img1, img2, flow, rng):
        ch, cw = self.crop_size
        ht, wd = img1.shape[:2]
        # floor keeps the post-resize image croppable with >=8px slack
        min_scale = max((ch + 8) / ht, (cw + 8) / wd)
        scale = 2.0 ** rng.uniform(self.min_scale, self.max_scale)
        sx = sy = scale
        if rng.random() < self.stretch_prob:
            sx *= 2.0 ** rng.uniform(-self.max_stretch, self.max_stretch)
            sy *= 2.0 ** rng.uniform(-self.max_stretch, self.max_stretch)
        sx = max(sx, min_scale)
        sy = max(sy, min_scale)
        img1 = _resize(img1, sx, sy)
        img2 = _resize(img2, sx, sy)
        flow = _resize(flow, sx, sy, is_flow=True)

        img1, img2, flow = _stereo_flips(img1, img2, flow, self.do_flip, rng)

        if self.yjitter:
            # crop img2 with ±2px vertical offset, simulating imperfect
            # rectification (reference: core/utils/augmentor.py:153-160)
            y0 = int(rng.integers(2, img1.shape[0] - ch - 2))
            x0 = int(rng.integers(2, img1.shape[1] - cw - 2))
            y1 = y0 + int(rng.integers(-2, 3))
            img1 = img1[y0:y0 + ch, x0:x0 + cw]
            img2 = img2[y1:y1 + ch, x0:x0 + cw]
            flow = flow[y0:y0 + ch, x0:x0 + cw]
        else:
            y0 = int(rng.integers(0, img1.shape[0] - ch))
            x0 = int(rng.integers(0, img1.shape[1] - cw))
            img1 = img1[y0:y0 + ch, x0:x0 + cw]
            img2 = img2[y0:y0 + ch, x0:x0 + cw]
            flow = flow[y0:y0 + ch, x0:x0 + cw]
        return img1, img2, flow

    def __call__(self, img1: np.ndarray, img2: np.ndarray, flow: np.ndarray,
                 rng: np.random.Generator):
        """uint8 (H,W,3) ×2 + float32 (H,W,2) flow → cropped/augmented."""
        if self.photometric:
            img1, img2 = self._color(img1, img2, rng)
        img2 = _eraser(img2, rng)
        img1, img2, flow = self._spatial(img1, img2, flow, rng)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow))


# --------------------------------------------------------- sparse augmentor
class SparseAugmentor:
    """Augmentation for sparse GT (KITTI/ETH3D/Middlebury/Sintel): flow must
    be scattered, not interpolated, when resizing.
    Reference: core/utils/augmentor.py:184-316."""

    def __init__(self, crop_size: Tuple[int, int], min_scale=-0.2,
                 max_scale=0.5, do_flip: Optional[str] = None, yjitter=False,
                 saturation_range=(0.7, 1.3), gamma=(1, 1, 1, 1),
                 photometric=True):
        self.crop_size = tuple(crop_size)
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.do_flip = do_flip
        # yjitter accepted-but-unused, like the reference (:184 signature)
        self.spatial_aug_prob = 0.8
        self.photometric = photometric
        self.jitter = ColorJitter(0.3, 0.3, saturation_range, 0.3 / 3.14,
                                  gamma)

    @staticmethod
    def resize_sparse_flow(flow: np.ndarray, valid: np.ndarray,
                           fx: float, fy: float):
        """Scatter valid flow vectors into the scaled grid (rounded target
        pixels), instead of bilinear interpolation which would smear valid
        and invalid values together (reference: core/utils/augmentor.py:223-255).
        """
        ht, wd = flow.shape[:2]
        ht1 = int(round(ht * fy))
        wd1 = int(round(wd * fx))

        yy0, xx0 = np.nonzero(valid >= 1)
        flow0 = flow[yy0, xx0] * np.array([fx, fy], np.float32)
        xx = np.round(xx0 * fx).astype(np.int32)
        yy = np.round(yy0 * fy).astype(np.int32)
        keep = (xx > 0) & (xx < wd1) & (yy > 0) & (yy < ht1)

        flow_img = np.zeros((ht1, wd1, 2), np.float32)
        valid_img = np.zeros((ht1, wd1), np.int32)
        flow_img[yy[keep], xx[keep]] = flow0[keep]
        valid_img[yy[keep], xx[keep]] = 1
        return flow_img, valid_img

    def _spatial(self, img1, img2, flow, valid, rng):
        ch, cw = self.crop_size
        ht, wd = img1.shape[:2]
        min_scale = max((ch + 1) / ht, (cw + 1) / wd)
        scale = max(2.0 ** rng.uniform(self.min_scale, self.max_scale),
                    min_scale)
        if rng.random() < self.spatial_aug_prob:
            img1 = _resize(img1, scale, scale)
            img2 = _resize(img2, scale, scale)
            flow, valid = self.resize_sparse_flow(flow, valid, scale, scale)

        img1, img2, flow = _stereo_flips(img1, img2, flow, self.do_flip, rng)

        # crop with margins so near-border crops are reachable
        # (reference: core/utils/augmentor.py:291-303)
        margin_y, margin_x = 20, 50
        y0 = int(rng.integers(0, img1.shape[0] - ch + margin_y))
        x0 = int(rng.integers(-margin_x, img1.shape[1] - cw + margin_x))
        y0 = int(np.clip(y0, 0, img1.shape[0] - ch))
        x0 = int(np.clip(x0, 0, img1.shape[1] - cw))
        img1 = img1[y0:y0 + ch, x0:x0 + cw]
        img2 = img2[y0:y0 + ch, x0:x0 + cw]
        flow = flow[y0:y0 + ch, x0:x0 + cw]
        valid = valid[y0:y0 + ch, x0:x0 + cw]
        return img1, img2, flow, valid

    def __call__(self, img1, img2, flow, valid, rng: np.random.Generator):
        if self.photometric:
            stack = np.concatenate([img1, img2], axis=0)
            stack = self.jitter(stack, rng)
            img1, img2 = np.split(stack, 2, axis=0)
        img2 = _eraser(img2, rng)
        img1, img2, flow, valid = self._spatial(img1, img2, flow, valid, rng)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow), np.ascontiguousarray(valid))
