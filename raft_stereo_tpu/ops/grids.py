"""Coordinate grids.

The reference carries a full 2-channel (x, y) coordinate grid and then zeroes
the y component of every update (reference: core/raft_stereo.py:46-53,120,
core/utils/utils.py:76-79).  Stereo disparity is 1-D, so we carry only the x
channel; the y channel is materialized as zeros exactly where a 2-channel
tensor is needed for checkpoint compatibility (motion encoder input).
"""

from __future__ import annotations

import jax.numpy as jnp


def coords_grid_x(batch: int, ht: int, wd: int, dtype=jnp.float32) -> jnp.ndarray:
    """x-coordinate grid of shape (batch, ht, wd)."""
    x = jnp.arange(wd, dtype=dtype)
    return jnp.broadcast_to(x[None, None, :], (batch, ht, wd))
