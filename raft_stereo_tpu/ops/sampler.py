"""1-D linear sampling along the disparity (W2) axis.

TPU-native replacement for the reference's ``grid_sample``-based
``bilinear_sampler`` (reference: core/utils/utils.py:59-73), specialized to the
stereo case the reference asserts anyway (H == 1 rows): sampling is linear
interpolation along the last axis with zero padding outside ``[0, W-1]`` and
``align_corners=True`` pixel-coordinate semantics.

Implemented as two clipped ``take_along_axis`` gathers + a lerp.  A fused
Pallas kernel (kernels/corr_lookup.py) provides the high-performance path; this
XLA version is the correctness reference.
"""

from __future__ import annotations

import jax.numpy as jnp


def linear_sampler_1d(vol: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Linearly sample ``vol`` along its last axis at positions ``x``.

    Args:
      vol: (..., W) values.
      x:   (..., K) sample positions in pixel coordinates; leading dims must
           broadcast against ``vol``'s leading dims.

    Returns:
      (..., K) sampled values, zero for taps outside ``[0, W-1]``.
    """
    w = vol.shape[-1]
    x0 = jnp.floor(x)
    frac = (x - x0).astype(vol.dtype)
    x0i = x0.astype(jnp.int32)
    x1i = x0i + 1

    def tap(idx):
        valid = (idx >= 0) & (idx <= w - 1)
        safe = jnp.clip(idx, 0, w - 1)
        v = jnp.take_along_axis(
            jnp.broadcast_to(vol, x.shape[:-1] + (w,)), safe, axis=-1)
        return jnp.where(valid, v, jnp.zeros_like(v))

    return tap(x0i) * (1.0 - frac) + tap(x1i) * frac


def linear_sampler_1d_features(fmap: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Vector-valued variant of :func:`linear_sampler_1d`: sample a feature
    map along its W axis.

    Same boundary semantics (zero padding outside ``[0, W-1]``,
    ``align_corners=True`` pixel coordinates) — keep the two in sync; the
    cross-backend tests in tests/test_corr.py assert they agree.

    Implemented with a direct ``take_along_axis`` on the W axis (rather than
    delegating to :func:`linear_sampler_1d`) so the (B,H,W1,K,D) result is
    gathered without materializing a (B,H,W1,D,W2) broadcast.

    Args:
      fmap: (B, H, W, D) features.
      x:    (B, H, W1, K) sample positions in pixels.

    Returns:
      (B, H, W1, K, D) sampled feature vectors.
    """
    b, h, w1, k = x.shape
    w = fmap.shape[2]
    x0 = jnp.floor(x)
    frac = (x - x0).astype(fmap.dtype)[..., None]
    x0i = x0.astype(jnp.int32).reshape(b, h, w1 * k)
    x1i = x0i + 1

    def tap(idx):
        valid = (idx >= 0) & (idx <= w - 1)
        safe = jnp.clip(idx, 0, w - 1)
        v = jnp.take_along_axis(fmap, safe[..., None], axis=2)
        return jnp.where(valid[..., None], v, jnp.zeros_like(v))

    out = tap(x0i).reshape(b, h, w1, k, -1) * (1.0 - frac) \
        + tap(x1i).reshape(b, h, w1, k, -1) * frac
    return out
