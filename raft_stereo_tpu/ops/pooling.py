"""Average pooling with the reference's exact divisor semantics.

``F.avg_pool2d`` defaults to ``count_include_pad=True`` — the divisor is always
the full window size even at padded borders (reference: core/update.py:87-91
``pool2x``/``pool4x``; core/corr.py:124 pyramid pooling, unpadded).  We use
``lax.reduce_window`` sums divided by the static window size.
"""

from __future__ import annotations

import jax.lax as lax
import jax.numpy as jnp
import numpy as np


def avg_pool2d(x: jnp.ndarray, window, strides, padding) -> jnp.ndarray:
    """NHWC average pool; ``padding`` is ((top,bottom),(left,right)).

    Divisor is the full window size (torch ``count_include_pad=True``).
    """
    wh, ww = window
    sums = lax.reduce_window(
        x, np.array(0, x.dtype), lax.add,
        window_dimensions=(1, wh, ww, 1),
        window_strides=(1, strides[0], strides[1], 1),
        padding=((0, 0), tuple(padding[0]), tuple(padding[1]), (0, 0)),
    )
    return sums / jnp.array(wh * ww, x.dtype)


def pool2x(x: jnp.ndarray) -> jnp.ndarray:
    """3×3 stride-2 pad-1 average pool (reference: core/update.py:87-88).

    The reference also defines ``pool4x`` (core/update.py:90-91) but never
    calls it — dead code, not rebuilt (SURVEY.md §2 policy)."""
    return avg_pool2d(x, (3, 3), (2, 2), ((1, 1), (1, 1)))
