from raft_stereo_tpu.ops.grids import coords_grid_x
from raft_stereo_tpu.ops.sampler import linear_sampler_1d, linear_sampler_1d_features
from raft_stereo_tpu.ops.resize import resize_bilinear_align_corners, interp_like, upsample_flow_bilinear
from raft_stereo_tpu.ops.pooling import avg_pool2d, pool2x
from raft_stereo_tpu.ops.upsample import convex_upsample
from raft_stereo_tpu.ops.padding import InputPadder

__all__ = [
    "coords_grid_x", "linear_sampler_1d", "linear_sampler_1d_features",
    "resize_bilinear_align_corners", "interp_like", "upsample_flow_bilinear",
    "avg_pool2d", "pool2x", "convex_upsample", "InputPadder",
]
