"""Bilinear resize with ``align_corners=True`` semantics.

The reference uses ``F.interpolate(..., mode='bilinear', align_corners=True)``
for cross-resolution GRU coupling (reference: core/update.py:93-95) and the
no-mask flow upsampling fallback (core/utils/utils.py:82-84).  ``jax.image``
has no align_corners mode, so we build the (dense, tiny) interpolation weight
matrices and apply them as two matmuls — which also happens to be the
MXU-friendly formulation on TPU.
"""

from __future__ import annotations

import functools

import jax.lax as lax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=128)
def _interp_matrix(src: int, dst: int) -> np.ndarray:
    """(dst, src) align-corners bilinear interpolation matrix (float32)."""
    m = np.zeros((dst, src), dtype=np.float32)
    if dst == 1:
        m[0, 0] = 1.0
        return m
    scale = (src - 1) / (dst - 1)
    pos = np.arange(dst) * scale
    lo = np.clip(np.floor(pos).astype(np.int64), 0, src - 1)
    hi = np.clip(lo + 1, 0, src - 1)
    frac = (pos - lo).astype(np.float32)
    m[np.arange(dst), lo] += 1.0 - frac
    m[np.arange(dst), hi] += frac
    return m


def resize_bilinear_align_corners(x: jnp.ndarray, out_hw) -> jnp.ndarray:
    """Resize NHWC ``x`` to spatial size ``out_hw`` (align-corners bilinear)."""
    h, w = x.shape[1], x.shape[2]
    oh, ow = int(out_hw[0]), int(out_hw[1])
    if (h, w) == (oh, ow):
        return x
    dtype = x.dtype
    if h != oh:
        my = jnp.asarray(_interp_matrix(h, oh), dtype=dtype)
        x = jnp.einsum("bhwc,oh->bowc", x, my, precision=lax.Precision.HIGHEST)
    if w != ow:
        mx = jnp.asarray(_interp_matrix(w, ow), dtype=dtype)
        x = jnp.einsum("bhwc,ow->bhoc", x, mx, precision=lax.Precision.HIGHEST)
    return x


def interp_like(x: jnp.ndarray, dest: jnp.ndarray) -> jnp.ndarray:
    """Resize ``x`` to ``dest``'s spatial size (reference: core/update.py:93-95)."""
    return resize_bilinear_align_corners(x, dest.shape[1:3])


def upsample_flow_bilinear(flow: jnp.ndarray, factor: int) -> jnp.ndarray:
    """×factor bilinear flow upsample, scaling values by ``factor``
    (reference: core/utils/utils.py:82-84)."""
    h, w = flow.shape[1], flow.shape[2]
    return factor * resize_bilinear_align_corners(flow, (factor * h, factor * w))
