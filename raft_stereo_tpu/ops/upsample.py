"""Convex (mask-weighted) flow upsampling.

Reference: core/raft_stereo.py:55-67 — softmax over a 9-way mask per output
subpixel, combining a 3×3 neighborhood of the coarse flow scaled by the
upsample factor.  The reference's ``F.unfold`` + view/permute dance becomes a
shift-stack + einsum in NHWC.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _neighborhood3x3(x: jnp.ndarray) -> jnp.ndarray:
    """(B,H,W,C) → (B,H,W,9,C): zero-padded 3×3 neighborhoods.

    Tap order matches ``F.unfold([3,3], padding=1)``: k = ky*3 + kx with
    (ky, kx) offsets in row-major order over {-1,0,1}².
    """
    b, h, w, c = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    taps = [xp[:, ky:ky + h, kx:kx + w, :] for ky in range(3) for kx in range(3)]
    return jnp.stack(taps, axis=3)


def convex_upsample(flow: jnp.ndarray, mask: jnp.ndarray, factor: int) -> jnp.ndarray:
    """Upsample (B,H,W,C) flow to (B,H*factor,W*factor,C) via convex combination.

    Args:
      flow: coarse flow field, NHWC.
      mask: (B,H,W,9*factor*factor) raw mask logits; channel layout
            c = k*factor² + iy*factor + ix (reference: core/raft_stereo.py:59).
      factor: integer upsample factor (2**n_downsample).

    Flow VALUES are scaled by ``factor`` (disparity is measured in pixels of
    the output resolution — reference: core/raft_stereo.py:62).
    """
    b, h, w, c = flow.shape
    f = factor
    m = mask.reshape(b, h, w, 9, f, f)
    m = jax.nn.softmax(m, axis=3)
    taps = _neighborhood3x3(flow * f)                      # (B,H,W,9,C)
    up = jnp.einsum("bhwkyx,bhwkc->bhwyxc", m, taps)       # (B,H,W,f,f,C)
    up = up.transpose(0, 1, 3, 2, 4, 5)                    # (B,H,f,W,f,C)
    return up.reshape(b, h * f, w * f, c)
