"""Pad inputs to a divisibility constraint and exactly un-pad outputs.

Reference: core/utils/utils.py:7-26 (``InputPadder``) — replicate-mode padding,
'sintel' (symmetric) vs default (bottom/right-biased) layouts, eval uses
``divis_by=32`` (evaluate_stereo.py:31).  NHWC here.

Note the reference's formula pads to the NEXT multiple when already divisible
is false; ``(((d // k) + 1) * k - d) % k`` is 0 when d is divisible by k.
"""

from __future__ import annotations

import jax.numpy as jnp


class InputPadder:
    def __init__(self, dims, mode: str = "sintel", divis_by: int = 8):
        self.ht, self.wd = int(dims[-3]), int(dims[-2])  # NHWC
        pad_ht = (((self.ht // divis_by) + 1) * divis_by - self.ht) % divis_by
        pad_wd = (((self.wd // divis_by) + 1) * divis_by - self.wd) % divis_by
        if mode == "sintel":
            # (left, right, top, bottom)
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2,
                         pad_ht // 2, pad_ht - pad_ht // 2]
        else:
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht]

    @property
    def pads(self):
        """(left, right, top, bottom) pad amounts — for callers that pad
        host-side (eval/runner.py) with the same layout semantics."""
        return tuple(self._pad)

    def pad(self, *inputs):
        out = []
        for x in inputs:
            assert x.ndim == 4, "expected NHWC"
            out.append(jnp.pad(
                x,
                ((0, 0), (self._pad[2], self._pad[3]),
                 (self._pad[0], self._pad[1]), (0, 0)),
                mode="edge"))
        return out

    def unpad(self, x):
        """Exactly undo ``pad``.  Accepts NHWC (B,H,W,C) or the model's 3-D
        disparity outputs (B,H,W)."""
        assert x.ndim in (3, 4), "expected (B,H,W[,C])"
        ht, wd = x.shape[1], x.shape[2]
        return x[:, self._pad[2]:ht - self._pad[3],
                 self._pad[0]:wd - self._pad[1]]
