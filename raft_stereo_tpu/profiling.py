"""Profiling & timing subsystem.

The reference has no profiler — only ad-hoc wall-clock timing inside its
KITTI validator with a 50-image warmup discard (reference:
evaluate_stereo.py:77-82,105-107).  This module makes both first-class:

* ``trace(log_dir)`` — XLA/TPU profiler traces viewable in TensorBoard or
  Perfetto (``jax.profiler``), covering device kernels, HBM transfers, and
  host dispatch.
* ``annotate(name)`` — named host spans that show up inside traces; wrap
  pipeline stages (decode, augment, device step) to see overlap.
* ``FpsProtocol`` — the reference's FPS measurement protocol (warmup
  discard, per-image wall time) plus a dispatch-robust *chained* variant
  for devices behind an async tunnel, where per-call host timing lies:
  K forwards are chained on device inside ``lax.fori_loop`` and two chain
  lengths are differenced to cancel dispatch/round-trip overhead
  (the method bench.py uses).
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from dataclasses import dataclass
from typing import Callable, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@contextlib.contextmanager
def trace(log_dir: str = "profiles", host_tracer_level: int = 2):
    """Capture a profiler trace into ``log_dir`` for the duration of the
    block (TensorBoard ``profile`` plugin or Perfetto reads it)."""
    os.makedirs(log_dir, exist_ok=True)
    if hasattr(jax.profiler, "ProfileOptions"):
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(log_dir, profiler_options=options)
    else:  # older jax without per-trace options
        jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named span context; nests.

    Two effects, one name: a host-timeline span (``TraceAnnotation``) for
    code that RUNS inside the block, and — because model code is traced,
    not run — an XLA op-name scope (``jax.named_scope``) so every op staged
    out inside the block carries ``name/`` in its metadata.  Device traces
    then break out the same phases the bench reports: the model wraps
    ``fnet``/``cnet``/``corr_pyramid``/``gru_iter``/``upsample``
    (models/raft_stereo.py) and bench.py's ``realtime_phase_split`` line
    reports encoder-vs-GRU wall time."""
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


def device_memory_stats(device: Optional[jax.Device] = None) -> dict:
    """Live/peak bytes on ``device`` (default: first device); {} if the
    backend doesn't report memory stats (e.g. CPU)."""
    d = device or jax.devices()[0]
    stats = getattr(d, "memory_stats", lambda: None)()
    return dict(stats) if stats else {}


def device_hbm_bytes(fallback: int = 16 * 2 ** 30) -> int:
    """Accelerator memory capacity; ``fallback`` when the backend doesn't
    report one (CPU test runs).  Basis for the memory-derived full-res
    gates (models/raft_stereo.sequential_fnet_threshold,
    models/banded.default_band_rows)."""
    try:
        limit = int(device_memory_stats().get("bytes_limit", 0))
    except Exception:  # pragma: no cover - backend without device queries
        limit = 0
    return limit if limit > 0 else fallback


@dataclass
class FpsResult:
    fps: float
    mean_s: float
    per_image_s: List[float]
    n_timed: int

    def __str__(self):
        return f"{self.fps:.2f} fps (mean {self.mean_s * 1e3:.2f} ms over " \
               f"{self.n_timed} images)"


class FpsProtocol:
    """The reference's KITTI FPS protocol (evaluate_stereo.py:77-82,105-107):
    run ``fn`` per image, discard the first ``warmup`` timings (absorbing
    XLA compilation the way the reference absorbs cuDNN autotune), report
    1/mean of the rest."""

    def __init__(self, warmup: int = 50):
        self.warmup = warmup

    def measure(self, fn: Callable[..., object],
                inputs: Iterable[Tuple]) -> FpsResult:
        times: List[float] = []
        n = 0
        for args in inputs:
            t0 = time.perf_counter()
            out = fn(*args)
            # A REAL device->host transfer is the only honest stop clock on
            # this hardware: jax.block_until_ready returns at DISPATCH
            # behind the async device tunnel (measured, bench.py:9-14).
            # device_get is a no-op on the NumPy outputs of already-honest
            # callables (e.g. eval.runner.InferenceRunner).
            jax.device_get(out)
            elapsed = time.perf_counter() - t0
            n += 1
            if n > self.warmup:
                times.append(elapsed)
        if not times:
            raise ValueError(
                f"need more than warmup={self.warmup} inputs, got {n}")
        mean = float(np.mean(times))
        return FpsResult(fps=1.0 / mean, mean_s=mean, per_image_s=times,
                         n_timed=len(times))


def make_forward_chain(apply_fn: Callable, variables, img1, img2):
    """The standard on-device forward chain for ``chained_seconds_per_call``:
    K calls of ``apply_fn(variables, image1, image2)`` inside a jitted
    ``fori_loop`` (inputs perturbed per iteration so XLA can't fold the
    loop), synced by a scalar ``float()`` fetch.  The one canonical copy of
    this scaffolding, used by bench.py / bench_product.py /
    tools/inference_profile.py (bench_fullres.py and tools/fullres_gates.py
    keep inline chains because the same compiled program doubles as their
    ``memory_analysis`` subject) — see ``chained_seconds_per_call`` for the
    timing pitfalls it guards against."""

    @functools.partial(jax.jit, static_argnums=(3,))
    def chain(variables, a, b, k):
        def body(i, acc):
            out = apply_fn(variables, a + i * 1e-6, b)
            return acc + jnp.mean(out)
        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    return lambda k: (lambda: float(chain(variables, img1, img2, k)))


def chained_seconds_per_call(make_chain: Callable[[int], Callable[[], object]],
                             k_lo: int = 3, k_hi: int = 23,
                             repeats: int = 3,
                             reduce: Callable = np.median) -> float:
    """Dispatch-robust per-call device time.

    ``make_chain(k)`` must return a zero-arg callable that runs ``k``
    device-chained iterations and blocks until a scalar is ready.  The
    difference ``(t(k_hi) - t(k_lo)) / (k_hi - k_lo)`` cancels constant
    dispatch/round-trip overhead — use when the device sits behind an async
    tunnel where ``block_until_ready`` returns at dispatch (see bench.py).
    ``reduce`` combines the per-repeat estimates; the default ``median``
    tolerates an outlier repeat.  Note ``min`` is the WRONG choice for this
    difference estimator: a spike during a k_lo run biases that repeat's
    difference low (possibly negative), and min would select exactly the
    corrupted repeat.
    """
    chains = {k: make_chain(k) for k in (k_lo, k_hi)}
    for k in (k_lo, k_hi):  # compile both
        chains[k]()
    estimates = []
    for _ in range(repeats):
        ts = {}
        for k in (k_lo, k_hi):
            t0 = time.perf_counter()
            chains[k]()
            ts[k] = time.perf_counter() - t0
        estimates.append((ts[k_hi] - ts[k_lo]) / (k_hi - k_lo))
    per_call = float(reduce(estimates))
    if per_call <= 0:
        raise RuntimeError(
            f"non-positive per-call estimate {per_call!r}: timing noise "
            f"exceeded the chained workload; raise k_hi or repeats")
    return per_call
