"""Deterministic fault injection for the serving engine.

A resilience layer is only real if it can be proven under failure, and
failures on demand must be (a) representative of what production devices
actually do and (b) reproducible, or a flaky chaos test erodes exactly the
confidence it was built to create.  This module injects the four failure
modes the engine's supervised-recovery path (serving/engine.py) handles:

* **worker crash mid-dispatch** — an exception between batch pickup and
  result delivery, the generic "XLA runtime died / plugin segfault
  surfaced as a Python error" case;
* **device RESOURCE_EXHAUSTED** — the allocator-failure flavor of the
  same (TPU HBM OOM arrives as an ``XlaRuntimeError`` whose message
  starts with ``RESOURCE_EXHAUSTED``);
* **added dispatch latency** — a slow device (thermal throttle, a noisy
  neighbor on the host) that should trip deadline triage and the
  brownout signals, not the crash path;
* **compile failure** — ``jit(...).lower().compile()`` raising, the
  failure class a persistent-cache restore or an XLA upgrade can hit.

Round 16 adds the REPLICA-level failure modes the fleet router
(serving/fleet/) must survive — the unit of failure is now the whole
process, not a worker thread:

* **process death mid-dispatch** (``die_after_dispatches``) — the
  deterministic kill -9: ``os._exit(137)`` when the Nth dispatch begins,
  in-flight requests and open sockets and all;
* **health-check blackhole** (``healthz_blackhole_after_s``) — /healthz
  and /readyz stop answering while the request path keeps working, the
  "zombie to the load balancer" mode a probe TIMEOUT must catch;
* **slow start** (``slow_start_s``) — the readiness gate held closed
  after boot, pinning that the router keeps a warming replica out of
  rotation.

Determinism: every injection decision is a pure function of
``(seed, site, worker, per-site call index)`` via SHA-256 — independent
of thread interleaving, platform hash seeds, and wall clock.  Two runs
with the same seed and the same per-worker dispatch sequence inject the
same faults, which is what lets scripts/chaos_smoke.py assert exact
recovery behavior in CI.

Zero-overhead contract: chaos is OFF unless a ``ChaosConfig`` is set on
``ServeConfig.chaos``.  The engine holds ``None`` then, and every
injection site is a single attribute check — the dispatch path compiles
the same programs and produces bitwise-identical results
(tests/test_resilience.py pins this against the solo runner).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = ["ChaosConfig", "ChaosInjector", "InjectedFault",
           "InjectedWorkerCrash", "InjectedResourceExhausted",
           "InjectedCompileFailure", "parse_chaos_spec"]


class InjectedFault(RuntimeError):
    """Base of every injected failure — the recovery path treats these
    exactly like real faults (that is the point), but tests and the smoke
    harness can still tell injected from organic."""


class InjectedWorkerCrash(InjectedFault):
    """Injected worker exception mid-dispatch."""


class InjectedResourceExhausted(InjectedFault):
    """Injected device allocator failure.  The message mirrors the real
    ``XlaRuntimeError: RESOURCE_EXHAUSTED: ...`` prefix so log-scrapers
    exercised under chaos match production strings."""

    def __init__(self, detail: str = ""):
        super().__init__(f"RESOURCE_EXHAUSTED: injected device OOM{detail}")


class InjectedCompileFailure(InjectedFault):
    """Injected XLA compile failure (lower/compile raising)."""


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Fault-injection knobs (``ServeConfig.chaos``; None = off).

    Rates are per-decision probabilities in [0, 1]: ``crash_rate`` and
    ``resource_exhausted_rate`` per dispatch, ``compile_failure_rate``
    per executable build, ``latency_rate`` per dispatch (adding
    ``latency_ms`` of host-side stall).  ``devices`` restricts injection
    to those worker indices (empty = all workers) — a one-element tuple
    is the "flapping device" scenario the circuit breaker is tested
    against.  ``max_faults`` caps TOTAL injected faults (latency
    excluded), after which the injector goes quiet: a deterministic
    "device recovers" story for half-open probe tests.
    """

    seed: int = 0
    crash_rate: float = 0.0
    resource_exhausted_rate: float = 0.0
    compile_failure_rate: float = 0.0
    latency_rate: float = 0.0
    latency_ms: float = 0.0
    devices: Tuple[int, ...] = ()
    max_faults: Optional[int] = None
    # ---- Replica-level faults (round 16; the fleet failover story) ----
    # Hard-kill the WHOLE PROCESS (os._exit(137), the kill -9 exit code)
    # when the engine's Nth dispatch begins: the replica dies
    # mid-dispatch with requests in flight, sockets open, and no
    # goodbye — exactly what the fleet router must survive
    # (scripts/fleet_smoke.py).  Deterministic by construction: the Nth
    # dispatch, not a probability.
    die_after_dispatches: Optional[int] = None
    # Health-check blackhole: after this many seconds of process
    # lifetime, /healthz and /readyz stop answering (connection closed
    # with no response) while the request path keeps working — the
    # "zombie to the load balancer" failure the router's probe timeout
    # must classify as dead.  0 = off.
    healthz_blackhole_after_s: float = 0.0
    # Slow start: the readiness gate stays closed for this many seconds
    # after boot even once the warm ladder is compiled — models a
    # replica fetching artifacts / weights slowly, so failover tests can
    # pin that the router keeps it out of rotation until /readyz opens.
    slow_start_s: float = 0.0

    def __post_init__(self):
        for f in ("crash_rate", "resource_exhausted_rate",
                  "compile_failure_rate", "latency_rate"):
            v = getattr(self, f)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{f}={v} must be in [0, 1]")
        if self.latency_ms < 0:
            raise ValueError(f"latency_ms={self.latency_ms} must be >= 0")
        if self.max_faults is not None and self.max_faults < 0:
            raise ValueError(f"max_faults={self.max_faults} must be >= 0")
        if (self.die_after_dispatches is not None
                and self.die_after_dispatches < 1):
            raise ValueError(f"die_after_dispatches="
                             f"{self.die_after_dispatches} must be >= 1")
        if self.healthz_blackhole_after_s < 0:
            raise ValueError(f"healthz_blackhole_after_s="
                             f"{self.healthz_blackhole_after_s} must be "
                             f">= 0")
        if self.slow_start_s < 0:
            raise ValueError(f"slow_start_s={self.slow_start_s} must be "
                             f">= 0")

    @property
    def enabled(self) -> bool:
        return (any(getattr(self, f) > 0
                    for f in ("crash_rate", "resource_exhausted_rate",
                              "compile_failure_rate", "latency_rate",
                              "healthz_blackhole_after_s",
                              "slow_start_s"))
                or self.die_after_dispatches is not None)


def _fraction(seed: int, site: str, worker: int, n: int) -> float:
    """Uniform [0, 1) from the decision coordinates — SHA-256 so the
    stream is identical across processes, platforms, and PYTHONHASHSEED."""
    digest = hashlib.sha256(
        f"{seed}:{site}:{worker}:{n}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2 ** 64


class ChaosInjector:
    """The engine-side injector: one per engine, shared by all workers.

    Each injection site draws from its own deterministic per-(site,
    worker) counter stream, so worker 0's fault sequence does not depend
    on how the scheduler interleaved worker 1's dispatches.  ``observe``
    (optional) is called with the fault kind on every injection — the
    engine wires the ``serve_chaos_injected_total{kind=...}`` counter
    family there.
    """

    def __init__(self, cfg: ChaosConfig, observe=None,
                 sleep=time.sleep, clock=time.monotonic,
                 exit_fn=None):
        self.cfg = cfg
        self.observe = observe
        self._sleep = sleep
        self._clock = clock
        self._t0 = clock()
        # os._exit bypasses atexit/finally on purpose: die_after is the
        # kill -9 simulation, and a graceful unwind would be a different
        # (gentler) failure mode than the one under test.  Injectable for
        # the unit tests.
        self._exit = exit_fn if exit_fn is not None else os._exit
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, int], int] = {}
        self._dispatches = 0
        self.faults_injected = 0

    def _roll(self, site: str, worker: int) -> float:
        with self._lock:
            n = self._counts.get((site, worker), 0)
            self._counts[(site, worker)] = n + 1
        return _fraction(self.cfg.seed, site, worker, n)

    def _targets(self, worker: int) -> bool:
        return not self.cfg.devices or worker in self.cfg.devices

    def _fire(self, kind: str) -> bool:
        """Consume one fault from the budget; False when exhausted."""
        with self._lock:
            if (self.cfg.max_faults is not None
                    and self.faults_injected >= self.cfg.max_faults):
                return False
            self.faults_injected += 1
        if self.observe is not None:
            self.observe(kind)
        return True

    # ------------------------------------------------ replica-level faults
    def ready_blocked(self) -> bool:
        """Slow start: True while the readiness gate must stay closed
        (``slow_start_s`` of process lifetime not yet elapsed)."""
        return (self.cfg.slow_start_s > 0
                and self._clock() - self._t0 < self.cfg.slow_start_s)

    def blackhole(self) -> bool:
        """Health-check blackhole: True once /healthz and /readyz must
        stop answering (the HTTP layer closes the connection with no
        response; the router's probe timeout classifies it dead)."""
        return (self.cfg.healthz_blackhole_after_s > 0
                and self._clock() - self._t0
                >= self.cfg.healthz_blackhole_after_s)

    # --------------------------------------------------- injection sites
    def on_dispatch(self, worker: int) -> None:
        """Called between batch pickup and the device call: may stall
        (latency), then may raise a crash or a RESOURCE_EXHAUSTED — or
        hard-kill the whole process (``die_after_dispatches``)."""
        if not self._targets(worker):
            return
        c = self.cfg
        if c.die_after_dispatches is not None:
            with self._lock:
                self._dispatches += 1
                die = self._dispatches == c.die_after_dispatches
            if die:
                if self.observe is not None:
                    self.observe("die")
                self._exit(137)     # kill -9 exit code; no unwinding
        if (c.latency_rate > 0 and c.latency_ms > 0
                and self._roll("latency", worker) < c.latency_rate
                and self._fire("latency")):
            self._sleep(c.latency_ms / 1e3)
        if (c.crash_rate > 0
                and self._roll("crash", worker) < c.crash_rate
                and self._fire("crash")):
            raise InjectedWorkerCrash(
                f"injected worker crash (worker {worker})")
        if (c.resource_exhausted_rate > 0
                and self._roll("oom", worker) < c.resource_exhausted_rate
                and self._fire("resource_exhausted")):
            raise InjectedResourceExhausted(f" (worker {worker})")

    def on_compile(self, worker: int) -> None:
        """Called before an executable build for ``worker``."""
        if not self._targets(worker):
            return
        c = self.cfg
        if (c.compile_failure_rate > 0
                and self._roll("compile", worker) < c.compile_failure_rate
                and self._fire("compile_failure")):
            raise InjectedCompileFailure(
                f"injected compile failure (worker {worker})")


_SPEC_FIELDS = {
    "seed": ("seed", int),
    "crash": ("crash_rate", float),
    "oom": ("resource_exhausted_rate", float),
    "compile": ("compile_failure_rate", float),
    "latency": ("latency_rate", float),
    "latency_ms": ("latency_ms", float),
    "max_faults": ("max_faults", int),
    "die_after": ("die_after_dispatches", int),
    "blackhole_after_s": ("healthz_blackhole_after_s", float),
    "slow_start_s": ("slow_start_s", float),
}


def parse_chaos_spec(spec: str) -> Optional[ChaosConfig]:
    """CLI chaos spec -> ChaosConfig.

    Comma-separated ``key=value`` pairs: ``crash=0.1,seed=7`` injects a
    10% worker-crash rate; keys are ``crash`` / ``oom`` / ``compile`` /
    ``latency`` (rates), ``latency_ms``, ``seed``, ``max_faults``, and
    ``devices`` (``|``-separated worker indices).  Empty/None -> None
    (chaos off)."""
    if not spec or not spec.strip():
        return None
    kwargs: Dict[str, object] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"chaos spec {spec!r}: {part!r} is not "
                             f"key=value")
        key, value = (s.strip() for s in part.split("=", 1))
        if key == "devices":
            kwargs["devices"] = tuple(
                int(d) for d in value.split("|") if d.strip())
        elif key in _SPEC_FIELDS:
            field, cast = _SPEC_FIELDS[key]
            kwargs[field] = cast(value)
        else:
            raise ValueError(
                f"chaos spec {spec!r}: unknown key {key!r} (use "
                f"{sorted(_SPEC_FIELDS) + ['devices']})")
    return ChaosConfig(**kwargs)
