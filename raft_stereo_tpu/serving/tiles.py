"""Halo-overlap row tiling: beyond-mesh inputs through the bucket engine.

The xl mesh tier (serving/engine.py) answers big pairs by sharding ONE
program over several devices — but any fixed mesh has a ceiling, and some
deployments have no mesh at all.  This module is the fallback that keeps
the SAME bucket engine answering arbitrarily large inputs: split the
image into horizontal bands, run each band as an ordinary bucket dispatch
(all tiles of one image share one padded bucket, so the continuous
batcher groups them into batch-N dispatches — no new scheduler), and
stitch the disparities back together.

Row tiling is the natural cut for stereo: epipolar lines are image ROWS,
so every tile sees the full disparity-search width and the correlation
math inside a tile is exactly the full-image math.  What a tile cannot
see is vertical context beyond its band — receptive fields of the
encoders and the GRU's iterative propagation — so each tile carries a
``halo`` of extra rows on both sides and only its interior ("owned")
rows land in the stitched output.  The default halo of 64 full-res rows
is 4x the rows_gru executors' validated 16-row fine-level (=64 full-res
at 1/4 resolution) per-iteration receptive-field contract
(parallel/rows_gru.default_gru_halo): tiling cannot refresh halos
between GRU iterations the way the sharded loop does, so it over-provisions
instead, and the residual disagreement is MEASURED per request as the
seam-error metric rather than assumed away.

Geometry mirrors the clamped-window scheme of ``parallel/rows_gru.py``:
every tile has the SAME height (``tile_rows + 2*halo``), with edge tiles
shifted inward instead of shrunk — identical tile shapes are what lets
the batcher put all of one image's tiles in one dispatch.  Stitching is
center-crop: each output row is taken from the tile that owns it (the
tile where the row is most interior).  Adjacent tiles both predict the
overlap rows, and ``seam_epe`` reports their mean absolute disagreement
there — zero when the tiles are consistent restrictions of one global
field (the property tests pin this), and a live per-request accuracy
signal (``serve_tile_seam_epe``) when they are not.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

# Owned rows per tile and overlap halo (full-resolution rows), the
# ServeConfig defaults.  See the module docstring for the halo rationale.
DEFAULT_TILE_ROWS = 512
DEFAULT_TILE_HALO = 64


@dataclasses.dataclass(frozen=True)
class TileSpec:
    """One row band: the tile runs rows ``[src0, src1)`` of the full
    image and OWNS rows ``[y0, y1)`` of the stitched output."""

    y0: int
    y1: int
    src0: int
    src1: int

    @property
    def height(self) -> int:
        return self.src1 - self.src0

    @property
    def own_slice(self) -> slice:
        """Owned rows in tile-local coordinates."""
        return slice(self.y0 - self.src0, self.y1 - self.src0)


def plan_tiles(height: int, tile_rows: int = DEFAULT_TILE_ROWS,
               halo: int = DEFAULT_TILE_HALO) -> List[TileSpec]:
    """Split ``height`` rows into equal-height overlapping tiles.

    Every tile spans exactly ``tile_rows + 2*halo`` source rows (edge
    tiles shift inward rather than shrink — same-shape tiles share one
    compiled bucket and batch together).  An image short enough for one
    tile returns a single full-image spec, which callers should treat as
    "don't tile".  Owned spans partition ``[0, height)`` exactly."""
    if height < 1:
        raise ValueError(f"height={height} must be >= 1")
    if tile_rows < 1:
        raise ValueError(f"tile_rows={tile_rows} must be >= 1")
    if halo < 0:
        raise ValueError(f"halo={halo} must be >= 0")
    extent = tile_rows + 2 * halo
    if height <= extent:
        return [TileSpec(0, height, 0, height)]
    n = -(-height // tile_rows)
    edges = [round(i * height / n) for i in range(n + 1)]
    specs = []
    for i in range(n):
        y0, y1 = edges[i], edges[i + 1]
        src0 = min(max(0, y0 - halo), height - extent)
        specs.append(TileSpec(y0, y1, src0, src0 + extent))
    return specs


def stitch(flows: Sequence[np.ndarray],
           specs: Sequence[TileSpec]) -> np.ndarray:
    """Assemble tile disparities into the full-image map by center-crop:
    row ``y`` comes from the tile that owns it.  ``flows[i]`` is tile
    ``i``'s full prediction, shape ``(specs[i].height, W)``."""
    if len(flows) != len(specs) or not specs:
        raise ValueError(f"{len(flows)} tile outputs for {len(specs)} "
                         f"specs")
    height = specs[-1].y1
    out = np.empty((height,) + tuple(flows[0].shape[1:]),
                   dtype=flows[0].dtype)
    for flow, spec in zip(flows, specs):
        if flow.shape[0] != spec.height:
            raise ValueError(
                f"tile output has {flow.shape[0]} rows for a "
                f"{spec.height}-row tile {spec}")
        out[spec.y0:spec.y1] = flow[spec.own_slice]
    return out


def seam_epe(flows: Sequence[np.ndarray],
             specs: Sequence[TileSpec]) -> Optional[float]:
    """Mean |Δdisparity| over all rows that adjacent tiles BOTH predict —
    the measured cost of tiling.  Zero iff every overlap agrees exactly
    (tiles that are restrictions of one global field); grows with the
    vertical context the halo failed to carry.  None for a single tile
    (nothing overlaps)."""
    if len(flows) < 2:
        return None
    total, count = 0.0, 0
    for i in range(len(flows) - 1):
        a, sa = flows[i], specs[i]
        b, sb = flows[i + 1], specs[i + 1]
        lo, hi = max(sa.src0, sb.src0), min(sa.src1, sb.src1)
        if hi <= lo:
            continue
        da = np.asarray(a[lo - sa.src0:hi - sa.src0], np.float64)
        db = np.asarray(b[lo - sb.src0:hi - sb.src0], np.float64)
        total += float(np.abs(da - db).sum())
        count += da.size
    return (total / count) if count else None
