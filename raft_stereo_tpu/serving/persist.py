"""Persistent AOT executable cache: restart-to-ready in seconds, not
compile-minutes.

COST_REPORT_r10.json measured 23.6 s of XLA compile for the 7-iter
realtime model *per shape bucket* — and round 11/12 multiplied the
executable surface to (bucket x batch size x tier).  A crashed or
rescheduled serving process repays that entire product on boot, which at
production scale means tens of seconds of dead pod per autoscale event.
This module makes prewarm disk-bound instead of compile-bound:

* ``ExecutableDiskCache`` — serializes compiled executables
  (``jax.experimental.serialize_executable``) to a content-addressed
  file per compile point and loads them back on the next boot.  The key
  is a SHA-256 over everything that invalidates an executable: jax
  version, backend platform + version, device kind, the model config
  JSON, padded shape, batch size, tier knobs, GRU depth, fetch dtype,
  donation, and the executable FAMILY / flow_init arity (the round-14
  warm-start programs take an extra traced input and return the low-res
  state, so warm and cold variants of one (config, shape, batch, tier)
  must never collide on one key — engine._disk_key passes both
  coordinates) — a new jax wheel or a config change misses cleanly and
  recompiles (stale entries are just dead files, never wrong programs).
* ``enable_persistent_compilation_cache`` — turns on jax's own
  persistent compilation cache in the same directory, which also covers
  compiles that do not route through the AOT path.

Degradation contract (same as telemetry/costs.py): serialization that
fails for any reason — backend without serialization support, pickle
drift across versions, a corrupt/truncated cache file — logs once and
falls back to a fresh compile.  The cache can make boot faster; it can
never make serving wrong or down.  Writes are atomic (tmp +
``os.replace``) so a crash mid-write cannot leave a torn entry for the
next boot to trip over.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
from typing import Any, Dict, Optional

log = logging.getLogger(__name__)

# Bump to invalidate every existing cache entry on a format change.
CACHE_FORMAT_VERSION = 1


def backend_fingerprint() -> Dict[str, str]:
    """The jax/backend identity an executable is only valid under."""
    import jax

    fp = {"jax": jax.__version__,
          "cache_format": str(CACHE_FORMAT_VERSION)}
    try:
        backend = jax.extend.backend.get_backend()
        fp["platform"] = str(backend.platform)
        fp["platform_version"] = str(
            getattr(backend, "platform_version", ""))
    except Exception:  # pragma: no cover - exotic backend init
        fp["platform"] = str(jax.default_backend())
    try:
        fp["device_kind"] = str(
            getattr(jax.devices()[0], "device_kind", ""))
    except Exception:  # pragma: no cover
        fp["device_kind"] = ""
    return fp


def executable_cache_key(**coords: Any) -> str:
    """Stable content key of one compile point: the caller passes every
    coordinate that selects a distinct program (config JSON, padded
    shape, batch, tier, iters, fetch dtype, donation, device index) and
    the backend fingerprint is mixed in here."""
    payload = dict(coords)
    payload["backend"] = backend_fingerprint()
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


class ExecutableDiskCache:
    """Directory of serialized compiled executables, keyed by
    ``executable_cache_key``.

    ``load`` returns a ready-to-call loaded executable or None (miss /
    unreadable / wrong format — misses never raise).  ``store`` is
    best-effort and atomic.  A ``disabled`` cache (serialization proved
    unavailable on this backend) stops trying after the first failure so
    a hot dispatch path does not repeatedly pay a doomed serialize.
    """

    def __init__(self, cache_dir: str):
        self.cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
        os.makedirs(self.cache_dir, exist_ok=True)
        self._lock = threading.Lock()
        self.disabled = False
        self.loads = 0       # warm hits served from disk
        self.stores = 0
        self.misses = 0

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, f"{key}.jaxexe")

    def load(self, key: str):
        if self.disabled:
            return None
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
        except FileNotFoundError:
            with self._lock:
                self.misses += 1
            return None
        except Exception:
            log.warning("unreadable executable cache entry %s; "
                        "recompiling (entry will be rewritten)", path,
                        exc_info=True)
            with self._lock:
                self.misses += 1
            return None
        try:
            from jax.experimental import serialize_executable
            exe = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception:
            log.warning("could not deserialize cached executable %s "
                        "(backend/jax drift past the fingerprint?); "
                        "recompiling", path, exc_info=True)
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            self.loads += 1
        return exe

    def store(self, key: str, compiled) -> bool:
        if self.disabled:
            return False
        try:
            from jax.experimental import serialize_executable
            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
        except Exception:
            log.warning("executable serialization unavailable on this "
                        "backend; persistent cache disabled for this "
                        "process", exc_info=True)
            self.disabled = True
            return False
        path = self._path(key)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            log.warning("could not write executable cache entry %s",
                        path, exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        with self._lock:
            self.stores += 1
        return True

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"loads": self.loads, "stores": self.stores,
                    "misses": self.misses,
                    "disabled": int(self.disabled)}


def enable_persistent_compilation_cache(cache_dir: str) -> bool:
    """Point jax's own persistent compilation cache at ``cache_dir`` —
    covers compiles outside the engine's AOT path (best-effort; False
    when this jax build does not support it)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(os.path.expanduser(cache_dir)))
        # Cache every compile, not just the slow ones: serving prewarm is
        # many medium-size compiles, each below the default 1s floor.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        return True
    except Exception:  # pragma: no cover - older jax
        log.warning("jax persistent compilation cache unsupported by "
                    "this jax build", exc_info=True)
        return False
