"""Shared content-addressed executable artifact store: restart-to-ready
(and fleet scale-out) in seconds, not compile-minutes.

COST_REPORT_r10.json measured 23.6 s of XLA compile for the 7-iter
realtime model *per shape bucket* — and rounds 11/12/14/15 multiplied
the executable surface to (bucket x batch x tier x family).  A crashed,
rescheduled, or newly scaled-out serving replica repays that entire
product on boot, which at production scale means tens of seconds of dead
pod per autoscale event — times N replicas.  This module makes prewarm
fetch-bound instead of compile-bound:

* ``ExecutableDiskCache`` — a content-addressed store of serialized
  compiled executables (``jax.experimental.serialize_executable``).  The
  key is a SHA-256 over everything that invalidates an executable: jax
  version, backend platform + version, device kind, the model config
  JSON, padded shape, batch size, tier knobs, GRU depth, fetch dtype,
  donation, quant mode, and the executable FAMILY / flow_init arity —
  a new jax wheel or a config change misses cleanly and recompiles
  (stale entries are dead files, never wrong programs).

  **Layout** (the fleet contract, docs/architecture.md §Fleet): entries
  live at ``<store>/<key[:2]>/<key>.jaxexe`` with an optional
  ``<key>.json`` manifest sidecar recording the human-readable compile
  coordinates — a flat SHA-256-addressed tree any shared medium can
  carry (NFS mount, object-store sync, an image layer baked by
  tools/compile_farm.py).  Round-13 flat-layout entries
  (``<store>/<key>.jaxexe``) still load.  Because keys are pure content
  hashes, concurrent writers (N replicas, a compile farm) can share one
  directory with no coordination: identical coordinates produce
  identical keys, and the atomic rename makes the last writer win with
  an equivalent artifact.

  **Shared-store roles**: a compile farm populates the store
  (read-write); replicas may mount it ``read_only`` — they fetch warm
  artifacts but never write, so a misconfigured replica cannot pollute
  the fleet's shared cache.

  **Garbage collection**: ``max_bytes`` bounds the store.  Entries are
  evicted least-recently-USED first (atime, which ``load`` refreshes
  explicitly via ``os.utime`` so noatime mounts still track use);
  config / jax-fingerprint churn therefore ages out instead of growing
  without bound.  The ``bytes_gauge`` hook keeps the
  ``serve_persist_cache_bytes`` gauge live.

* ``enable_persistent_compilation_cache`` — turns on jax's own
  persistent compilation cache in the same directory, which also covers
  compiles that do not route through the AOT path.

* ``SessionHandoffStore`` — the store's ``sessions/`` namespace (round
  18): serialized SessionStore blobs a draining replica publishes so
  its live streams survive a planned restart (docs/architecture.md
  §Fleet, "Session handoff").  Content-hash keys, atomic writes,
  TTL-bounded, and the same can-only-cost-warmth degradation contract.

Degradation contract (same as telemetry/costs.py): serialization that
fails for any reason — backend without serialization support, pickle
drift across versions, a corrupt/truncated cache file — logs once and
falls back to a fresh compile.  The store can make boot faster; it can
never make serving wrong or down.  Writes are atomic (tmp +
``os.replace``) so a crash mid-write cannot leave a torn entry for the
next boot (or another replica) to trip over.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

# Bump to invalidate every existing cache entry on a format change.
CACHE_FORMAT_VERSION = 1

ENTRY_SUFFIX = ".jaxexe"
MANIFEST_SUFFIX = ".json"


def backend_fingerprint() -> Dict[str, str]:
    """The jax/backend identity an executable is only valid under."""
    import jax

    fp = {"jax": jax.__version__,
          "cache_format": str(CACHE_FORMAT_VERSION)}
    try:
        backend = jax.extend.backend.get_backend()
        fp["platform"] = str(backend.platform)
        fp["platform_version"] = str(
            getattr(backend, "platform_version", ""))
    except Exception:  # pragma: no cover - exotic backend init
        fp["platform"] = str(jax.default_backend())
    try:
        fp["device_kind"] = str(
            getattr(jax.devices()[0], "device_kind", ""))
    except Exception:  # pragma: no cover
        fp["device_kind"] = ""
    return fp


def executable_cache_key(**coords: Any) -> str:
    """Stable content key of one compile point: the caller passes every
    coordinate that selects a distinct program (config JSON, padded
    shape, batch, tier, iters, fetch dtype, donation, device index) and
    the backend fingerprint is mixed in here."""
    payload = dict(coords)
    payload["backend"] = backend_fingerprint()
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


class ExecutableDiskCache:
    """Content-addressed store of serialized compiled executables, keyed
    by ``executable_cache_key``.

    ``load`` returns a ready-to-call loaded executable or None (miss /
    unreadable / wrong format — misses never raise).  ``store`` is
    best-effort and atomic, a no-op in ``read_only`` mode.  A
    ``disabled`` cache (serialization proved unavailable on this
    backend) stops trying after the first failure so a hot dispatch path
    does not repeatedly pay a doomed serialize.  ``max_bytes`` bounds
    the store with LRU-by-atime eviction; ``bytes_gauge`` (any object
    with ``set``) tracks the post-GC total.
    """

    def __init__(self, cache_dir: str, max_bytes: Optional[int] = None,
                 read_only: bool = False, bytes_gauge=None):
        if max_bytes is not None and max_bytes < 0:
            raise ValueError(f"max_bytes={max_bytes} must be >= 0")
        self.cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
        if not read_only:
            os.makedirs(self.cache_dir, exist_ok=True)
        self.max_bytes = max_bytes
        self.read_only = read_only
        self.bytes_gauge = bytes_gauge
        self._lock = threading.Lock()
        self.disabled = False
        self.loads = 0       # warm hits served from disk
        self.stores = 0
        self.misses = 0
        self.evictions = 0
        if bytes_gauge is not None:
            bytes_gauge.set(self.total_bytes())

    # ------------------------------------------------------------- layout
    def _path(self, key: str) -> str:
        """Sharded canonical path: ``<store>/<key[:2]>/<key>.jaxexe``."""
        return os.path.join(self.cache_dir, key[:2],
                            f"{key}{ENTRY_SUFFIX}")

    def _legacy_path(self, key: str) -> str:
        """Round-13 flat layout, still honored on load."""
        return os.path.join(self.cache_dir, f"{key}{ENTRY_SUFFIX}")

    def _entries(self) -> List[Tuple[str, int, float]]:
        """Every entry file as ``(path, size, atime)`` — flat and
        sharded layouts alike; never raises (a racing eviction or an
        unshared store mid-write just drops out of the listing)."""
        out: List[Tuple[str, int, float]] = []
        try:
            roots = [self.cache_dir] + [
                os.path.join(self.cache_dir, d)
                for d in os.listdir(self.cache_dir)
                if len(d) == 2
                and os.path.isdir(os.path.join(self.cache_dir, d))]
        except OSError:
            return out
        for root in roots:
            try:
                names = os.listdir(root)
            except OSError:
                continue
            for name in names:
                if not name.endswith(ENTRY_SUFFIX):
                    continue
                path = os.path.join(root, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                out.append((path, st.st_size, st.st_atime))
        return out

    def total_bytes(self) -> int:
        """Bytes of executable entries on disk (manifest sidecars are
        noise-level and not counted)."""
        return sum(size for _, size, _ in self._entries())

    # ----------------------------------------------------------------- load
    def load(self, key: str):
        if self.disabled:
            return None
        path = self._path(key)
        if not os.path.exists(path):
            legacy = self._legacy_path(key)
            path = legacy if os.path.exists(legacy) else path
        try:
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
        except FileNotFoundError:
            with self._lock:
                self.misses += 1
            return None
        except Exception:
            log.warning("unreadable executable cache entry %s; "
                        "recompiling (entry will be rewritten)", path,
                        exc_info=True)
            with self._lock:
                self.misses += 1
            return None
        try:
            from jax.experimental import serialize_executable
            exe = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception:
            log.warning("could not deserialize cached executable %s "
                        "(backend/jax drift past the fingerprint?); "
                        "recompiling", path, exc_info=True)
            with self._lock:
                self.misses += 1
            return None
        # Mark use explicitly: LRU eviction orders by atime, and noatime
        # mounts would otherwise never see reads.  Best-effort (a
        # read-only mount cannot utime — fine, its GC runs elsewhere).
        try:
            os.utime(path)
        except OSError:
            pass
        with self._lock:
            self.loads += 1
        return exe

    # ---------------------------------------------------------------- store
    def store(self, key: str, compiled,
              meta: Optional[Dict[str, Any]] = None) -> bool:
        """Serialize ``compiled`` under ``key``; ``meta`` (optional)
        lands in a ``<key>.json`` manifest sidecar so a human (or an
        audit job) can read WHAT each content hash is without
        deserializing it."""
        if self.disabled or self.read_only:
            return False
        try:
            from jax.experimental import serialize_executable
            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
        except Exception:
            log.warning("executable serialization unavailable on this "
                        "backend; persistent cache disabled for this "
                        "process", exc_info=True)
            self.disabled = True
            return False
        path = self._path(key)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            log.warning("could not write executable cache entry %s",
                        path, exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        if meta is not None:
            self._write_manifest(key, meta, len(blob))
        with self._lock:
            self.stores += 1
        self.gc()
        return True

    def _write_manifest(self, key: str, meta: Dict[str, Any],
                        size: int) -> None:
        mpath = os.path.join(os.path.dirname(self._path(key)),
                             f"{key}{MANIFEST_SUFFIX}")
        tmp = f"{mpath}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"key": key, "bytes": size,
                           "backend": backend_fingerprint(), **meta},
                          f, indent=1, sort_keys=True, default=str)
            os.replace(tmp, mpath)
        except OSError:   # the manifest is advisory — never fail a store
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # ------------------------------------------------------------------- gc
    def gc(self) -> int:
        """Evict least-recently-used entries until the store fits
        ``max_bytes``; returns the number evicted.  Also refreshes the
        bytes gauge.  No-op without a bound (the gauge still updates)."""
        entries = self._entries()
        total = sum(size for _, size, _ in entries)
        evicted = 0
        if (self.max_bytes is not None and not self.read_only
                and total > self.max_bytes):
            for path, size, _ in sorted(entries, key=lambda e: e[2]):
                if total <= self.max_bytes:
                    break
                try:
                    os.unlink(path)
                except OSError:
                    continue
                try:   # the manifest dies with its entry
                    os.unlink(path[:-len(ENTRY_SUFFIX)]
                              + MANIFEST_SUFFIX)
                except OSError:
                    pass
                total -= size
                evicted += 1
            if evicted:
                with self._lock:
                    self.evictions += evicted
                log.info("executable cache GC: evicted %d LRU entr%s "
                         "(max_bytes=%d, now %d bytes)", evicted,
                         "y" if evicted == 1 else "ies",
                         self.max_bytes, total)
        if self.bytes_gauge is not None:
            self.bytes_gauge.set(total)
        return evicted

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"loads": self.loads, "stores": self.stores,
                    "misses": self.misses, "evictions": self.evictions,
                    "disabled": int(self.disabled),
                    "read_only": int(self.read_only)}


class SessionHandoffStore:
    """The artifact store's ``sessions/`` namespace (round 18): a
    gracefully draining replica publishes its serialized session blob
    here (serving/sessions.py ``SessionStore.export``), the router hands
    the content key to whichever survivors inherit those ids
    (``X-Handoff-Artifact``), and the receiving replica fetches the blob
    lazily at the session's next frame.

    Same degradation contract as the executable store above: a handoff
    that cannot be written, read, or parsed costs warmth (those sessions
    cold-start), never correctness or uptime.  Keys are SHA-256 content
    hashes, writes are atomic, and ``gc`` ages published blobs out after
    ``ttl_s`` — a handoff is only useful for about one session TTL, so
    the namespace is self-bounding under rolling restarts.
    """

    SUFFIX = ".sessions"

    def __init__(self, store_dir: str, ttl_s: float = 600.0,
                 read_only: bool = False):
        self.dir = os.path.join(
            os.path.abspath(os.path.expanduser(store_dir)), "sessions")
        self.ttl_s = ttl_s
        self.read_only = read_only
        if not read_only:
            try:
                os.makedirs(self.dir, exist_ok=True)
            except OSError:
                log.warning("cannot create session handoff namespace %s",
                            self.dir, exc_info=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}{self.SUFFIX}")

    def publish(self, blob: bytes) -> Optional[str]:
        """Write one handoff blob; returns its content key, or None when
        the write failed (the drain proceeds — its sessions fail over to
        the r16 typed-loss path instead)."""
        if self.read_only:
            return None
        key = hashlib.sha256(blob).hexdigest()
        path = self._path(key)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            log.warning("could not publish session handoff %s", path,
                        exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        self.gc()
        return key

    def fetch(self, key: str) -> Optional[bytes]:
        """The blob for ``key``, or None (missing / unreadable / key
        fails the content-hash check — a torn or tampered file must not
        reach the parser as trusted state)."""
        try:
            with open(self._path(key), "rb") as f:
                blob = f.read()
        except OSError:
            return None
        if hashlib.sha256(blob).hexdigest() != key:
            log.warning("session handoff %s fails its content hash; "
                        "ignoring", key)
            return None
        return blob

    def gc(self) -> int:
        """Drop handoff blobs older than ``ttl_s`` (mtime); returns the
        count removed."""
        if self.read_only:
            return 0
        removed = 0
        try:
            names = os.listdir(self.dir)
        except OSError:
            return 0
        cutoff = time.time() - self.ttl_s
        for name in names:
            if not name.endswith(self.SUFFIX):
                continue
            path = os.path.join(self.dir, name)
            try:
                if os.stat(path).st_mtime < cutoff:
                    os.unlink(path)
                    removed += 1
            except OSError:
                continue
        return removed


def enable_persistent_compilation_cache(cache_dir: str) -> bool:
    """Point jax's own persistent compilation cache at ``cache_dir`` —
    covers compiles outside the engine's AOT path (best-effort; False
    when this jax build does not support it)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(os.path.expanduser(cache_dir)))
        # Cache every compile, not just the slow ones: serving prewarm is
        # many medium-size compiles, each below the default 1s floor.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        return True
    except Exception:  # pragma: no cover - older jax
        log.warning("jax persistent compilation cache unsupported by "
                    "this jax build", exc_info=True)
        return False
