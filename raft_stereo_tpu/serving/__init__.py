"""Inference serving subsystem: the batch-N serving engine — bucketed
batch executables, continuous batching, admission control + backpressure,
waste-driven bucket selection, and a plain-text metrics endpoint.  See
docs/architecture.md §Serving."""

from raft_stereo_tpu.serving.batcher import (BucketQueue, DeadlineExceeded,
                                             Overloaded, Request,
                                             decompose_batch,
                                             pick_batch_size)
from raft_stereo_tpu.serving.engine import (BucketPolicy, ServeConfig,
                                            ServeResult, ServingEngine,
                                            StereoService)
from raft_stereo_tpu.serving.metrics import (MetricsRegistry, ServingMetrics)

__all__ = ["BucketQueue", "DeadlineExceeded", "Overloaded", "Request",
           "decompose_batch", "pick_batch_size", "BucketPolicy",
           "MetricsRegistry", "ServingMetrics", "ServeConfig", "ServeResult",
           "ServingEngine", "StereoService"]
