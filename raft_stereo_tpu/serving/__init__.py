"""Inference serving subsystem: the batch-N serving engine — bucketed
batch executables, continuous batching, admission control + backpressure,
waste-driven bucket selection, supervised crash recovery (retries,
per-device circuit breakers, brownout degradation, chaos testing, a
persistent executable cache), streaming stereo sessions (warm-start video
serving with temporal state, serving/sessions.py), a plain-text
metrics endpoint, and fleet-scale replication (serving/fleet/: a
session-sticky router with failover, fleet-wide brownout, and the shared
executable artifact store).  See docs/architecture.md §Serving,
§Resilience, §Fleet, and §Streaming sessions."""

from raft_stereo_tpu.serving.batcher import (BucketQueue, DeadlineExceeded,
                                             Overloaded, Request,
                                             RequestPoisoned,
                                             decompose_batch,
                                             pick_batch_size)
from raft_stereo_tpu.serving.chaos import (ChaosConfig, ChaosInjector,
                                           InjectedCompileFailure,
                                           InjectedFault,
                                           InjectedResourceExhausted,
                                           InjectedWorkerCrash,
                                           parse_chaos_spec)
from raft_stereo_tpu.serving.engine import (FAMILY_BASE, FAMILY_STATE,
                                            FAMILY_STATE_CTX,
                                            FAMILY_STATE_CTX_H,
                                            FAMILY_STATE_H, FAMILY_WARM,
                                            FAMILY_WARM_CTX,
                                            FAMILY_WARM_CTX_H,
                                            FAMILY_WARM_H, FAMILY_XL,
                                            BucketPolicy,
                                            ServeConfig, ServeResult,
                                            ServingEngine, StereoService)
from raft_stereo_tpu.serving.tiles import (TileSpec, plan_tiles, seam_epe,
                                           stitch)
from raft_stereo_tpu.serving.metrics import (MetricsRegistry, ServingMetrics)
from raft_stereo_tpu.serving.persist import (ExecutableDiskCache,
                                             enable_persistent_compilation_cache,
                                             executable_cache_key)
from raft_stereo_tpu.serving.resilience import (CIRCUIT_CLOSED,
                                                CIRCUIT_HALF_OPEN,
                                                CIRCUIT_OPEN,
                                                BrownoutController,
                                                CircuitBreaker,
                                                circuit_state_name,
                                                cost_ladder)
from raft_stereo_tpu.serving.sessions import (SessionExpired,
                                              SessionsDisabled,
                                              SessionStore, StereoSession,
                                              frame_delta, frame_thumbnail)

__all__ = ["BucketQueue", "DeadlineExceeded", "Overloaded", "Request",
           "RequestPoisoned", "decompose_batch", "pick_batch_size",
           "ChaosConfig", "ChaosInjector", "InjectedCompileFailure",
           "InjectedFault", "InjectedResourceExhausted",
           "InjectedWorkerCrash", "parse_chaos_spec", "BucketPolicy",
           "MetricsRegistry", "ServingMetrics", "ServeConfig", "ServeResult",
           "ServingEngine", "StereoService", "ExecutableDiskCache",
           "enable_persistent_compilation_cache", "executable_cache_key",
           "CIRCUIT_CLOSED", "CIRCUIT_HALF_OPEN", "CIRCUIT_OPEN",
           "BrownoutController", "CircuitBreaker", "circuit_state_name",
           "cost_ladder", "FAMILY_BASE", "FAMILY_STATE",
           "FAMILY_STATE_CTX", "FAMILY_WARM", "FAMILY_WARM_CTX",
           "FAMILY_XL", "TileSpec", "plan_tiles", "seam_epe", "stitch",
           "SessionExpired", "SessionsDisabled", "SessionStore",
           "StereoSession", "frame_delta", "frame_thumbnail"]
