"""Inference serving subsystem: dynamic micro-batching over shape buckets,
admission control + backpressure, device worker pool, and a plain-text
metrics endpoint.  See docs/architecture.md §Serving."""

from raft_stereo_tpu.serving.batcher import (DeadlineExceeded, MicroBatcher,
                                             Overloaded, Request)
from raft_stereo_tpu.serving.metrics import (MetricsRegistry, ServingMetrics)
from raft_stereo_tpu.serving.service import (ServeConfig, ServeResult,
                                             StereoService)

__all__ = ["DeadlineExceeded", "MicroBatcher", "Overloaded", "Request",
           "MetricsRegistry", "ServingMetrics", "ServeConfig", "ServeResult",
           "StereoService"]
