"""Concurrent stereo-depth service: batcher front door + device worker pool.

Turns the single-image ``eval.runner.InferenceRunner`` into a
traffic-handling subsystem.  Requests enter through ``submit`` (or the
blocking ``infer``), are grouped by /32-padded shape bucket in the
``MicroBatcher``, and micro-batches run on a pool of device workers — one
per local device for data-parallel dispatch — each owning an
``InferenceRunner`` whose bounded per-(shape, batch) compile cache this
service inherits unchanged.

Two batch execution modes, because they trade differently:

* ``"chain"`` (default) — every image in the micro-batch runs through the
  SAME compiled batch-1 executable the solo ``InferenceRunner.__call__``
  uses; the N forwards are dispatched back-to-back (JAX async dispatch
  pipelines them) and synced once at the batch fetch.  One executable per
  padded shape regardless of batch size, and results are **bitwise equal**
  to a solo run of the same image (tests/test_serving.py asserts it) —
  batching amortizes the per-image host sync + Python overhead without
  touching numerics.
* ``"stack"`` — the micro-batch is stacked into ONE batched dispatch,
  batch-padded to the next power of two (at most log2(max_batch)+1
  executables per shape).  Maximum amortization of per-dispatch overhead —
  the right mode behind a high-RTT device tunnel — but a batch-N
  executable reassociates differently from batch-1 (~1e-5 drift, the
  documented run_batch trade; tests/test_cli.py).

Shutdown mirrors the train loop's preemption story (training/train_loop.py):
``drain()`` refuses new work with the typed ``Overloaded``, flushes the
queue, finishes in-flight batches, and only then stops the workers.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_stereo_tpu import profiling
from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.eval.runner import InferenceRunner
from raft_stereo_tpu.ops.padding import InputPadder
from raft_stereo_tpu.serving.batcher import (DeadlineExceeded, MicroBatcher,
                                             Overloaded, Request)
from raft_stereo_tpu.serving.metrics import MetricsRegistry, ServingMetrics

log = logging.getLogger(__name__)

BATCH_MODES = ("chain", "stack")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving knobs (model architecture stays in RaftStereoConfig)."""

    max_batch: int = 8           # flush a bucket at this many requests
    max_wait_ms: float = 5.0     # ... or when its oldest waited this long
    max_queue: int = 64          # admission bound; beyond it -> Overloaded
    batch_mode: str = "chain"    # see module docstring
    data_parallel: int = 1       # device workers (<= local device count)
    iters: int = 32              # GRU iterations per request
    shape_bucket: Optional[int] = None   # coarser-than-/32 padding grid
    max_cached_shapes: int = 16  # per-worker compile cache bound
    fetch_dtype: Optional[str] = None    # "fp16" | "bf16" half fetch
    default_deadline_ms: Optional[float] = None  # per-request override wins
    # Fraction of requests whose span tree is recorded (telemetry/spans.py:
    # admission -> queue -> dispatch -> fetch -> respond, exported as
    # Chrome trace JSON via GET /debug/spans).  0.0 (default) disables
    # tracing entirely — every span site takes the constant-time None exit.
    trace_sample_rate: float = 0.0
    # Compile-cost telemetry (telemetry/costs.py): route every worker
    # compile through the AOT path so GET /debug/compiles lists each
    # bucket executable's flops/bytes/memory and the MFU gauges get their
    # flops numerator.  False (default) keeps the workers' exact jax.jit
    # dispatch — zero new code on the request path.
    cost_telemetry: bool = False
    # MFU denominator override (TFLOP/s); None = the auto table keyed by
    # the local device kind (costs.DEVICE_PEAK_TFLOPS).
    device_peak_tflops: Optional[float] = None

    def __post_init__(self):
        if self.batch_mode not in BATCH_MODES:
            raise ValueError(
                f"batch_mode={self.batch_mode!r} not in {BATCH_MODES}")
        if self.data_parallel < 1:
            raise ValueError(f"data_parallel={self.data_parallel} must be "
                             f">= 1")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError(f"trace_sample_rate={self.trace_sample_rate} "
                             f"must be in [0, 1]")


@dataclasses.dataclass
class ServeResult:
    """One answered request: the flow plus its latency decomposition."""

    flow: np.ndarray             # (H, W) x-flow (= -disparity), float32
    queue_wait_s: float          # admission -> worker pickup
    device_s: float              # dispatch -> outputs ready (advisory
    #                              behind an async tunnel; see metrics.py)
    fetch_s: float               # device->host result transfer
    total_s: float               # admission -> result ready
    batch_size: int              # occupancy of the micro-batch it rode in

    @property
    def disparity(self) -> np.ndarray:
        """Positive disparity (the user-facing convention, cli/demo.py)."""
        return -self.flow


@dataclasses.dataclass
class _Payload:
    """What the service parks in Request.payload: padded inputs + unpadder."""

    left: np.ndarray             # (Hp, Wp, 3) host-padded
    right: np.ndarray
    padder: InputPadder


_STOP = object()


class StereoService:
    """The concurrent front door over ``InferenceRunner``.

    ``devices`` defaults to the first ``serve_cfg.data_parallel`` local JAX
    devices; each gets a worker thread with the variables resident on that
    device, so same-bucket micro-batches dispatch data-parallel across the
    pool.
    """

    def __init__(self, config: RaftStereoConfig, variables,
                 serve_cfg: ServeConfig = ServeConfig(),
                 devices: Optional[Sequence] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer=None):
        import jax

        from raft_stereo_tpu.telemetry.spans import SpanTracer

        self.serve_cfg = serve_cfg
        # Request-path span tracer (telemetry/spans.py).  At the default
        # sample rate 0.0 every start_trace returns None and the span
        # plumbing below is a handful of no-op attribute checks per
        # request — serving numerics and dispatch behavior are untouched.
        self.tracer = (tracer if tracer is not None
                       else SpanTracer(serve_cfg.trace_sample_rate))
        if devices is None:
            local = jax.local_devices()
            if serve_cfg.data_parallel > len(local):
                raise ValueError(
                    f"data_parallel={serve_cfg.data_parallel} exceeds the "
                    f"{len(local)} local devices")
            devices = local[:serve_cfg.data_parallel]
        self.devices = list(devices)
        self.metrics = ServingMetrics(registry,
                                      max_batch=serve_cfg.max_batch)
        # Compile-cost registry (telemetry/costs.py): one per service,
        # shared by all workers — same bucket => same executable => one
        # cost record.  None (default) leaves the runners' jit dispatch
        # untouched.
        self.costs = None
        self._mfu = None
        if serve_cfg.cost_telemetry:
            from raft_stereo_tpu.telemetry.costs import (CompileRegistry,
                                                         MfuMeter)
            self.costs = CompileRegistry(
                registry=self.metrics.registry,
                device_peak_tflops=serve_cfg.device_peak_tflops)
            self._mfu = MfuMeter(
                self.metrics.mfu, self.costs.peak_flops,
                achieved_gauge=self.metrics.achieved_flops_per_s)
        # Per-worker runner: variables live on that worker's device, and the
        # bounded per-(padded shape, batch) compile cache is per worker.
        self._runners: List[InferenceRunner] = []
        for dev in self.devices:
            self._runners.append(InferenceRunner(
                config, jax.device_put(variables, dev),
                iters=serve_cfg.iters, shape_bucket=serve_cfg.shape_bucket,
                max_cached_shapes=serve_cfg.max_cached_shapes,
                fetch_dtype=serve_cfg.fetch_dtype,
                cost_registry=self.costs, cost_site="serving"))
        self.config = self._runners[0].config
        self._divis = self._runners[0].divis_by
        # Handoff between the batcher's flush thread and the workers: small
        # and bounded so a saturated pool stalls flushing (the backpressure
        # path) instead of accumulating dispatched-but-unstarted batches.
        self._work: "queue.Queue" = queue.Queue(maxsize=len(self.devices))
        self._workers = [
            threading.Thread(target=self._worker_loop, args=(r, d),
                             daemon=True, name=f"stereo-worker-{i}")
            for i, (r, d) in enumerate(zip(self._runners, self.devices))]
        for t in self._workers:
            t.start()
        self.batcher = MicroBatcher(
            dispatch=self._dispatch, max_batch=serve_cfg.max_batch,
            max_wait_ms=serve_cfg.max_wait_ms, max_queue=serve_cfg.max_queue,
            metrics=self.metrics)
        self._closed = False

    # ------------------------------------------------------------ front door
    def bucket_for(self, shape: Tuple[int, int, int]) -> Tuple[int, int]:
        """The padded (Hp, Wp) this image shape dispatches at."""
        padder = InputPadder((1,) + tuple(shape), divis_by=self._divis)
        l, r, t, b = padder.pads
        return (shape[0] + t + b, shape[1] + l + r)

    def submit(self, left: np.ndarray, right: np.ndarray,
               deadline_ms: Optional[float] = None) -> Future:
        """Admit one stereo pair; returns a Future of ``ServeResult``.

        Raises ``Overloaded`` at the door when the queue is full or the
        service is draining; the Future fails with ``DeadlineExceeded`` if
        the request's deadline passes before a device picks it up.
        """
        t_admit = time.perf_counter()
        left, right = np.asarray(left), np.asarray(right)
        if left.ndim != 3 or left.shape != right.shape:
            raise ValueError(
                f"need two same-shape (H, W, 3) images, got {left.shape} "
                f"vs {right.shape}")
        padder = InputPadder((1,) + left.shape, divis_by=self._divis)
        l, r, t, b = padder.pads
        spec = ((t, b), (l, r), (0, 0))
        payload = _Payload(left=np.pad(left, spec, mode="edge"),
                           right=np.pad(right, spec, mode="edge"),
                           padder=padder)
        now = time.monotonic()
        deadline_ms = (deadline_ms if deadline_ms is not None
                       else self.serve_cfg.default_deadline_ms)
        req = Request(bucket=payload.left.shape[:2], payload=payload,
                      future=Future(), t_enqueue=now,
                      deadline=(None if deadline_ms is None
                                else now + deadline_ms / 1e3))
        # Sampled request: root span + admission (validate/pad) span; the
        # queue span opens here and closes at worker pickup (_run_batch) or
        # in the done-callback for requests dropped in the queue.
        trace = self.tracer.start_trace(
            "serve.request", bucket=str(req.bucket),
            deadline_ms=deadline_ms)
        if trace is not None:
            req.trace = trace
            self.tracer.add_span("serve.admission", trace,
                                 t_admit, time.perf_counter(),
                                 bucket=str(req.bucket))
            req.queue_span = self.tracer.start_span("serve.queue", trace)
            req.future.add_done_callback(
                lambda f, r=req: self._finish_request_trace(r, f))
        try:
            self.batcher.submit(req)   # raises Overloaded at the door
        except Overloaded:
            if trace is not None and trace.root is not None:
                trace.root.set_attr("status", "overloaded")
                self._finish_request_trace(req, None)
            raise
        return req.future

    def _finish_request_trace(self, req: Request, future) -> None:
        """Close the queue span (if the worker never picked the request
        up) and the root span; idempotence guards the two close paths
        (worker pickup vs future resolution)."""
        qs = req.queue_span
        if qs is not None and qs.t_end is None:
            self.tracer.finish(qs)
        root = req.trace.root if req.trace is not None else None
        if root is not None and root.t_end is None:
            if future is not None:
                exc = future.exception()
                root.set_attr("status",
                              "ok" if exc is None else type(exc).__name__)
            self.tracer.finish(root)

    def infer(self, left: np.ndarray, right: np.ndarray,
              deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None) -> ServeResult:
        """Blocking convenience: submit + wait (the in-process client)."""
        return self.submit(left, right, deadline_ms).result(timeout=timeout)

    # --------------------------------------------------------------- workers
    def _dispatch(self, batch: List[Request]) -> None:
        """Batcher flush -> worker pool handoff.  Inflight is counted from
        HERE (not worker pickup) so ``drain``'s inflight==0 check covers
        batches parked in the handoff queue; the bounded ``put`` is the
        backpressure stall when the pool is saturated."""
        self.metrics.inflight.inc(len(batch))
        self._work.put(batch)

    def _worker_loop(self, runner: InferenceRunner, device) -> None:
        while True:
            batch = self._work.get()
            if batch is _STOP:
                return
            try:
                self._run_batch(runner, device, batch)
            except BaseException as e:  # noqa: BLE001 — fail the batch, not
                self.metrics.failed.inc(len(batch))       # the worker thread
                log.exception("micro-batch of %d failed", len(batch))
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)
            finally:
                self.metrics.inflight.dec(len(batch))

    def _run_batch(self, runner: InferenceRunner, device,
                   batch: List[Request]) -> None:
        import jax
        import jax.numpy as jnp

        t_pickup = time.monotonic()
        waits = [t_pickup - r.t_enqueue for r in batch]
        bucket = batch[0].bucket
        n = len(batch)

        # Sampled requests: the queue leg ends at worker pickup; the
        # dispatch/fetch spans below share the batch's time window but land
        # in each request's own trace (a trace stays self-contained).
        sampled = [r for r in batch if r.trace is not None]
        p_pickup = time.perf_counter() if sampled else 0.0
        for r in sampled:
            if r.queue_span is not None and r.queue_span.t_end is None:
                r.queue_span.set_attr("batch_size", n)
                self.tracer.finish(r.queue_span)

        with profiling.annotate("serve.device"):
            if self.serve_cfg.batch_mode == "chain":
                # N batch-1 dispatches through the one per-shape executable
                # (bitwise-identical to solo InferenceRunner), pipelined by
                # async dispatch, synced once below.
                exec_batch, frames = 1, n
                fwd = runner._forward_for(bucket, batch=1)
                outs = [fwd(runner.variables,
                            jax.device_put(r.payload.left[None], device),
                            jax.device_put(r.payload.right[None], device))
                        for r in batch]
            else:
                # "stack": one batched dispatch.  The batch axis pads to the
                # next power of two (not to max_batch): compiles per shape
                # stay bounded at log2(max_batch)+1 executables while a
                # half-full flush wastes at most ~2x filler compute instead
                # of always paying the full max_batch forward.
                nb = 1 << (n - 1).bit_length()
                exec_batch, frames = nb, nb
                p1 = np.stack([r.payload.left for r in batch]
                              + [batch[-1].payload.left] * (nb - n))
                p2 = np.stack([r.payload.right for r in batch]
                              + [batch[-1].payload.right] * (nb - n))
                fwd = runner._forward_for(bucket, batch=nb)
                stacked = fwd(runner.variables,
                              jax.device_put(p1, device),
                              jax.device_put(p2, device))
                outs = [stacked[i] for i in range(n)]
            # Advisory device clock: honest on a local backend; behind an
            # async tunnel readiness reports at dispatch (profiling.py) and
            # only the fetch below is a real stop clock.
            for o in outs:
                jax.block_until_ready(o)
        t_ready = time.monotonic()
        p_ready = time.perf_counter() if sampled else 0.0

        with profiling.annotate("serve.fetch"):
            flows_padded = [np.asarray(o) for o in outs]
        t_fetched = time.monotonic()
        p_fetched = time.perf_counter() if sampled else 0.0
        for r in sampled:
            self.tracer.add_span(
                "serve.dispatch", r.trace, p_pickup, p_ready,
                bucket=str(bucket), batch_size=n, device=str(device),
                mode=self.serve_cfg.batch_mode)
            self.tracer.add_span("serve.fetch", r.trace, p_ready, p_fetched,
                                 batch_size=n)

        device_s = t_ready - t_pickup
        fetch_s = t_fetched - t_ready
        self.metrics.batches.inc()
        self.metrics.batch_occupancy.observe(n)
        self.metrics.device_time.observe(device_s)
        self.metrics.fetch_time.observe(fetch_s)
        # Padding-waste accounting: every dispatched pixel beyond the
        # requests' real image pixels — the /32 spatial pad plus stack
        # mode's pow2 batch fill — is pure waste at fixed GRU depth.
        real_px = sum(r.payload.padder.ht * r.payload.padder.wd
                      for r in batch)
        self.metrics.observe_padding(bucket, real_px,
                                     frames * bucket[0] * bucket[1])
        # MFU numerator: the compiled executable's model flops times the
        # dispatches this batch issued (chain: n batch-1 programs; stack:
        # one batch-nb program).
        if self._mfu is not None:
            rec = runner.compiled_cost(bucket, batch=exec_batch)
            if rec is not None and rec.flops:
                flops = rec.flops * (n if exec_batch == 1 else 1)
                self.metrics.dispatched_flops.inc(flops)
                self._mfu.note(flops)
        self.metrics.note_batch_done()
        for r, fp, wait in zip(batch, flows_padded, waits):
            exemplar = r.trace.trace_id if r.trace is not None else None
            p_respond = time.perf_counter() if exemplar is not None else 0.0
            fp = fp if fp.ndim == 3 else fp[None]        # stack mode: (Hp,Wp)
            flow = r.payload.padder.unpad(fp)[0]
            if flow.dtype != np.float32:                 # half-precision fetch
                flow = flow.astype(np.float32)
            total = t_fetched - r.t_enqueue
            self.metrics.queue_wait.observe(wait, exemplar=exemplar)
            self.metrics.total_latency.observe(total, exemplar=exemplar)
            self.metrics.completed.inc()
            r.future.set_result(ServeResult(
                flow=np.ascontiguousarray(flow), queue_wait_s=wait,
                device_s=device_s, fetch_s=fetch_s, total_s=total,
                batch_size=n))
            if exemplar is not None:
                self.tracer.add_span("serve.respond", r.trace, p_respond,
                                     time.perf_counter())

    # -------------------------------------------------------------- shutdown
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful SIGTERM story: refuse new work (``Overloaded``), flush
        the queue, finish in-flight batches, stop the workers.  Returns
        False if ``timeout`` elapsed first (workers are still stopped; any
        stranded requests fail rather than hang)."""
        t0 = time.monotonic()
        ok = self.batcher.drain(timeout=timeout)
        # Wait for the work queue + in-flight batches to finish.
        remaining = (None if timeout is None
                     else max(0.0, timeout - (time.monotonic() - t0)))
        deadline = None if remaining is None else time.monotonic() + remaining
        while self.metrics.inflight.value > 0:
            if deadline is not None and time.monotonic() > deadline:
                ok = False
                break
            time.sleep(0.002)
        self.close()
        return ok

    def close(self) -> None:
        """Hard stop: ends the batcher (queued requests fail with
        ``Overloaded``) and the worker threads.  ``drain`` first for the
        graceful version."""
        if self._closed:
            return
        self._closed = True
        self.batcher.close()
        for _ in self._workers:
            self._work.put(_STOP)
        for t in self._workers:
            t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
