"""Compatibility surface for the round-6 serving API.

Round 11 replaced the ``StereoService`` + ``MicroBatcher`` + per-worker
``InferenceRunner`` split with the unified batch-N serving engine
(serving/engine.py): one object owning the compile cache (true batch-N
bucket executables with buffer donation), the continuous-batching
scheduler, and the cost/padding-waste telemetry loop.  ``StereoService``
is now an alias of ``ServingEngine`` and every import from this module
keeps working; see the engine module for the design.
"""

from raft_stereo_tpu.serving.engine import (  # noqa: F401 — re-exports
    ServeConfig, ServeResult, ServingEngine, StereoService)

__all__ = ["ServeConfig", "ServeResult", "ServingEngine", "StereoService"]
