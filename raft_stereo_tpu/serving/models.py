"""Model registry: versioned weight pytrees in the shared artifact store.

The engine historically served exactly one variables pytree per process,
forever — the weights rode the constructor and nothing could change them
short of a restart (ROADMAP item 5).  This module is the identity layer
that lifts that: a **ModelStore** keeps versioned checkpoints in the
SAME artifact store the compiled executables already share
(``models/<name>/<version>`` next to persist.py's ``<key[:2]>/*.jaxexe``
entries and the ``sessions/`` handoff namespace), and a **RegisteredModel**
is one loaded version the engine's registry threads through dispatch,
compile keys, prewarm, and telemetry.

Store layout — one directory per version, written by the SAME atomic
r20 deep-validation machinery the train loop checkpoints with
(training/checkpoint.py): ``config.json`` + orbax ``state/`` + a
per-file SHA-256 ``MANIFEST`` sealed by the ``COMMIT`` marker, staged in
a same-filesystem tmp dir and ``os.replace``d into place.  A version is
IMMUTABLE once published (re-publishing an existing version is a typed
error unless forced); a flipped byte anywhere in the blob fails
``verify`` instead of serving garbage weights.

    models/
      kitti/
        v1/   config.json  state/  MANIFEST  COMMIT
        v2/   ...

Identity rules the rest of the subsystem builds on:

* A model COORDINATE is ``name@version`` (``parse_model_spec``).  Names
  and versions are path-safe tokens — the store never joins untrusted
  path segments.
* The engine's implicit constructor model has NO coordinate (``None``):
  every key, metric, and wire field it touches is byte-identical to the
  pre-registry build.  The model coordinate only exists where a named
  model does.
* ``ModelUnknown`` is the typed admission error (HTTP 404
  ``model_unknown``) — same contract as the tier ladder's unknown-tier
  400, one level up.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

MODELS_SUBDIR = "models"

# Path-safe model name / version tokens: the store builds filesystem
# paths from them, so they must never carry separators or traversal.
_TOKEN_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class ModelUnknown(KeyError):
    """A request named a model this engine does not serve (HTTP 404,
    ``{"error": "model_unknown"}``) — the model-layer sibling of the
    tier ladder's unknown-tier ValueError."""

    def __init__(self, model: str, known: List[str]):
        super().__init__(
            f"unknown model {model!r}: this engine serves "
            f"{sorted(known) or '(no registered models)'}")
        self.model = model
        self.known = sorted(known)

    def __str__(self) -> str:  # KeyError quotes its arg; keep it readable
        return self.args[0]


class ModelStoreError(RuntimeError):
    """Typed store failure: missing/torn version, hash mismatch, or an
    immutability violation (publishing over an existing version)."""


class ModelVersionExists(ModelStoreError):
    """Publish refused: the version already exists and is complete —
    versions are immutable; publish a NEW version instead."""


def _check_token(kind: str, value: str) -> str:
    if not isinstance(value, str) or not _TOKEN_RE.match(value):
        raise ValueError(
            f"model {kind} {value!r} must match {_TOKEN_RE.pattern} "
            f"(path-safe token; the store builds paths from it)")
    return value


def parse_model_spec(spec: str) -> Tuple[str, Optional[str]]:
    """``"name@version"`` -> (name, version); bare ``"name"`` -> (name,
    None) — the caller resolves None to the store's latest version."""
    if "@" in spec:
        name, _, version = spec.partition("@")
        return _check_token("name", name), _check_token("version", version)
    return _check_token("name", spec), None


def model_coord(name: str, version: str) -> str:
    """The canonical ``name@version`` coordinate every key and metric
    label carries."""
    return f"{name}@{version}"


@dataclasses.dataclass
class RegisteredModel:
    """One loaded model version: the identity coordinate plus the host
    pytree the engine builds its per-worker/per-tier state from.  The
    registry is architecture-agnostic — the version carries its OWN
    ``RaftStereoConfig``, so a registered model may differ from the
    process default in any architecture knob."""

    name: str
    version: str
    config: Any                      # RaftStereoConfig
    variables: Any                   # host pytree ({"params": ...})
    metadata: Optional[Dict[str, Any]] = None

    @property
    def coord(self) -> str:
        return model_coord(self.name, self.version)


class ModelStore:
    """The ``models/<name>/<version>`` namespace of the shared artifact
    store.  Thread-safe; every version directory is written atomically
    by training/checkpoint.py's stage-manifest-commit-rename machinery
    and verified (deep SHA-256) before its weights are ever served."""

    def __init__(self, root: str, subdir: str = MODELS_SUBDIR):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.dir = os.path.join(self.root, subdir)
        self._lock = threading.Lock()

    def _version_dir(self, name: str, version: str) -> str:
        _check_token("name", name)
        _check_token("version", version)
        return os.path.join(self.dir, name, version)

    # -------------------------------------------------------------- publish
    def publish(self, name: str, version: str, config, variables,
                metadata: Optional[Dict[str, Any]] = None,
                force: bool = False) -> str:
        """Snapshot ``(config, variables)`` into the store as
        ``name@version``, atomically (the r20 checkpoint saver: staged
        tmp dir, per-file SHA-256 MANIFEST, COMMIT seal, os.replace).
        Returns the version directory.  Raises ``ModelVersionExists``
        when the version is already complete (immutable) unless
        ``force=True`` — force exists for re-publishing after a torn
        write, not for mutating a served version."""
        from raft_stereo_tpu.training.checkpoint import (is_valid_checkpoint,
                                                         save_checkpoint)

        path = self._version_dir(name, version)
        with self._lock:
            if not force and is_valid_checkpoint(path):
                raise ModelVersionExists(
                    f"model {model_coord(name, version)} already exists "
                    f"in {self.dir} — versions are immutable; publish a "
                    f"new version (or force=True to repair a torn one)")
        tree = {"params": variables.get("params", variables)}
        if isinstance(variables, dict) and variables.get("batch_stats"):
            tree["batch_stats"] = variables["batch_stats"]
        meta = dict(metadata or {})
        meta.setdefault("name", name)
        meta.setdefault("version", version)
        save_checkpoint(path, config, tree, runtime_state=meta)
        log.info("published model %s -> %s",
                 model_coord(name, version), path)
        return path

    # ---------------------------------------------------------------- load
    def load(self, name: str, version: str,
             deep: bool = True) -> RegisteredModel:
        """Load one version as a ``RegisteredModel``; ``deep`` (default)
        verifies every file against the sealed SHA-256 manifest first —
        a corrupt blob raises typed instead of serving wrong weights."""
        from raft_stereo_tpu.training.checkpoint import (is_valid_checkpoint,
                                                         load_runtime_state,
                                                         load_weights,
                                                         verify_manifest)

        path = self._version_dir(name, version)
        if not is_valid_checkpoint(path):
            raise ModelStoreError(
                f"model {model_coord(name, version)} is missing or torn "
                f"under {self.dir}")
        if deep:
            ok, reason = verify_manifest(path)
            if not ok:
                raise ModelStoreError(
                    f"model {model_coord(name, version)} failed deep "
                    f"validation: {reason}")
        cfg, variables = load_weights(path)
        return RegisteredModel(name=name, version=version, config=cfg,
                               variables=variables,
                               metadata=load_runtime_state(path))

    def resolve(self, spec: str, deep: bool = True) -> RegisteredModel:
        """Load a ``name@version`` spec; a bare ``name`` resolves to the
        newest complete version."""
        name, version = parse_model_spec(spec)
        if version is None:
            version = self.latest_version(name)
            if version is None:
                raise ModelStoreError(
                    f"model {name!r} has no complete versions under "
                    f"{self.dir}")
        return self.load(name, version, deep=deep)

    # -------------------------------------------------------------- queries
    def has(self, name: str, version: str) -> bool:
        from raft_stereo_tpu.training.checkpoint import is_valid_checkpoint
        try:
            return is_valid_checkpoint(self._version_dir(name, version))
        except ValueError:
            return False

    def versions(self, name: str) -> List[str]:
        """Complete versions of one model, sorted (publication order is
        not recoverable from names alone; callers wanting the newest use
        ``latest_version`` — mtime-ranked)."""
        from raft_stereo_tpu.training.checkpoint import is_valid_checkpoint
        root = os.path.join(self.dir, _check_token("name", name))
        try:
            entries = sorted(os.listdir(root))
        except OSError:
            return []
        return [e for e in entries
                if ".tmp-" not in e and ".old-" not in e
                and is_valid_checkpoint(os.path.join(root, e))]

    def latest_version(self, name: str) -> Optional[str]:
        root = os.path.join(self.dir, _check_token("name", name))
        best, best_mtime = None, -1.0
        for v in self.versions(name):
            mtime = os.path.getmtime(os.path.join(root, v))
            if mtime > best_mtime:
                best, best_mtime = v, mtime
        return best

    def list_models(self) -> Dict[str, List[str]]:
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return {}
        out = {}
        for n in names:
            if not _TOKEN_RE.match(n):
                continue
            vs = self.versions(n)
            if vs:
                out[n] = vs
        return out

    def verify(self, name: str, version: str) -> Tuple[bool, str]:
        """Deep integrity verdict of one version (``(ok, reason)``) —
        the operator's pre-rollout check."""
        from raft_stereo_tpu.training.checkpoint import verify_manifest
        return verify_manifest(self._version_dir(name, version))
