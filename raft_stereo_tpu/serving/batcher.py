"""Dynamic micro-batcher: bounded queue, shape-bucket grouping, timed flush.

The serving front door (service.StereoService.submit) turns each stereo pair
into a ``Request`` and offers it here.  The batcher groups compatible
requests by their padded-shape bucket — RAFT-Stereo's fixed-iteration GRU
loop makes per-frame device time a function of the padded shape alone
(PAPER.md §1), so same-bucket requests batch with zero compute waste — and
flushes a bucket when it reaches ``max_batch`` or its oldest request has
waited ``max_wait_ms``.  Admission control is a hard bound on queued
requests: past ``max_queue`` the submit raises the typed ``Overloaded``
(load shedding at the door beats collapsing under a backlog), and during a
drain new work is refused the same way while queued work finishes.

Model-agnostic on purpose: ``dispatch(batch)`` is an injected callable (the
service routes it to a device worker pool), so every queueing policy in this
file is testable without touching JAX.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from raft_stereo_tpu.serving.metrics import ServingMetrics


class Overloaded(RuntimeError):
    """Typed load-shed rejection: the bounded queue is full, or the service
    is draining.  Callers should back off and retry (the HTTP layer maps
    this to 429/503 with Retry-After)."""

    def __init__(self, message: str, draining: bool = False):
        super().__init__(message)
        self.draining = draining


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before a device picked it up."""


@dataclasses.dataclass
class Request:
    """One queued stereo pair.  ``payload`` is opaque to the batcher (the
    service stores images + padder there); ``bucket`` keys compatibility.
    ``trace``/``queue_span`` are likewise opaque (telemetry/spans.py
    handles of a sampled request — the service opens/closes them; the
    batcher only carries them across its threads)."""

    bucket: Tuple[int, int]
    payload: object
    future: Future
    t_enqueue: float
    deadline: Optional[float] = None  # absolute monotonic seconds
    trace: Optional[object] = None
    queue_span: Optional[object] = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class MicroBatcher:
    """Bucketed request queue + flush thread.

    ``dispatch(requests)`` runs on the flush thread and is expected to BLOCK
    when the downstream worker pool is saturated — that stall is the
    backpressure path: flushing pauses, the queue fills, and submits shed at
    the ``max_queue`` bound instead of growing an unbounded backlog.
    """

    def __init__(self, dispatch: Callable[[List[Request]], None],
                 max_batch: int = 8, max_wait_ms: float = 5.0,
                 max_queue: int = 64,
                 metrics: Optional[ServingMetrics] = None,
                 clock: Callable[[], float] = time.monotonic):
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        if max_queue < 1:
            raise ValueError(f"max_queue={max_queue} must be >= 1")
        self._dispatch = dispatch
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.max_queue = max_queue
        self.metrics = metrics or ServingMetrics(max_batch=max_batch)
        self._clock = clock
        self._cond = threading.Condition()
        # bucket -> FIFO of requests; dict preserves insertion order so the
        # flush scan visits oldest buckets first
        self._buckets: Dict[Tuple[int, int], List[Request]] = {}
        self._depth = 0
        self._draining = False
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="stereo-batcher")
        self._thread.start()

    # ------------------------------------------------------------ admission
    @property
    def depth(self) -> int:
        with self._cond:
            return self._depth

    @property
    def draining(self) -> bool:
        with self._cond:
            return self._draining

    def submit(self, req: Request) -> None:
        with self._cond:
            if self._draining or self._closed:
                self.metrics.rejected_draining.inc()
                raise Overloaded("service is draining; not accepting work",
                                 draining=True)
            if self._depth >= self.max_queue:
                self.metrics.rejected_queue_full.inc()
                raise Overloaded(
                    f"queue full ({self._depth}/{self.max_queue} requests "
                    f"waiting); retry later")
            self._buckets.setdefault(req.bucket, []).append(req)
            self._depth += 1
            self.metrics.admitted.inc()
            self.metrics.queue_depth.set(self._depth)
            self._cond.notify()

    # ---------------------------------------------------------------- flush
    def _ready_bucket(self, now: float) -> Optional[Tuple[int, int]]:
        """Oldest bucket due for flush: full, past max_wait, or draining."""
        for key, reqs in self._buckets.items():
            if (len(reqs) >= self.max_batch or self._draining
                    or now - reqs[0].t_enqueue >= self.max_wait_s):
                return key
        return None

    def _next_due(self, now: float) -> Optional[float]:
        """Seconds until the earliest bucket hits max_wait; None if empty."""
        if not self._buckets:
            return None
        oldest = min(r[0].t_enqueue for r in self._buckets.values())
        return max(0.0, oldest + self.max_wait_s - now)

    def _run(self) -> None:
        while True:
            with self._cond:
                now = self._clock()
                key = self._ready_bucket(now)
                while key is None and not self._closed:
                    self._cond.wait(timeout=self._next_due(now))
                    now = self._clock()
                    key = self._ready_bucket(now)
                if key is None and self._closed:
                    return
                reqs = self._buckets.pop(key)
                batch, rest = reqs[:self.max_batch], reqs[self.max_batch:]
                if rest:  # burst bigger than max_batch: keep FIFO order
                    # reinsertion puts the remainder last in the scan order,
                    # but its t_enqueue keeps it due immediately
                    self._buckets[key] = rest
                self._depth -= len(batch)
                self.metrics.queue_depth.set(self._depth)
                self._cond.notify_all()  # wake drain() waiters
            # Outside the lock: deadline triage + the (blocking) dispatch.
            live: List[Request] = []
            now = self._clock()
            for r in batch:
                if r.expired(now):
                    self.metrics.deadline_missed.inc()
                    r.future.set_exception(DeadlineExceeded(
                        f"deadline passed after "
                        f"{(now - r.t_enqueue) * 1e3:.1f} ms in queue"))
                else:
                    live.append(r)
            if live:
                self._dispatch(live)

    # ---------------------------------------------------------------- drain
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting (submits raise ``Overloaded``), flush all queued
        requests immediately (no max_wait stalling), and wait until the
        queue is empty.  Returns False on timeout.  Dispatched batches may
        still be running on workers — the service waits for those
        separately."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._depth > 0:
                remaining = (None if deadline is None
                             else deadline - self._clock())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def close(self) -> None:
        """Stop the flush thread.  Queued requests (drain not called, or
        timed out) fail with ``Overloaded`` rather than hanging forever."""
        with self._cond:
            self._closed = True
            self._draining = True
            orphans = [r for reqs in self._buckets.values() for r in reqs]
            self._buckets.clear()
            self._depth = 0
            self.metrics.queue_depth.set(0)
            self._cond.notify_all()
        for r in orphans:
            r.future.set_exception(
                Overloaded("service shut down before this request ran",
                           draining=True))
        self._thread.join(timeout=5.0)


def drain_order(batches: Sequence[Sequence[Request]]) -> List[Request]:
    """Flatten dispatched batches back to admission order (report helper)."""
    return sorted((r for b in batches for r in b), key=lambda r: r.t_enqueue)
