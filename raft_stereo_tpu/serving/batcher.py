"""Continuous-batching request queue: bounded admission, shape buckets,
worker-pull dispatch with batch-size bucket selection.

The serving engine (serving/engine.py) turns each stereo pair into a
``Request`` and offers it here.  Requests group by their padded-shape
bucket — RAFT-Stereo's fixed-iteration GRU loop makes per-frame device
time a function of the padded shape alone (PAPER.md §1), so same-bucket
requests batch with zero compute waste.  Admission control is a hard bound
on queued requests: past ``max_queue`` the submit raises the typed
``Overloaded`` (load shedding at the door beats collapsing under a
backlog), and during a drain new work is refused the same way while queued
work finishes.

Dispatch is **continuous batching**: there is no flush thread and no
``max_wait`` stall — a device worker that goes idle calls ``pop`` and
immediately takes whatever is queued.  ``pop`` picks the bucket whose head
request has waited longest and takes the largest configured batch size the
bucket's depth fills (``pick_batch_size``), so occupancy is set by queue
pressure, not by a timer: below capacity every request dispatches the
moment a worker is free (batch 1, minimum latency); once workers are busy
the queue deepens and the next pop grabs a 4 or an 8.  This replaced the
round-6 MicroBatcher, whose timed flush left the device idle while
requests aged toward ``max_wait_ms`` (BENCH_SERVE_r06.json: queue-wait p95
~4 s at offered 1.91 Hz with the device under-occupied).

Model-agnostic on purpose: the queue never touches JAX, so every
scheduling policy in this file is testable in milliseconds.
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

from raft_stereo_tpu.serving.metrics import ServingMetrics


class Overloaded(RuntimeError):
    """Typed load-shed rejection: the bounded queue is full, or the service
    is draining.  Callers should back off and retry (the HTTP layer maps
    this to 429/503 with Retry-After)."""

    def __init__(self, message: str, draining: bool = False):
        super().__init__(message)
        self.draining = draining


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before a device picked it up."""


class RequestPoisoned(RuntimeError):
    """Typed terminal failure of the supervised-recovery path: this
    request's dispatch crashed on every one of its bounded attempts, so
    it is failed individually instead of being retried forever or taking
    the server down.  ``last_error`` is the final dispatch's exception."""

    def __init__(self, message: str, attempts: int,
                 last_error: Optional[BaseException] = None):
        super().__init__(message)
        self.attempts = attempts
        self.last_error = last_error


@dataclasses.dataclass(eq=False)   # identity equality: payloads hold arrays
class Request:
    """One queued stereo pair.  ``payload`` is opaque to the queue (the
    engine stores images + padder there); ``bucket`` keys compatibility.
    ``tier`` extends the compatibility key: requests of different latency
    tiers run different compiled programs (per-tier early-exit knobs,
    serving/engine.py), so they never share a dispatch batch.
    ``trace``/``queue_span`` are likewise opaque (telemetry/spans.py
    handles of a sampled request — the engine opens/closes them; the
    queue only carries them across its threads)."""

    bucket: Tuple[int, int]
    payload: object
    future: Future
    t_enqueue: float
    deadline: Optional[float] = None  # absolute monotonic seconds
    tier: Optional[str] = None
    trace: Optional[object] = None
    queue_span: Optional[object] = None
    # Supervised-recovery bookkeeping (serving/engine.py): dispatch
    # attempts so far (a crashed dispatch requeues the request until the
    # engine's bound poisons it), and the tier the CLIENT asked for when
    # brownout degradation reroutes ``tier`` down the ladder
    # (``requested_tier is None`` means no degradation happened).
    attempts: int = 0
    requested_tier: Optional[str] = None
    # Executable family (serving/engine.py streaming sessions): None =
    # the base sessionless program; "state" = session cold frames (the
    # program additionally returns the low-res state); "warm" = session
    # warm frames (the program also CONSUMES a flow_init input).  Part
    # of the compatibility key below — the three families are distinct
    # compiled programs and must never share a dispatch batch.  Frames
    # of ONE session never coexist in the queue at all (the engine holds
    # the session's ordering lock from submit to resolution), so a
    # dispatch cycle cannot reorder a session's frames.
    family: Optional[str] = None
    session_id: Optional[str] = None
    # Model coordinate (serving/models.py registry): the registered
    # ``name`` this request's dispatch must consume the weights of.
    # None = the engine's implicit constructor model — the pre-registry
    # build, byte-identical.  Part of the compatibility key: two models
    # share shapes but never a dispatch batch (a batch is ONE forward
    # against ONE variables tree).
    model: Optional[str] = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    @property
    def group_key(self) -> Tuple:
        """What batches together: same padded bucket, same tier, same
        executable family (base / session-state / warm), same model."""
        return (self.bucket, self.tier, self.family, self.model)


def edf_key(req: Request) -> float:
    """The EDF scheduler's priority of one request: its absolute
    deadline, or — for deadline-less requests — its enqueue stamp.
    Both are monotonic-clock seconds, and an enqueue stamp is always in
    the past while a live deadline is in the future, so deadline-less
    requests sort AHEAD of every deadline-carrying one that arrived
    after their enqueue: a stream flood can never starve plain traffic
    (the no-starvation contract, tests/test_edf.py)."""
    return req.t_enqueue if req.deadline is None else req.deadline


def edf_slack_end(reqs: Sequence[Request], now: float,
                  max_slack_s: float, est_latency_s: float) -> float:
    """The absolute monotonic time an EDF pop may wait until before
    dispatching this group — the deliberate-coalescing window.

    Two hard bounds, both ANCHORED (absolute, so a re-evaluating waiter
    converges instead of sliding):

    * ``head_enqueue + max_slack_s`` — no request waits more than the
      configured slack beyond its arrival just to fatten a batch;
    * ``nearest_deadline - est_latency_s`` — the wait must leave the
      bucket's measured dispatch latency before the earliest deadline
      in the group, so coalescing can delay a frame but never be the
      REASON it misses (the bounded-slack contract).

    Groups with no deadline-carrying member return ``now`` — plain
    requests keep today's immediate-pop behavior."""
    deadlines = [r.deadline for r in reqs if r.deadline is not None]
    if not deadlines:
        return now
    head_enqueue = min(r.t_enqueue for r in reqs)
    return min(head_enqueue + max_slack_s,
               min(deadlines) - est_latency_s)


def pick_batch_size(depth: int, sizes: Sequence[int]) -> int:
    """The batch size a pop at queue depth ``depth`` dispatches: the
    largest compiled bucket size the depth fills.  A partial batch (depth
    between two sizes) dispatches at the next size down rather than being
    padded up — the batch axis carries no filler frames, ever; the
    remainder stays queued and the next free worker takes it immediately.
    ``sizes`` must be ascending and start at 1 (the engine validates)."""
    if depth < 1:
        raise ValueError(f"depth={depth} must be >= 1")
    fit = [s for s in sizes if s <= depth]
    if not fit:
        raise ValueError(f"no batch size in {tuple(sizes)} fits depth "
                         f"{depth}; sizes must include 1")
    return fit[-1]


def decompose_batch(n: int, sizes: Sequence[int]) -> List[int]:
    """Split ``n`` requests into dispatch chunks of configured sizes,
    largest-first (greedy): 7 -> [4, 2, 1] with the default 1/2/4/8 set.
    Used when deadline triage shrinks a popped batch below the size the
    scheduler picked — every device dispatch still runs a compiled
    batch-size bucket, never an ad-hoc batch axis."""
    out: List[int] = []
    while n > 0:
        k = pick_batch_size(n, sizes)
        out.append(k)
        n -= k
    return out


class BucketQueue:
    """Bucketed request queue for continuous batching.

    ``submit`` is the bounded front door (``Overloaded`` past ``max_queue``
    or while draining); ``pop`` is the worker side — it blocks until work
    is queued, then returns the oldest bucket's head requests at the batch
    size ``pick_batch_size`` selects.  Backpressure needs no extra
    machinery: a saturated worker pool simply stops popping, the queue
    fills, and submits shed at the bound.
    """

    def __init__(self, max_batch: int = 8,
                 batch_sizes: Sequence[int] = (1, 2, 4, 8),
                 max_queue: int = 64,
                 metrics: Optional[ServingMetrics] = None,
                 clock=time.monotonic,
                 edf: bool = False,
                 edf_max_slack_s: float = 0.05,
                 latency_fn=None):
        """``edf=True`` turns on the round-19 deadline-aware pop policy:
        groups are taken earliest-deadline-first (``edf_key``) and a pop
        whose group cannot yet fill the largest compiled batch size
        WAITS a bounded slack (``edf_slack_end``: at most
        ``edf_max_slack_s`` past the head's arrival and never closer to
        the nearest deadline than the bucket's measured dispatch
        latency) to deliberately coalesce concurrent sessions' frames
        into one batch-N dispatch.  ``latency_fn(group_key, batch_size)
        -> seconds | None`` supplies that measured latency (the engine
        feeds a per-group EWMA of its dispatch wall); None/absent
        estimates 0.  Deadline-LESS requests keep today's immediate-pop
        FIFO behavior under either policy, and ``edf=False`` (default)
        leaves the existing pop path untouched."""
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        if max_queue < 1:
            raise ValueError(f"max_queue={max_queue} must be >= 1")
        if edf_max_slack_s < 0:
            raise ValueError(f"edf_max_slack_s={edf_max_slack_s} must "
                             f"be >= 0")
        self.edf = bool(edf)
        self.edf_max_slack_s = float(edf_max_slack_s)
        self._latency_fn = latency_fn
        sizes = sorted(set(int(s) for s in batch_sizes if s <= max_batch))
        if not sizes or sizes[0] != 1 or any(s < 1 for s in sizes):
            raise ValueError(
                f"batch_sizes={tuple(batch_sizes)} must be positive and "
                f"include 1 after capping at max_batch={max_batch}")
        self.sizes = tuple(sizes)
        self.max_batch = max_batch
        self.max_queue = max_queue
        self.metrics = metrics or ServingMetrics(max_batch=max_batch)
        self._clock = clock
        self._cond = threading.Condition()
        # (bucket, tier) -> FIFO of requests; the pop scan picks the group
        # whose head request has waited longest (global FIFO across
        # groups).
        self._buckets: Dict[Tuple, List[Request]] = {}
        self._depth = 0
        self._draining = False
        self._closed = False
        self._paused = False   # test hook: stage submits, then release

    # ------------------------------------------------------------ admission
    @property
    def depth(self) -> int:
        with self._cond:
            return self._depth

    @property
    def draining(self) -> bool:
        with self._cond:
            return self._draining

    def submit(self, req: Request) -> None:
        with self._cond:
            if self._draining or self._closed:
                self.metrics.rejected_draining.inc()
                raise Overloaded("service is draining; not accepting work",
                                 draining=True)
            if self._depth >= self.max_queue:
                self.metrics.rejected_queue_full.inc()
                raise Overloaded(
                    f"queue full ({self._depth}/{self.max_queue} requests "
                    f"waiting); retry later")
            self._buckets.setdefault(req.group_key, []).append(req)
            self._depth += 1
            self.metrics.admitted.inc()
            self.metrics.queue_depth.set(self._depth)
            # notify_all, not notify: with worker CLASSES (solo vs xl
            # device groups, serving/engine.py) a single wake could land
            # on a worker whose ``want`` filter rejects this request's
            # group while an eligible worker sleeps on.
            self._cond.notify_all()

    def requeue(self, reqs: Sequence[Request]) -> int:
        """Re-admit requests whose dispatch crashed (supervised recovery,
        serving/engine.py).  Returns how many actually re-entered.

        Differs from ``submit`` deliberately:

        * **no admission bound** — these requests were already admitted
          once; shedding them now would turn a transient device fault
          into client-visible drops while fresh submits still succeed;
        * **allowed while draining** — a drain must finish admitted work,
          and that includes work bounced by a crash mid-drain (``close``
          still fails them: the queue is gone);
        * **ordered by admission time** — each request is inserted into
          its bucket's FIFO by ``t_enqueue``, so a retried request rejoins
          AHEAD of fresh requests that arrived after it (crashes must not
          also cost queue position);
        * **deduplicated** — a request already present in its bucket
          (identity) or already resolved (its future is done: poisoned,
          deadline-failed, or raced to completion) is skipped, so no
          request can be dispatched twice.
        """
        requeued = 0
        with self._cond:
            if self._closed:
                failed = [r for r in reqs if not r.future.done()]
            else:
                failed = []
                for r in reqs:
                    if r.future.done():
                        continue
                    fifo = self._buckets.setdefault(r.group_key, [])
                    if any(q is r for q in fifo):
                        continue
                    keys = [q.t_enqueue for q in fifo]
                    fifo.insert(bisect.bisect_right(keys, r.t_enqueue), r)
                    self._depth += 1
                    requeued += 1
                self.metrics.queue_depth.set(self._depth)
                if requeued:
                    self._cond.notify_all()
        for r in failed:
            r.future.set_exception(
                Overloaded("service shut down before this request could "
                           "be retried", draining=True))
        return requeued

    # ----------------------------------------------------------------- pop
    def _oldest_bucket(self, want=None) -> Optional[Tuple]:
        key, oldest = None, None
        for k, reqs in self._buckets.items():
            if want is not None and not want(k):
                continue
            if reqs and (oldest is None or reqs[0].t_enqueue < oldest):
                key, oldest = k, reqs[0].t_enqueue
        return key

    def _edf_bucket(self, want=None) -> Optional[Tuple]:
        """EDF group selection: the group holding the globally smallest
        ``edf_key`` (earliest deadline; enqueue stamp for deadline-less
        requests, which therefore sort ahead of any later stream
        flood)."""
        key, best = None, None
        for k, reqs in self._buckets.items():
            if want is not None and not want(k):
                continue
            if not reqs:
                continue
            head = min(edf_key(r) for r in reqs)
            if best is None or head < best:
                key, best = k, head
        return key

    def _edf_slack_end_locked(self, group_key: Tuple,
                              reqs: List[Request], now: float,
                              sizes: Sequence[int]) -> float:
        est = 0.0
        if self._latency_fn is not None:
            measured = self._latency_fn(group_key, sizes[-1])
            if measured is not None:
                est = float(measured)
        return edf_slack_end(reqs, now, self.edf_max_slack_s, est)

    def pop(self, timeout: Optional[float] = None, want=None,
            sizes: Optional[Sequence[int]] = None
            ) -> Optional[List[Request]]:
        """Take the next dispatch batch, blocking until one is available.

        Returns the oldest bucket's head ``pick_batch_size(depth)``
        requests with deadline-expired ones triaged out (their futures
        fail with ``DeadlineExceeded``), or None when the queue is closed
        (worker shutdown) or ``timeout`` elapsed.  The survivors are
        counted into ``metrics.inflight`` before the lock drops, so
        ``drain``'s depth==0 + inflight==0 check never misses a batch in
        hand.

        ``want`` (group-key predicate) restricts which groups this
        caller may take — how the engine keeps mesh-sharded xl work on
        the xl device groups and everything else on the solo workers
        without a second queue (one admission bound, one depth gauge,
        one drain).  ``sizes`` overrides the batch-size ladder for this
        pop (xl buckets compile their own, typically shorter, ladder)."""
        deadline = None if timeout is None else self._clock() + timeout
        sizes = self.sizes if sizes is None else tuple(sizes)
        while True:
            with self._cond:
                while not self._closed and (
                        self._paused or self._oldest_bucket(want) is None):
                    remaining = (None if deadline is None
                                 else deadline - self._clock())
                    if remaining is not None and remaining <= 0:
                        return None
                    self._cond.wait(timeout=remaining)
                if self._closed:
                    return None
                if self.edf:
                    key = self._edf_bucket(want)
                    reqs = self._buckets[key]
                    now_edf = self._clock()
                    if len(reqs) < sizes[-1]:
                        # Bounded-slack coalescing: hold this pop open a
                        # beat so concurrent sessions' frames merge into
                        # a bigger compiled batch instead of an idle
                        # worker instantly dispatching batch-1.  The
                        # wake time is absolute (edf_slack_end), so
                        # re-evaluation converges; a submit filling the
                        # largest size notifies and the re-check
                        # dispatches immediately.
                        # Clamped at now + max_slack: the anchors are
                        # absolute (enqueue stamps / deadlines), so with
                        # a well-behaved clock the clamp is a no-op —
                        # it only guards against a stalled or injected
                        # clock turning the wait into a busy loop.
                        wake = min(
                            self._edf_slack_end_locked(
                                key, reqs, now_edf, sizes),
                            now_edf + self.edf_max_slack_s)
                        if wake > now_edf:
                            self.metrics.edf_slack_waits.inc()
                            self._cond.wait(timeout=wake - now_edf)
                            continue   # re-evaluate under the lock
                    k = pick_batch_size(len(reqs), sizes)
                    # Earliest-deadline-first WITHIN the group too: the
                    # popped batch is the k most urgent members (stable
                    # on ties, so FIFO is preserved among equals).
                    order = sorted(range(len(reqs)),
                                   key=lambda i: (edf_key(reqs[i]), i))
                    take = frozenset(order[:k])
                    batch = [reqs[i] for i in sorted(take)]
                    rest = [r for i, r in enumerate(reqs)
                            if i not in take]
                else:
                    key = self._oldest_bucket(want)
                    reqs = self._buckets[key]
                    k = pick_batch_size(len(reqs), sizes)
                    batch, rest = reqs[:k], reqs[k:]
                if rest:
                    self._buckets[key] = rest
                else:
                    del self._buckets[key]
                self._depth -= len(batch)
                self.metrics.queue_depth.set(self._depth)
                # Deadline triage inside the lock's shadow: expired
                # requests never count inflight.
                now = self._clock()
                live = [r for r in batch if not r.expired(now)]
                expired = [r for r in batch if r.expired(now)]
                self.metrics.inflight.inc(len(live))
                self._cond.notify_all()  # wake drain() waiters
            for r in expired:
                self.metrics.deadline_missed.inc()
                r.future.set_exception(DeadlineExceeded(
                    f"deadline passed after "
                    f"{(now - r.t_enqueue) * 1e3:.1f} ms in queue"))
            if live:
                return live
            # every popped request had expired: go take the next batch

    # ------------------------------------------------------------ test hook
    def pause(self) -> None:
        """Stage mode for tests: submits queue up but ``pop`` blocks, so a
        test can build an exact queue depth before releasing the workers."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    # ---------------------------------------------------------------- drain
    def stop_admitting(self) -> None:
        """Flip to draining WITHOUT waiting: fresh submits shed with the
        typed draining ``Overloaded`` while queued work keeps flowing to
        the workers (and crashed dispatches may still ``requeue``).
        ``drain()`` is stop_admitting + wait-for-empty; the engine uses
        this split so its drain can wait on queue depth, inflight count,
        and pending retries as ONE combined condition."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting (submits raise ``Overloaded``) and wait until the
        workers have popped everything queued.  Returns False on timeout.
        Popped batches may still be running on workers — the engine waits
        on ``metrics.inflight`` separately."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._depth > 0:
                remaining = (None if deadline is None
                             else deadline - self._clock())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def close(self) -> None:
        """Stop the queue: blocked ``pop`` calls return None (worker
        shutdown), and queued requests (drain not called, or timed out)
        fail with ``Overloaded`` rather than hanging forever."""
        with self._cond:
            self._closed = True
            self._draining = True
            orphans = [r for reqs in self._buckets.values() for r in reqs]
            self._buckets.clear()
            self._depth = 0
            self.metrics.queue_depth.set(0)
            self._cond.notify_all()
        for r in orphans:
            r.future.set_exception(
                Overloaded("service shut down before this request ran",
                           draining=True))


def drain_order(batches: Sequence[Sequence[Request]]) -> List[Request]:
    """Flatten dispatched batches back to admission order (report helper)."""
    return sorted((r for b in batches for r in b), key=lambda r: r.t_enqueue)
