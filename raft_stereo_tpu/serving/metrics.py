"""Serving observability: the serving instrument set over the SHARED
registry (telemetry/registry.py).

The Counter/Gauge/Histogram/MetricsRegistry implementations started life in
this module; PR 3 promoted them to ``raft_stereo_tpu.telemetry.registry`` as
the single implementation the training runtime and bench tooling share, and
this module re-exports them so every existing ``serving.metrics`` import
keeps working unchanged.  ``ServingMetrics`` — the serving subsystem's
standard instrument set — still lives here.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from raft_stereo_tpu.telemetry.registry import (  # noqa: F401 — re-exports
    DEFAULT_LATENCY_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry)

__all__ = ["DEFAULT_LATENCY_BUCKETS", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "ServingMetrics"]


class ServingMetrics:
    """The serving subsystem's standard instrument set, in one place so the
    batcher / workers / HTTP layer all record into the same names.

    Latency is split into the three legs the product-path profiling
    established as the interesting decomposition (PRODUCT_r03/r04,
    profiling.py): queue wait (admission -> device worker pickup), device
    time (dispatch -> outputs ready), and fetch (device->host transfer of
    the results).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 max_batch: int = 8):
        r = registry or MetricsRegistry()
        self.registry = r
        self.admitted = r.counter(
            "serve_requests_admitted_total", "requests accepted into the queue")
        self.rejected_queue_full = r.counter(
            "serve_requests_rejected_queue_full_total",
            "requests shed because the bounded queue was full")
        self.rejected_draining = r.counter(
            "serve_requests_rejected_draining_total",
            "requests refused while the service was draining")
        self.deadline_missed = r.counter(
            "serve_requests_deadline_missed_total",
            "requests dropped at dispatch because their deadline had passed")
        self.completed = r.counter(
            "serve_requests_completed_total", "requests answered successfully")
        self.failed = r.counter(
            "serve_requests_failed_total", "requests failed with an error")
        self.batches = r.counter(
            "serve_batches_total", "micro-batches dispatched to a device")
        self.queue_depth = r.gauge(
            "serve_queue_depth", "requests waiting in the batcher")
        self.inflight = r.gauge(
            "serve_inflight_requests", "requests on a device worker right now")
        self.batch_occupancy = r.histogram(
            "serve_batch_occupancy", "requests per dispatched micro-batch",
            buckets=tuple(range(1, max(2, max_batch) + 1)))
        self.queue_wait = r.histogram(
            "serve_queue_wait_seconds", "admission -> worker pickup")
        self.device_time = r.histogram(
            "serve_device_seconds",
            "forward dispatch -> outputs ready (advisory behind an async "
            "device tunnel, where readiness reports at dispatch — see "
            "profiling.py; the fetch leg below is always honest)")
        self.fetch_time = r.histogram(
            "serve_fetch_seconds", "device->host transfer of the results")
        self.total_latency = r.histogram(
            "serve_total_latency_seconds", "admission -> response ready")
        self.anomalies = r.counter(
            "serve_anomalies_total",
            "anomalies detected (queue saturation, deadline-miss rate)")
        self.last_batch_unix = r.gauge(
            "serve_last_batch_unix_seconds",
            "wall-clock time the last micro-batch finished (0 until one "
            "does)")
        self._age_lock = threading.Lock()
        self._last_batch_mono: Optional[float] = None

    def note_batch_done(self) -> None:
        """Stamp micro-batch completion — the freshness signal behind
        ``/healthz``'s ``last_batch_age_s`` (a serving twin of the train
        loop's ``last_step_age_s``)."""
        self.last_batch_unix.set(time.time())
        with self._age_lock:
            self._last_batch_mono = time.monotonic()

    def last_batch_age_s(self) -> Optional[float]:
        """Seconds since the last micro-batch finished; None before the
        first one (an idle-from-boot service is not stale, it is idle)."""
        with self._age_lock:
            last = self._last_batch_mono
        return (round(time.monotonic() - last, 3)
                if last is not None else None)

    def render_text(self) -> str:
        return self.registry.render_text()
