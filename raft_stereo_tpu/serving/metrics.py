"""Serving observability: the serving instrument set over the SHARED
registry (telemetry/registry.py).

The Counter/Gauge/Histogram/MetricsRegistry implementations started life in
this module; PR 3 promoted them to ``raft_stereo_tpu.telemetry.registry`` as
the single implementation the training runtime and bench tooling share, and
this module re-exports them so every existing ``serving.metrics`` import
keeps working unchanged.  ``ServingMetrics`` — the serving subsystem's
standard instrument set — still lives here.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from raft_stereo_tpu.telemetry.registry import (  # noqa: F401 — re-exports
    DEFAULT_LATENCY_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry)

__all__ = ["DEFAULT_LATENCY_BUCKETS", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "ServingMetrics", "PADDING_WASTE_BUCKETS",
           "SEAM_EPE_BUCKETS"]

# Waste-fraction buckets for serve_padding_waste: fraction of dispatched
# pixels that were padding (0 = every pixel real).  KITTI's /32 pad wastes
# ~2.3% (375x1242 -> 384x1248); a stack-mode pow2 batch fill can waste up
# to ~50%, hence the wide top end.
PADDING_WASTE_BUCKETS = (0.005, 0.01, 0.02, 0.05, 0.1, 0.15, 0.2,
                         0.3, 0.5, 0.75)

# GRU-iteration buckets for infer_gru_iters_used: trip counts, not
# seconds.  Covers the realtime depth (7), the accuracy depth (32), and
# headroom past it.
ITERS_USED_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)

# Seam-error buckets for serve_tile_seam_epe: mean |Δdisparity| (px)
# between adjacent tiles' predictions on their overlap rows
# (serving/tiles.py).  Consistent tiles sit at ~0; values past ~1 px mean
# the halo is not carrying enough vertical context for this content.
SEAM_EPE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0)

# Inter-frame delta buckets for serve_session_frame_delta: mean
# |Δintensity| (0..255) between consecutive frames' thumbnails.  Video at
# normal motion sits in the low single digits; a hard scene cut jumps
# past the default 40-unit threshold, hence the wide top end.
FRAME_DELTA_BUCKETS = (0.5, 1, 2, 4, 8, 16, 32, 64, 128, 255)


class ServingMetrics:
    """The serving subsystem's standard instrument set, in one place so the
    batcher / workers / HTTP layer all record into the same names.

    Latency is split into the three legs the product-path profiling
    established as the interesting decomposition (PRODUCT_r03/r04,
    profiling.py): queue wait (admission -> device worker pickup), device
    time (dispatch -> outputs ready), and fetch (device->host transfer of
    the results).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 max_batch: int = 8):
        r = registry or MetricsRegistry()
        self.registry = r
        self.admitted = r.counter(
            "serve_requests_admitted_total", "requests accepted into the queue")
        self.rejected_queue_full = r.counter(
            "serve_requests_rejected_queue_full_total",
            "requests shed because the bounded queue was full")
        self.rejected_draining = r.counter(
            "serve_requests_rejected_draining_total",
            "requests refused while the service was draining")
        self.deadline_missed = r.counter(
            "serve_requests_deadline_missed_total",
            "requests dropped at dispatch because their deadline had passed")
        self.completed = r.counter(
            "serve_requests_completed_total", "requests answered successfully")
        self.failed = r.counter(
            "serve_requests_failed_total", "requests failed with an error")
        self.batches = r.counter(
            "serve_batches_total", "micro-batches dispatched to a device")
        self.queue_depth = r.gauge(
            "serve_queue_depth", "requests waiting in the batcher")
        self.inflight = r.gauge(
            "serve_inflight_requests", "requests on a device worker right now")
        self.batch_occupancy = r.histogram(
            "serve_batch_occupancy", "requests per dispatched micro-batch",
            buckets=tuple(range(1, max(2, max_batch) + 1)))
        self.queue_wait = r.histogram(
            "serve_queue_wait_seconds", "admission -> worker pickup")
        self.device_time = r.histogram(
            "serve_device_seconds",
            "forward dispatch -> outputs ready (advisory behind an async "
            "device tunnel, where readiness reports at dispatch — see "
            "profiling.py; the fetch leg below is always honest)")
        self.fetch_time = r.histogram(
            "serve_fetch_seconds", "device->host transfer of the results")
        self.total_latency = r.histogram(
            "serve_total_latency_seconds", "admission -> response ready")
        self.anomalies = r.counter(
            "serve_anomalies_total",
            "anomalies detected (queue saturation, deadline-miss rate)")
        # Resilience instruments (serving/engine.py supervised recovery +
        # serving/resilience.py): the failure story's audit trail — every
        # crashed dispatch must show up as retries that converge, a
        # poisoned request, or a breaker transition, never as silence.
        self.retries = r.counter(
            "serve_retries_total",
            "requests requeued after a crashed dispatch (each retry hop "
            "counts once)")
        self.worker_restarts = r.counter(
            "serve_worker_restarts_total",
            "device worker threads restarted by the engine supervisor "
            "after a dispatch crash")
        self.poisoned = r.counter(
            "serve_requests_poisoned_total",
            "requests failed with the typed RequestPoisoned after "
            "exhausting their dispatch attempts")
        self.degraded = r.counter(
            "serve_requests_degraded_total",
            "requests answered at a cheaper tier than requested "
            "(brownout degradation)")
        self.brownout_level = r.gauge(
            "serve_brownout_level",
            "current brownout degradation level (0 = off; each level "
            "pushes eligible requests one rung down the tier ladder)")
        self.compiles_cold = r.counter(
            "serve_compiles_cold_total",
            "serving executables built by XLA compilation (cold)")
        self.compiles_warm = r.counter(
            "serve_compiles_warm_total",
            "serving executables restored from the persistent disk "
            "cache (warm — no XLA compile paid)")
        self.persist_cache_bytes = r.gauge(
            "serve_persist_cache_bytes",
            "bytes of serialized executables in the persistent artifact "
            "store (post-GC; 0 without executable_cache_dir)")
        self._circuit_lock = threading.Lock()
        self._circuit_by_device: Dict[int, Gauge] = {}
        self._chaos_lock = threading.Lock()
        self._chaos_by_kind: Dict[str, Counter] = {}
        # Engine dispatch accounting: serve_batches_total counts device
        # dispatches (the "fewer dispatches than requests" batching win is
        # completed/batches), and the per-batch-size family shows which
        # bucket ladder rungs traffic actually exercises.
        self._dispatch_lock = threading.Lock()
        self._dispatch_by_size: Dict[int, Counter] = {}
        self.bucket_refinements = r.counter(
            "serve_bucket_refinements_total",
            "spatial buckets refined to a finer pad grid by the measured "
            "padding-waste feedback loop (adaptive_buckets)")
        # Padding-waste accounting (telemetry/costs.py motivates it): the
        # device runs padded shapes, so wasted pixels are wasted flops in
        # exact proportion — the /32 spatial pad plus stack mode's pow2
        # batch fill.  Complements serve_batch_occupancy (which only sees
        # request counts, not pixel geometry).
        self.padding_waste = r.histogram(
            "serve_padding_waste",
            "per-dispatch fraction of device pixels that were padding "
            "(spatial /32 pad + stack-mode pow2 batch fill)",
            buckets=PADDING_WASTE_BUCKETS)
        self.dispatched_flops = r.counter(
            "serve_dispatched_flops_total",
            "model FLOPs dispatched to the device (compiled-executable "
            "cost x dispatches; 0 without cost telemetry)")
        self.achieved_flops_per_s = r.gauge(
            "serve_achieved_flops_per_s",
            "dispatched FLOP/s over the rolling MFU window (0 without "
            "cost telemetry)")
        self.mfu = r.gauge(
            "serve_mfu",
            "model FLOP utilization: achieved FLOP/s / device peak (0 "
            "without cost telemetry or with an unknown peak)")
        # Streaming-session instruments (serving/sessions.py +
        # engine.submit_session): the warm-start story's audit trail —
        # how many streams are live, how their frames split warm vs cold,
        # and how temporally coherent the traffic actually is (the
        # inter-frame delta the scene-cut fallback gates on).
        self.sessions_active = r.gauge(
            "serve_sessions_active",
            "live streaming sessions holding warm-start state")
        self.sessions_created = r.counter(
            "serve_sessions_created_total", "streaming sessions opened")
        self.sessions_expired = r.counter(
            "serve_sessions_expired_total",
            "streaming sessions expired by the TTL sweep")
        self.sessions_evicted = r.counter(
            "serve_sessions_evicted_total",
            "streaming sessions evicted at LRU capacity")
        self.scene_cuts = r.counter(
            "serve_session_scene_cuts_total",
            "session frames that fell back to a cold start because the "
            "inter-frame delta check failed (scene cut)")
        self.session_reseeds = r.counter(
            "serve_session_reseeds_total",
            "session states dropped by the keyframe guard: a warm frame "
            "ran to the iteration cap without converging, so the next "
            "frame cold-starts (session_reseed_on_cap)")
        self.ctx_cache_hits = r.counter(
            "serve_session_ctx_cache_hits_total",
            "session frames served with the cached context bundle (the "
            "context encoder never ran — session_ctx_cache; the "
            "X-Ctx-Cached response header marks these)")
        self.sessions_exported = r.counter(
            "serve_sessions_exported_total",
            "streaming sessions serialized into a graceful-drain "
            "handoff blob (engine.publish_handoff — these streams move "
            "to a survivor instead of 410ing)")
        self.sessions_adopted = r.counter(
            "serve_sessions_adopted_total",
            "streaming sessions whose state was imported from another "
            "replica's handoff blob at the session's first frame here "
            "(X-Handoff-Artifact; the frame dispatches WARM)")
        # serve_handoff_import_skipped_total{reason=...}: a labeled
        # family (round 19) — "corrupt" entries failed their checksum /
        # parse; "config_mismatch" blobs carried another exec-config
        # fingerprint than this engine compiles (r18 follow-up: the
        # mismatch is TYPED, never a silent cold start).
        self._handoff_skip_lock = threading.Lock()
        self._handoff_skip_by_reason: Dict[str, Counter] = {}
        self.frame_delta = r.histogram(
            "serve_session_frame_delta",
            "mean |delta intensity| (0..255) between consecutive session "
            "frames' thumbnails — the scene-cut gate's input",
            buckets=FRAME_DELTA_BUCKETS)
        self._session_frame_lock = threading.Lock()
        self._session_frames_by_mode: Dict[str, Counter] = {}
        # Per-model request accounting (round 21 multi-model serving).
        # Lazily labeled like every family here: a single-model engine
        # never touches it, so its /metrics stay byte-identical.
        self._model_req_lock = threading.Lock()
        self._model_req_by_coord: Dict[Tuple[str, str], Counter] = {}
        self._bucket_lock = threading.Lock()
        self._bucket_px: Dict[str, Tuple[Counter, Counter]] = {}
        # Adaptive early-exit accounting (serving/engine.py per-tier
        # executables): the per-tier trip-count histogram family
        # infer_gru_iters_used{tier=...} and the iterations-saved counter
        # family — (configured depth - iters_used) summed over every
        # request, i.e. the GRU compute the convergence gate recovered.
        self._iters_lock = threading.Lock()
        self._iters_by_tier: Dict[str, Tuple[Histogram, Counter]] = {}
        # XL tier + tiling instruments (serving/engine.py xl mesh groups,
        # serving/tiles.py): how much big-image traffic runs sharded, how
        # much falls back to tiles, and what the tiles' measured seam
        # disagreement is.  The per-(mesh, bucket) HBM gauge family
        # surfaces the sharding win itself — per-device bytes from the xl
        # executable's memory_analysis, directly comparable to the solo
        # bucket's record in /debug/compiles.
        self.xl_dispatches = r.counter(
            "serve_xl_dispatches_total",
            "device-group dispatches of mesh-sharded xl bucket "
            "executables")
        self.tiled_requests = r.counter(
            "serve_tiled_requests_total",
            "requests answered by halo-overlap tiling (stitched from "
            "multiple bucket dispatches)")
        self.tile_seam_epe = r.histogram(
            "serve_tile_seam_epe",
            "mean |delta disparity| (px) between adjacent tiles' "
            "predictions on their overlap rows — the measured accuracy "
            "cost of tiling (serving/tiles.py)",
            buckets=SEAM_EPE_BUCKETS)
        self._xl_hbm_lock = threading.Lock()
        self._xl_hbm: Dict[Tuple[str, str], Gauge] = {}
        # EDF scheduler accounting (round 19, serving/batcher.py): how
        # often a pop deliberately held open to coalesce concurrent
        # sessions' frames.  The coalescing RESULT reads off the
        # existing serve_requests_completed_total / serve_batches_total
        # ratio (frames per dispatch).
        self.edf_slack_waits = r.counter(
            "serve_edf_slack_waits_total",
            "EDF pops that waited a bounded slack to coalesce "
            "deadline-carrying frames into a larger batch "
            "(edf_scheduler; 0 with the policy off)")
        self.last_batch_unix = r.gauge(
            "serve_last_batch_unix_seconds",
            "wall-clock time the last micro-batch finished (0 until one "
            "does)")
        self._age_lock = threading.Lock()
        self._last_batch_mono: Optional[float] = None

    def xl_hbm_gauge(self, mesh: str, bucket: str) -> Gauge:
        """``serve_xl_hbm_bytes{mesh=,bucket=}``: per-device HBM of one
        compiled xl bucket executable (CompileRecord.hbm_bytes — the
        ROWSGRU_MEMORY scaling claim, measured through the serving path).
        ``mesh`` is the compact spec label (``"rows4"``); the solo
        comparison row uses ``mesh="solo"``."""
        with self._xl_hbm_lock:
            g = self._xl_hbm.get((mesh, bucket))
            if g is None:
                g = self.registry.gauge(
                    "serve_xl_hbm_bytes",
                    "per-device HBM bytes of a compiled xl bucket "
                    "executable (memory_analysis via the compile-cost "
                    "registry; 0 when the analysis degraded)",
                    labels={"mesh": mesh, "bucket": bucket})
                self._xl_hbm[(mesh, bucket)] = g
        return g

    def circuit_gauge(self, device_index: int) -> Gauge:
        """The ``serve_circuit_state{device="N"}`` gauge for one device
        worker: 0 closed, 1 open (quarantined), 2 half-open (probing)."""
        with self._circuit_lock:
            g = self._circuit_by_device.get(device_index)
            if g is None:
                g = self.registry.gauge(
                    "serve_circuit_state",
                    "per-device circuit breaker state (0 closed, 1 open/"
                    "quarantined, 2 half-open/probing)",
                    labels={"device": str(device_index)})
                self._circuit_by_device[device_index] = g
        return g

    def observe_injected_fault(self, kind: str) -> None:
        """Count one injected chaos fault into the per-kind
        ``serve_chaos_injected_total`` family (serving/chaos.py wires
        this as the injector's observe hook)."""
        with self._chaos_lock:
            c = self._chaos_by_kind.get(kind)
            if c is None:
                c = self.registry.counter(
                    "serve_chaos_injected_total",
                    "faults injected by the chaos harness, by kind",
                    labels={"kind": kind})
                self._chaos_by_kind[kind] = c
        c.inc()

    def injected_faults(self, kind: str) -> int:
        with self._chaos_lock:
            c = self._chaos_by_kind.get(kind)
        return 0 if c is None else c.value

    def observe_dispatch(self, batch_size: int) -> None:
        """Record one device dispatch at ``batch_size`` occupancy: the
        batches counter, the occupancy histogram, and the per-size
        ``serve_dispatches_total{batch="N"}`` counter family."""
        self.batches.inc()
        self.batch_occupancy.observe(batch_size)
        with self._dispatch_lock:
            c = self._dispatch_by_size.get(batch_size)
            if c is None:
                c = self.registry.counter(
                    "serve_dispatches_total",
                    "device dispatches by batch-size bucket",
                    labels={"batch": str(batch_size)})
                self._dispatch_by_size[batch_size] = c
        c.inc()

    def observe_iters_used(self, tier: str, iters_used: int,
                           max_iters: int, n_requests: int = 1) -> None:
        """Record one dispatch's GRU trip count: the per-tier histogram
        gets one observation per dispatch, the saved counter accumulates
        (max_iters - iters_used) per REQUEST (the whole batch rode the
        worst member's depth)."""
        with self._iters_lock:
            pair = self._iters_by_tier.get(tier)
            if pair is None:
                labels = {"tier": tier}
                pair = (self.registry.histogram(
                            "infer_gru_iters_used",
                            "GRU iterations actually run per dispatch "
                            "(convergence-gated early exit; fixed-depth "
                            "tiers always report the configured depth)",
                            buckets=ITERS_USED_BUCKETS, labels=labels),
                        self.registry.counter(
                            "serve_gru_iters_saved_total",
                            "GRU iterations the early-exit gate skipped, "
                            "summed over requests (configured depth - "
                            "iters_used)", labels=labels))
                self._iters_by_tier[tier] = pair
        pair[0].observe(iters_used)
        pair[1].inc(max(0, max_iters - iters_used) * max(1, n_requests))

    def iters_used_stats(self, tier: str):
        """(histogram, saved-counter) for one tier, or None before its
        first dispatch — what the smoke/bench harnesses assert on."""
        with self._iters_lock:
            return self._iters_by_tier.get(tier)

    def observe_handoff_skip(self, reason: str, n: int = 1) -> None:
        """Count ``n`` handoff sessions skipped at import into the
        per-reason ``serve_handoff_import_skipped_total{reason=...}``
        family ("corrupt" | "config_mismatch")."""
        if n <= 0:
            return
        with self._handoff_skip_lock:
            c = self._handoff_skip_by_reason.get(reason)
            if c is None:
                c = self.registry.counter(
                    "serve_handoff_import_skipped_total",
                    "handoff sessions skipped at import, by reason "
                    "(corrupt = checksum/parse failure; config_mismatch "
                    "= the blob's exec-config fingerprint differs from "
                    "this engine's) — each degrades that session to a "
                    "cold start, never a crash",
                    labels={"reason": reason})
                self._handoff_skip_by_reason[reason] = c
        c.inc(n)

    def observe_model_request(self, model: str, version: str,
                              n_requests: int = 1) -> None:
        """Count ``n_requests`` completed requests against one registered
        model version (``serve_model_requests_total{model=,version=}``) —
        the canary/shadow rollout's per-version traffic signal.  Only
        NAMED models land here; the implicit constructor model keeps the
        pre-registry metric surface."""
        if n_requests <= 0:
            return
        with self._model_req_lock:
            c = self._model_req_by_coord.get((model, version))
            if c is None:
                c = self.registry.counter(
                    "serve_model_requests_total",
                    "completed requests by registered model version "
                    "(named models only; the implicit model is not "
                    "labeled)",
                    labels={"model": model, "version": version})
                self._model_req_by_coord[(model, version)] = c
        c.inc(n_requests)

    def model_requests(self, model: str, version: str) -> int:
        """Completed-request count for one model version (0 before the
        first) — what model_smoke asserts routing on."""
        with self._model_req_lock:
            c = self._model_req_by_coord.get((model, version))
        return 0 if c is None else c.value

    def handoff_skips(self, reason: str) -> int:
        """Skipped-session count for one reason (0 before the first)."""
        with self._handoff_skip_lock:
            c = self._handoff_skip_by_reason.get(reason)
        return 0 if c is None else c.value

    def observe_session_frame(self, mode: str) -> None:
        """Count one completed session frame into the per-mode
        ``serve_session_frames_total{mode="warm"|"cold"}`` family — the
        warm-vs-cold split the streaming smoke asserts on."""
        with self._session_frame_lock:
            c = self._session_frames_by_mode.get(mode)
            if c is None:
                c = self.registry.counter(
                    "serve_session_frames_total",
                    "streaming session frames served, by warm/cold start",
                    labels={"mode": mode})
                self._session_frames_by_mode[mode] = c
        c.inc()

    def session_frames(self, mode: str) -> int:
        """Completed session frames for one mode (0 before the first)."""
        with self._session_frame_lock:
            c = self._session_frames_by_mode.get(mode)
        return 0 if c is None else c.value

    def dispatches_at(self, batch_size: int) -> int:
        """Dispatch count for one batch-size bucket (0 if never used)."""
        with self._dispatch_lock:
            c = self._dispatch_by_size.get(batch_size)
        return 0 if c is None else c.value

    def observe_padding(self, bucket: Tuple[int, int], real_pixels: int,
                        dispatched_pixels: int) -> None:
        """Record one dispatch's pixel accounting: ``real_pixels`` the sum
        of un-padded image pixels in the batch, ``dispatched_pixels`` what
        the device actually ran (frames x padded H x padded W, including
        stack-mode batch fill).  Feeds the waste histogram and the
        per-bucket real/pad counter family."""
        if dispatched_pixels <= 0:
            return
        waste = max(0, dispatched_pixels - real_pixels)
        self.padding_waste.observe(waste / dispatched_pixels)
        label = f"{bucket[0]}x{bucket[1]}"
        with self._bucket_lock:
            pair = self._bucket_px.get(label)
            if pair is None:
                labels = {"bucket": label}
                pair = (self.registry.counter(
                            "serve_bucket_real_pixels_total",
                            "un-padded image pixels dispatched, by padded-"
                            "shape bucket", labels=labels),
                        self.registry.counter(
                            "serve_bucket_pad_pixels_total",
                            "padding pixels dispatched (pure waste), by "
                            "padded-shape bucket", labels=labels))
                self._bucket_px[label] = pair
        pair[0].inc(real_pixels)
        pair[1].inc(waste)

    def bucket_pixels(self) -> Dict[str, Dict[str, int]]:
        """Per-bucket pixel accounting snapshot: ``{"HxW": {"real_px": n,
        "pad_px": n}}`` — what bench_serve.py publishes next to the MFU
        numbers and what the waste feedback loop acts on."""
        with self._bucket_lock:
            return {label: {"real_px": pair[0].value,
                            "pad_px": pair[1].value}
                    for label, pair in self._bucket_px.items()}

    def note_batch_done(self) -> None:
        """Stamp micro-batch completion — the freshness signal behind
        ``/healthz``'s ``last_batch_age_s`` (a serving twin of the train
        loop's ``last_step_age_s``)."""
        self.last_batch_unix.set(time.time())
        with self._age_lock:
            self._last_batch_mono = time.monotonic()

    def last_batch_age_s(self) -> Optional[float]:
        """Seconds since the last micro-batch finished; None before the
        first one (an idle-from-boot service is not stale, it is idle)."""
        with self._age_lock:
            last = self._last_batch_mono
        return (round(time.monotonic() - last, 3)
                if last is not None else None)

    def render_text(self) -> str:
        return self.registry.render_text()
