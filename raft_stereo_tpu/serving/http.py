"""Localhost HTTP front end over the serving engine — stdlib only.

Endpoints:

* ``POST /v1/disparity`` — one stereo pair in, one disparity map out.
  Request body:
    - ``Content-Type: application/x-npz`` (default): an ``np.savez``
      archive with arrays ``left`` and ``right``, each (H, W, 3) uint8.
    - ``Content-Type: image/png``: ONE side-by-side pair (left|right
      concatenated along width; even width), the common packed layout for
      stereo capture streams.
  Optional ``X-Deadline-Ms`` header bounds the queue wait.  Optional
  ``?tier=`` (or ``X-Tier`` header) selects a configured latency tier —
  a named early-exit knob setting (``interactive`` / ``balanced`` /
  ``quality``, serving/engine.py); unknown tiers get 400.  Response
  (``?format=``):
    - ``npy`` (default): raw ``.npy`` float32 positive-disparity map;
    - ``png``: 16-bit PNG, disparity*256 (the KITTI on-disk convention —
      data/frame_utils.write_disp_kitti reads it back losslessly to
      1/256 px);
    - ``npz`` (round 24): an ``np.savez`` archive with ``disparity``
      (float32) plus — when the engine serves with confidence telemetry
      (``--confidence``) — the full-resolution per-pixel ``confidence``
      map (float32 in (0, 1]);
    - ``conf_png``: the CONFIDENCE map alone as an 8-bit PNG
      (confidence*255) — the quick-look heat map; 400 when the result
      carries no confidence.
  Errors map to transport codes with TYPED JSON bodies so clients can
  machine-react: 429 (queue full) and 503 (draining) both carry
  ``{"error": "overloaded", "retry_after_s": N}`` plus the matching
  ``Retry-After`` header (back off instead of hammering); 504 (deadline
  passed in queue); 500 with ``{"error": "request_poisoned",
  "attempts": N}`` when a request's dispatch crashed on every bounded
  retry (serving/engine.py supervised recovery); 400 (malformed input).
  Under brownout degradation a response served at a cheaper tier than
  requested carries ``X-Degraded: <requested>-><served>``; the
  ``X-No-Degrade`` request header opts one request out.
  Quality observability (round 24, ``--confidence``): every response
  carries ``X-Confidence`` (the answer's mean per-pixel confidence,
  4 decimals).  ``?tier=auto`` rides the confidence-gated cascade
  (``--cascade``): the draft tier answers first and only low-confidence
  requests re-run on the quality tier — responses carry
  ``X-Escalated: 0|1``, ``X-Draft-Tier``, and (escalated)
  ``X-Draft-Confidence``; 400 without a cascade configured.
* ``POST /v1/stream/<session-id>`` — one FRAME of a streaming stereo
  session (warm-start video serving, serving/sessions.py).  Body,
  content types, ``?tier=`` / ``X-Tier``, ``X-Deadline-Ms``, and the
  response encodings are exactly ``/v1/disparity``; the session id rides
  the path (or the ``X-Session-Id`` header when the path is bare
  ``/v1/stream``).  The first frame of a new id creates the session and
  cold-starts; subsequent frames warm-start the GRU from the previous
  frame's disparity unless the scene-cut check fires.  Responses carry
  ``X-Session-Id``, ``X-Frame-Index``, ``X-Warm: 0|1``,
  ``X-Scene-Cut: 1`` (when the inter-frame delta check forced a cold
  start), ``X-Frame-Delta`` (the measured delta), and ``X-Iters-Used``.
  Session errors are typed: **410** ``{"error": "session_expired",
  "reason": "expired"|"evicted"|"closed"}`` on a dead id (open a new
  session), 400 ``{"error": "sessions_disabled"}`` when the engine runs
  stateless.  Frames of ONE session are strictly ordered (a frame
  blocks while the previous one is in flight); stream different
  sessions concurrently for pipelining.
* ``DELETE /v1/stream/<session-id>`` — close the session; 200 with its
  lifetime stats (frames, warm/cold split, scene cuts, mean GRU
  iterations), 404 on an unknown id, 410 on an already-dead one.
* ``GET /metrics`` — Prometheus text exposition (serving/metrics.py).
* ``GET /quality`` — online quality posture (round 24): per-tier rolling
  mean confidence, good/bad totals vs the floor, the PSI drift
  watchdog's state, the quality SLO burn, and the cascade's
  draft/escalation split; 404 unless the engine serves with
  ``--confidence`` (the off wire surface is unchanged).
* ``GET /healthz`` — LIVENESS: one JSON line (status, queue depth,
  inflight count, last-batch age, device count, readiness) answered
  whenever the process and its queue exist.  A restart-looping load
  balancer should probe this.
* ``GET /readyz`` — READINESS: 200 only once the configured
  bucket x tier x batch warm ladder has fully compiled (or restored
  from the persistent executable cache); 503 with warm progress before
  that.  Pointing traffic here keeps cold pods out of rotation while
  they prewarm (docs/architecture.md §Resilience).
* ``?model=`` / ``X-Model`` (both request kinds) — pick a REGISTERED
  model version (serving/models.py); absent means the engine default
  (byte-identical to the pre-registry single-model server).  Unknown
  names get a typed 404 ``{"error": "model_unknown"}``; responses
  served by a named model carry ``X-Model`` / ``X-Model-Version``.
  Session frames pin the model their stream started on — naming a
  DIFFERENT model mid-stream is a 400.
* ``GET /admin/models`` — registry inventory (default pointer,
  registered versions, per-model in-flight counts); ``POST
  /admin/models`` — live hot swap: ``{"action": "register", "model":
  "name@version", "default": true}`` loads + prewarms + flips,
  ``{"action": "retire", "model": "name"}`` drains + evicts (409 on
  the default, 504 on drain timeout), ``{"action": "set_default",
  "model": name|null}`` flips the pointer atomically.
* ``POST /admin/brownout`` — fleet control plane (serving/fleet/):
  ``{"level": N}`` sets the brownout degradation FLOOR the router
  computed from aggregate fleet pressure, so every replica steps down
  the tier ladder in lockstep; 200 with the effective level, 409
  ``brownout_unavailable`` without a brownout controller.
* ``POST /debug/trace`` — bounded on-demand profiler window on the live
  serving process (telemetry/trace.py); optional JSON body
  ``{"duration_ms": N}``; replies with the trace directory, 409 while a
  window is already open.
* ``GET /debug/spans`` / ``GET /debug/stacks`` / ``GET|POST
  /debug/flightrecorder`` / ``GET /debug/compiles`` — the same debug
  surface the training endpoint serves (telemetry/http.py
  ``handle_debug_get``/``handle_debug_post``): the request-path span ring
  as Chrome trace JSON, an all-thread stack dump, flight-recorder status /
  forced bundle dump, and the compile-cost registry's executable
  inventory (flops / bytes accessed / memory analysis per bucket
  executable; 404 unless ``ServeConfig.cost_telemetry``).
* Trace propagation (round 23 fleet observability): an inbound
  ``traceparent`` header (W3C-style, telemetry/spans.py codec) makes the
  request's ``serve.request`` span a child of the upstream trace — the
  fleet router injects one per forwarded hop so one trace id spans
  router and replica.  Sampled/adopted requests answer with
  ``X-Trace-Id`` for lookup via ``/debug/spans?trace=<id>``.

``ThreadingHTTPServer`` gives one Python thread per connection; the real
concurrency limit is the service's bounded queue, which is the point —
admission control lives in ONE place and the transport just reports it.
"""

from __future__ import annotations

import io
import json
import logging
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from raft_stereo_tpu.serving.batcher import (DeadlineExceeded, Overloaded,
                                             RequestPoisoned)
from raft_stereo_tpu.serving.models import ModelStoreError, ModelUnknown
from raft_stereo_tpu.serving.service import StereoService
from raft_stereo_tpu.serving.sessions import SessionExpired, SessionsDisabled
from raft_stereo_tpu.telemetry.flight_recorder import FlightRecorder
from raft_stereo_tpu.telemetry.http import (handle_debug_get,
                                            handle_debug_post,
                                            handle_trace_post)
from raft_stereo_tpu.telemetry.spans import (TRACE_CONTEXT_HEADER,
                                             decode_traceparent)
from raft_stereo_tpu.telemetry.trace import TraceCapture

log = logging.getLogger(__name__)

MAX_BODY_BYTES = 256 * 2 ** 20  # refuse absurd uploads before reading them


def _decode_pair(body: bytes, content_type: str
                 ) -> Tuple[np.ndarray, np.ndarray]:
    if content_type.startswith("image/png"):
        from PIL import Image

        pair = np.asarray(Image.open(io.BytesIO(body)).convert("RGB"))
        if pair.shape[1] % 2:
            raise ValueError(
                f"side-by-side pair width {pair.shape[1]} must be even")
        w = pair.shape[1] // 2
        return pair[:, :w], pair[:, w:]
    # default: npz with left/right
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        if "left" not in z or "right" not in z:
            raise ValueError(
                f"npz must contain 'left' and 'right', got {sorted(z.files)}")
        return z["left"], z["right"]


def _encode_disparity(disp: np.ndarray, fmt: str,
                      confidence: Optional[np.ndarray] = None
                      ) -> Tuple[bytes, str]:
    if fmt == "npy":
        buf = io.BytesIO()
        np.save(buf, disp.astype(np.float32))
        return buf.getvalue(), "application/x-npy"
    if fmt == "png":
        from PIL import Image

        enc = np.clip(disp * 256.0, 0, 2 ** 16 - 1).astype(np.uint16)
        buf = io.BytesIO()
        Image.fromarray(enc).save(buf, format="PNG")
        return buf.getvalue(), "image/png"
    if fmt == "npz":
        # Disparity + (confidence on) the full-res per-pixel confidence
        # map in one archive — the "answer with its error bars" payload.
        arrays = {"disparity": disp.astype(np.float32)}
        if confidence is not None:
            arrays["confidence"] = confidence.astype(np.float32)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        return buf.getvalue(), "application/x-npz"
    if fmt == "conf_png":
        from PIL import Image

        if confidence is None:
            raise ValueError(
                "format=conf_png: this result carries no confidence map "
                "(serve with --confidence; xl-tier results have none)")
        enc = np.clip(confidence * 255.0, 0, 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(enc).save(buf, format="PNG")
        return buf.getvalue(), "image/png"
    raise ValueError(f"format={fmt!r}: use 'npy', 'png', 'npz' or "
                     f"'conf_png'")


def _stream_session_id(path: str, headers) -> Optional[str]:
    """The session id of one ``/v1/stream`` request: the path segment
    (``/v1/stream/<id>``, the canonical spelling) or the
    ``X-Session-Id`` header on the bare path.  None when the path is not
    a stream route at all."""
    if path == "/v1/stream":
        return headers.get("X-Session-Id") or ""
    if path.startswith("/v1/stream/"):
        return path[len("/v1/stream/"):]
    return None


def make_handler(service: StereoService,
                 trace: Optional[TraceCapture] = None,
                 recorder: Optional[FlightRecorder] = None):
    """Handler class closed over ``service`` (BaseHTTPRequestHandler is
    instantiated per request by the server, so state rides the closure)."""
    trace = trace if trace is not None else TraceCapture()

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through logging, not
            log.debug("%s " + fmt, self.client_address[0], *args)  # stderr

        def _reply(self, code: int, body: bytes, content_type: str,
                   extra_headers=()):
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in extra_headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, code: int, obj, extra_headers=()):
            self._reply(code, (json.dumps(obj) + "\n").encode(),
                        "application/json", extra_headers)

        def do_GET(self):
            url = urlparse(self.path)
            path = url.path
            if (path in ("/healthz", "/readyz")
                    and service.chaos is not None
                    and service.chaos.blackhole()):
                # Injected health-check blackhole (serving/chaos.py
                # healthz_blackhole_after_s): the probe's connection
                # closes with no response — the router's probe timeout
                # must classify this replica dead even though its
                # request path still works.
                self.close_connection = True
                return
            if path == "/metrics":
                self._reply(200, service.metrics.render_text().encode(),
                            "text/plain; version=0.0.4")
            elif path == "/quality":
                # Online quality posture (round 24); 404 with confidence
                # off so the off wire surface stays unchanged.
                q = service.quality_status()
                if q is None:
                    self._reply_json(404, {
                        "error": "quality telemetry off (start "
                                 "raft-serve with --confidence)"})
                else:
                    self._reply_json(200, q)
            elif path == "/healthz":
                # Liveness: answers as long as the process is up; the
                # readiness decision lives on /readyz (split so a warm
                # restart is not health-flapped out of existence while
                # it prewarms).  queue_depth/queue_limit/inflight are
                # the load signals the fleet router balances and
                # aggregates brownout pressure on.
                self._reply_json(200, {
                    "status": ("draining" if service.queue.draining
                               else "ok"),
                    "ready": service.ready,
                    "queue_depth": service.queue.depth,
                    "queue_limit": service.serve_cfg.max_queue,
                    "inflight": service.metrics.inflight.value,
                    # Running totals the fleet autoscaler differences
                    # into a deadline-miss RATE (fleet/autoscaler.py).
                    "admitted": service.metrics.admitted.value,
                    "deadline_missed":
                        service.metrics.deadline_missed.value,
                    "last_batch_age_s":
                        service.metrics.last_batch_age_s(),
                    "anomalies": service.metrics.anomalies.value,
                    "brownout_level":
                        service.metrics.brownout_level.value,
                    "sessions_active": (
                        service.sessions.active_count
                        if service.sessions is not None else None),
                    # Streaming-v2 surface (round 19): whether frames
                    # carry the GRU hidden state across dispatches and
                    # whether the deadline-aware coalescing scheduler
                    # is on — what the multi-stream smoke keys off.
                    "session_hidden": service.serve_cfg.session_hidden,
                    "edf_scheduler": service.serve_cfg.edf_scheduler,
                    "devices": len(service.devices),
                    "xl": service.xl_status(),
                    # Registry inventory, only once a named model exists
                    # (a single-model replica's /healthz body is pinned
                    # byte-identical to pre-registry builds).
                    **({"models": service.models_status()}
                       if (service.default_model is not None
                           or len(service._models) > 1) else {})})
            elif path == "/readyz":
                status = service.warm_status()
                status["status"] = ("ready" if status["ready"]
                                    else "warming")
                self._reply_json(200 if status["ready"] else 503, status)
            elif path == "/admin/models":
                # Registry inventory: the default pointer plus every
                # registered version's coordinate / retiring flag /
                # in-flight count (serving/engine.py models_status).
                self._reply_json(200, service.models_status())
            elif path == "/admin/handoff":
                # The drain handoff manifest (round 18): after a
                # graceful SIGTERM published the session blob, the
                # fleet router reads WHICH ids moved and which artifact
                # key carries their state; 404 until then (the router
                # polls while the replica reports draining).
                manifest = getattr(service, "handoff_manifest", None)
                if manifest is None:
                    self._reply_json(404, {"error": "no_handoff"})
                else:
                    service.note_handoff_fetched()
                    self._reply_json(200, manifest)
            elif handle_debug_get(path, url.query, service.tracer, recorder,
                                  service.metrics.registry,
                                  self._reply, self._reply_json,
                                  costs=service.costs):
                pass
            else:
                self._reply_json(404, {"error": f"no route {path!r}"})

        def _handle_brownout_post(self):
            """``POST /admin/brownout {"level": N}`` — the fleet-wide
            degradation floor the router pushes (serving/fleet/router.py)
            so every replica steps down the tier ladder in lockstep.
            200 with the effective level; 409 ``brownout_unavailable``
            when this engine runs without a brownout controller."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length)) if length \
                    else {}
                level = int(body["level"])
            except (ValueError, KeyError, TypeError) as e:
                self._reply_json(400, {
                    "error": 'need a JSON body {"level": N}',
                    "detail": str(e)})
                return
            try:
                effective = service.set_brownout_floor(level)
            except RuntimeError as e:
                self._reply_json(409, {"error": "brownout_unavailable",
                                       "detail": str(e)})
                return
            self._reply_json(200, {"status": "ok", "floor": level,
                                   "level": effective})

        def _handle_models_post(self):
            """``POST /admin/models`` — live model lifecycle (round 21
            hot swap; serving/models.py + engine registry):

            * ``{"action": "register", "model": "name[@version]",
              "default": bool, "prewarm": bool}`` — load + verify the
              version from the artifact store, prewarm its ladder
              (readiness gate closed until warm), optionally flip the
              default pointer.  200 with the registration status.
            * ``{"action": "retire", "model": "name"}`` — drain the
              model's in-flight dispatches, then evict its pytree and
              executables.  409 while it is the default.
            * ``{"action": "set_default", "model": "name"|null}`` —
              atomic default-pointer flip (null restores the implicit
              constructor model).

            Typed errors: 404 ``model_unknown``; 409 ``model_store`` /
            ``retire_default``; 504 ``retire_timeout``."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length)) if length \
                    else {}
                action = body["action"]
                if action not in ("register", "retire", "set_default"):
                    raise ValueError(f"unknown action {action!r}")
            except (ValueError, KeyError, TypeError) as e:
                self._reply_json(400, {
                    "error": 'need a JSON body {"action": '
                             '"register"|"retire"|"set_default", ...}',
                    "detail": str(e)})
                return
            try:
                if action == "register":
                    out = service.register_model(
                        str(body["model"]),
                        set_default=bool(body.get("default", False)),
                        prewarm=bool(body.get("prewarm", True)))
                elif action == "retire":
                    timeout = float(body.get("timeout_s", 30.0))
                    service.retire_model(str(body["model"]),
                                         timeout=timeout)
                    out = {"model": body["model"], "retired": True}
                else:
                    name = body.get("model")
                    service.set_default_model(
                        str(name) if name is not None else None)
                    out = {"default": name}
            except ModelUnknown as e:
                self._reply_json(404, {"error": "model_unknown",
                                       "model": e.model, "known": e.known,
                                       "detail": str(e)})
                return
            except ModelStoreError as e:
                self._reply_json(409, {"error": "model_store",
                                       "detail": str(e)})
                return
            except TimeoutError as e:
                self._reply_json(504, {"error": "retire_timeout",
                                       "detail": str(e)})
                return
            except (ValueError, KeyError, TypeError) as e:
                self._reply_json(400, {"error": str(e)})
                return
            except RuntimeError as e:
                self._reply_json(409, {"error": "retire_default",
                                       "detail": str(e)})
                return
            self._reply_json(200, {"status": "ok", **out,
                                   "models": service.models_status()})

        def do_POST(self):
            url = urlparse(self.path)
            if url.path == "/admin/brownout":
                self._handle_brownout_post()
                return
            if url.path == "/admin/models":
                self._handle_models_post()
                return
            if url.path == "/debug/trace":
                handle_trace_post(self, trace, self._reply_json)
                return
            if handle_debug_post(url.path, recorder, self._reply_json):
                return
            session_id = _stream_session_id(url.path, self.headers)
            if url.path != "/v1/disparity" and session_id is None:
                self._reply_json(404, {"error": f"no route {url.path!r}"})
                return
            try:
                if session_id == "":
                    raise ValueError(
                        "stream frames need a session id: POST "
                        "/v1/stream/<id> or set X-Session-Id")
                length = int(self.headers.get("Content-Length", 0))
                if not 0 < length <= MAX_BODY_BYTES:
                    raise ValueError(f"Content-Length {length} out of range")
                body = self.rfile.read(length)
                left, right = _decode_pair(
                    body, self.headers.get("Content-Type",
                                           "application/x-npz"))
                deadline_hdr = self.headers.get("X-Deadline-Ms")
                deadline_ms: Optional[float] = (
                    float(deadline_hdr) if deadline_hdr else None)
                query = parse_qs(url.query)
                fmt = query.get("format", ["npy"])[0]
                if fmt not in ("npy", "png", "npz", "conf_png"):
                    raise ValueError(f"format={fmt!r}: use 'npy', 'png', "
                                     f"'npz' or 'conf_png'")
                tier = query.get("tier", [None])[0] or \
                    self.headers.get("X-Tier")
                if tier == "xl":
                    # The xl pseudo-tier routes to the mesh-sharded
                    # family (serving/engine.py submit); valid only on
                    # an engine with an xl tier and a mesh-compatible
                    # bucket — the engine raises ValueError (-> 400)
                    # otherwise.
                    if getattr(service, "xl", None) is None:
                        raise ValueError(
                            "tier 'xl': this server has no xl mesh "
                            "tier (start raft-serve with --xl_mesh)")
                    if session_id is not None:
                        raise ValueError(
                            "tier 'xl': streaming sessions are "
                            "single-device — the warm/ctx state "
                            "machinery does not compose with the "
                            "mesh-sharded program")
                elif tier == "auto":
                    # The confidence-gated cascade pseudo-tier (round
                    # 24): valid only on an engine with a cascade
                    # configured; the engine re-raises ValueError
                    # (-> 400) at submit, this check just answers with
                    # the actionable message before reading weights.
                    if getattr(service, "_cascade_draft", None) is None:
                        raise ValueError(
                            "tier 'auto': this server has no confidence "
                            "cascade (start raft-serve with --confidence "
                            "--cascade)")
                    if session_id is not None:
                        raise ValueError(
                            "tier 'auto': streaming sessions pin one "
                            "compiled family per stream — the cascade's "
                            "draft/escalate re-run does not compose "
                            "with warm session state")
                elif tier is not None:
                    service.resolve_tier(tier)  # 400 on unknown tiers
                # ``?model=`` / ``X-Model`` picks a REGISTERED model
                # (serving/models.py); absent means the engine default.
                model = query.get("model", [None])[0] or \
                    self.headers.get("X-Model")
                degradable = self.headers.get("X-No-Degrade") is None
                # Inbound trace context (round 23 fleet observability):
                # a ``traceparent`` header — typically injected by the
                # fleet router — makes this request's serve.request span
                # a CHILD of the upstream trace, regardless of the local
                # sample rate (the upstream sampling decision wins).
                # Malformed headers decode to None and are ignored.
                trace_context = decode_traceparent(
                    self.headers.get(TRACE_CONTEXT_HEADER))
            except (ValueError, KeyError, OSError) as e:
                self._reply_json(400, {"error": str(e)})
                return
            try:
                if session_id is not None:
                    result = service.infer_session(
                        session_id, left, right, deadline_ms=deadline_ms,
                        tier=tier, degradable=degradable, model=model,
                        handoff_key=self.headers.get(
                            "X-Handoff-Artifact"),
                        trace_context=trace_context)
                else:
                    result = service.infer(left, right,
                                           deadline_ms=deadline_ms,
                                           tier=tier, degradable=degradable,
                                           model=model,
                                           trace_context=trace_context)
            except ModelUnknown as e:
                # Typed admission contract: the request named a model
                # this replica does not serve — 404, machine-readable.
                self._reply_json(404, {"error": "model_unknown",
                                       "model": e.model,
                                       "known": e.known,
                                       "detail": str(e)})
                return
            except SessionsDisabled as e:
                self._reply_json(400, {"error": "sessions_disabled",
                                       "detail": str(e)})
                return
            except SessionExpired as e:
                # The typed dead-session contract: 410 Gone — the client
                # must open a fresh session (a silent cold restart would
                # hide the stream break).
                self._reply_json(410, {"error": "session_expired",
                                       "session_id": e.session_id,
                                       "reason": e.reason,
                                       "detail": str(e)})
                return
            except Overloaded as e:
                # Typed overload contract: machine-readable body + the
                # matching Retry-After, so clients back off instead of
                # hammering a saturated (or draining) server.
                retry_after_s = 5.0 if e.draining else 1.0
                body = {"error": "overloaded",
                        "retry_after_s": retry_after_s,
                        "draining": e.draining,
                        "detail": str(e)}
                self._reply_json(
                    503 if e.draining else 429, body,
                    extra_headers=[("Retry-After",
                                    str(int(retry_after_s)))])
                return
            except DeadlineExceeded as e:
                self._reply_json(504, {"error": "deadline_exceeded",
                                       "detail": str(e)})
                return
            except RequestPoisoned as e:
                self._reply_json(500, {"error": "request_poisoned",
                                       "attempts": e.attempts,
                                       "detail": str(e)})
                return
            except ValueError as e:
                # Engine-side admission rejections that only trigger at
                # submit time: xl with a named model, a session's
                # mid-stream model switch.
                self._reply_json(400, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001 — model/device failure
                log.exception("inference failed")
                self._reply_json(500, {"error": str(e)})
                return
            try:
                payload, ctype = _encode_disparity(
                    result.disparity, fmt, confidence=result.confidence)
            except ValueError as e:
                # conf_png on a result without a confidence map (xl
                # tier, or a confidence-off engine): client error.
                self._reply_json(400, {"error": str(e)})
                return
            headers = [
                ("X-Queue-Wait-Ms", f"{result.queue_wait_s * 1e3:.2f}"),
                ("X-Device-Ms", f"{result.device_s * 1e3:.2f}"),
                ("X-Batch-Size", str(result.batch_size))]
            if result.iters_used is not None:
                headers.append(("X-Iters-Used", str(result.iters_used)))
            if result.trace_id is not None:
                # Sampled (or trace-context-adopted) requests echo their
                # trace id so a slow response can be looked up in
                # /debug/spans?trace=<id> — on this replica and, when the
                # fleet router originated the trace, in the router's
                # federated view.
                headers.append(("X-Trace-Id", result.trace_id))
            if result.tier is not None:
                headers.append(("X-Tier", result.tier))
            if result.mesh is not None:
                headers.append(("X-Mesh", result.mesh))
            if result.tiles is not None:
                headers.append(("X-Tiles", str(result.tiles)))
                if result.seam_epe is not None:
                    headers.append(("X-Seam-EPE",
                                    f"{result.seam_epe:.4f}"))
            if result.degraded:
                headers.append(("X-Degraded",
                                f"{result.requested_tier}->{result.tier}"))
            if result.confidence_mean is not None:
                headers.append(("X-Confidence",
                                f"{result.confidence_mean:.4f}"))
            if result.draft_tier is not None:
                # Cascade (?tier=auto) provenance: which tier drafted,
                # whether the draft's confidence forced the re-run.
                headers.append(("X-Escalated",
                                "1" if result.escalated else "0"))
                headers.append(("X-Draft-Tier", result.draft_tier))
                if result.draft_confidence is not None:
                    headers.append(("X-Draft-Confidence",
                                    f"{result.draft_confidence:.4f}"))
            if result.model is not None:
                # Named-model responses carry the exact version that
                # served them — the canary comparator keys on this.
                headers.append(("X-Model", result.model))
                headers.append(("X-Model-Version", result.model_version))
            if result.session_id is not None:
                headers.append(("X-Session-Id", result.session_id))
                headers.append(("X-Frame-Index", str(result.frame_index)))
                headers.append(("X-Warm", "1" if result.warm else "0"))
                if result.scene_cut:
                    headers.append(("X-Scene-Cut", "1"))
                if result.ctx_cached:
                    headers.append(("X-Ctx-Cached", "1"))
                if result.frame_delta is not None:
                    headers.append(("X-Frame-Delta",
                                    f"{result.frame_delta:.2f}"))
            self._reply(200, payload, ctype, extra_headers=headers)

        def do_DELETE(self):
            url = urlparse(self.path)
            session_id = _stream_session_id(url.path, self.headers)
            if session_id is None:
                self._reply_json(404, {"error": f"no route {url.path!r}"})
                return
            if session_id == "":
                self._reply_json(400, {"error": "stream close needs a "
                                                "session id"})
                return
            try:
                stats = service.close_session(session_id)
            except SessionsDisabled as e:
                self._reply_json(400, {"error": "sessions_disabled",
                                       "detail": str(e)})
                return
            except SessionExpired as e:
                self._reply_json(410, {"error": "session_expired",
                                       "session_id": e.session_id,
                                       "reason": e.reason})
                return
            except KeyError:
                self._reply_json(404, {"error": "unknown_session",
                                       "session_id": session_id})
                return
            self._reply_json(200, {"status": "closed", **stats})

    return Handler


class StereoHTTPServer:
    """Owns the ThreadingHTTPServer; ``port=0`` binds an ephemeral port
    (tests).  ``serve_forever`` blocks (the CLI's mode); ``start`` runs it
    on a daemon thread (in-process tests)."""

    def __init__(self, service: StereoService, host: str = "127.0.0.1",
                 port: int = 8551,
                 recorder: Optional[FlightRecorder] = None):
        self.service = service
        self.trace = TraceCapture()
        self.recorder = recorder
        self.server = ThreadingHTTPServer(
            (host, port), make_handler(service, self.trace,
                                       recorder=recorder))
        self._thread = None

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def serve_forever(self):
        self.server.serve_forever()

    def start(self) -> "StereoHTTPServer":
        import threading

        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="stereo-http")
        self._thread.start()
        return self

    def shutdown(self):
        self.server.shutdown()
        self.server.server_close()
        self.trace.stop()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
