"""Pressure-driven replica autoscaling: the control loop that turns the
r16 aggregate-pressure signal into fleet size changes.

The router already measures everything a scaling decision needs — the
aggregate queued fraction (the brownout engage signal), the fleet
brownout level itself (the fleet is ALREADY degrading answers to keep
up), and the deadline-miss totals each replica's /healthz exports.  The
``Autoscaler`` folds those into one composite pressure in [0, 1] and
runs the same engage/restore hysteresis shape as every other controller
in this repo (serving/resilience.py BrownoutController, the router's
fleet brownout): engaging needs SUSTAINED pressure, restoring needs a
longer sustained calm at a lower watermark, and the dead band between
the watermarks holds — a fleet hovering at the threshold can never flap
replicas up and down.

Scale-up registers a fresh replica with the router (``add_replica``)
and lets readiness gate traffic: the new process boots warm from the
shared artifact store and joins rotation when /readyz opens.
**Scale-down always DRAINS**: the launcher delivers SIGTERM, the
replica publishes its session handoff (serving/sessions.py export →
artifact store), the router remaps the streams to survivors, and only
after the process exited cleanly is it deregistered — a scale-down is
operationally indistinguishable from a rolling restart and produces
zero typed session losses (pinned in tests/test_fleet.py).

``ReplicaLauncher`` is the deployment seam: ``LocalProcessLauncher``
spawns ``raft-serve`` subprocesses on this host (what ``raft-route
--autoscale_cmd`` and scripts/fleet_smoke.py use); a k8s/Borg launcher
implements the same four methods against its API and nothing else
changes.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from raft_stereo_tpu.serving.fleet.router import FleetRouter

log = logging.getLogger(__name__)


class ReplicaLauncher:
    """Deployment seam: how the autoscaler materializes and retires
    replica processes.  Implementations must be idempotent about names
    they never launched."""

    def launch(self, name: str) -> str:
        """Start a replica; returns its base URL.  The replica may take
        arbitrarily long to become ready — the router's probes gate
        traffic, not this call."""
        raise NotImplementedError

    def drain(self, name: str) -> None:
        """Begin a GRACEFUL shutdown (SIGTERM): readyz flips, sessions
        hand off, queued work finishes.  Never a hard kill."""
        raise NotImplementedError

    def poll(self, name: str) -> Optional[int]:
        """The replica's exit code, or None while it is still running
        (also None for unknown names)."""
        raise NotImplementedError

    def destroy(self, name: str) -> None:
        """Force-stop and forget one replica (shutdown cleanup only —
        the scaling path always drains)."""
        raise NotImplementedError


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LocalProcessLauncher(ReplicaLauncher):
    """Launch replicas as local subprocesses.

    ``argv_for(name, port)`` returns the full command line (the CLI
    builds it from the ``--autoscale_cmd`` template, substituting
    ``{name}`` / ``{port}``).  Logs go to ``<log_dir>/<name>.log`` when
    a directory is given, else inherit.
    """

    def __init__(self, argv_for: Callable[[str, int], List[str]],
                 env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None):
        self.argv_for = argv_for
        self.env = env
        self.log_dir = log_dir
        self._lock = threading.Lock()
        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs: Dict[str, object] = {}

    def launch(self, name: str) -> str:
        port = _free_port()
        argv = self.argv_for(name, port)
        stdout = stderr = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            stdout = stderr = open(
                os.path.join(self.log_dir, f"{name}.log"), "ab")
        proc = subprocess.Popen(argv, env=self.env, stdout=stdout,
                                stderr=stderr)
        with self._lock:
            self._procs[name] = proc
            if stdout is not None:
                self._logs[name] = stdout
        log.info("launched replica %s (pid %d, port %d): %s", name,
                 proc.pid, port, shlex.join(argv))
        return f"http://127.0.0.1:{port}"

    def drain(self, name: str) -> None:
        with self._lock:
            proc = self._procs.get(name)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            log.info("draining replica %s (SIGTERM to pid %d)", name,
                     proc.pid)

    def poll(self, name: str) -> Optional[int]:
        with self._lock:
            proc = self._procs.get(name)
        return None if proc is None else proc.poll()

    def destroy(self, name: str) -> None:
        with self._lock:
            proc = self._procs.pop(name, None)
            fh = self._logs.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
        if fh is not None:
            try:
                fh.close()
            except OSError:  # pragma: no cover
                pass

    def stop_all(self) -> None:
        with self._lock:
            names = list(self._procs)
        for name in names:
            self.destroy(name)


def serve_argv_template(template: str) -> Callable[[str, int], List[str]]:
    """Turn an ``--autoscale_cmd`` template ("... --port {port}") into
    the launcher's argv factory.  ``{port}`` is required (every replica
    needs its own); ``{name}`` is optional."""
    if "{port}" not in template:
        raise ValueError("--autoscale_cmd template needs a {port} "
                         "placeholder")

    def argv_for(name: str, port: int) -> List[str]:
        line = template.replace("{port}", str(port)).replace("{name}",
                                                             name)
        argv = shlex.split(line)
        if argv and argv[0] == "python":
            argv[0] = sys.executable
        return argv

    return argv_for


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Scaling-policy knobs (cli/route.py maps flags here)."""

    min_replicas: int = 1
    max_replicas: int = 4
    # Composite pressure in [0, 1] = max(aggregate queued fraction,
    # brownout level / max level, deadline-miss rate over the window).
    # Engage: pressure >= engage_fraction sustained for engage_s.
    engage_fraction: float = 0.6
    engage_s: float = 2.0
    # Restore: pressure <= restore_fraction sustained for restore_s
    # (longer, lower watermark — the anti-flap hysteresis).
    restore_fraction: float = 0.15
    restore_s: float = 10.0
    # Minimum spacing between ANY two scaling actions: a fresh replica
    # needs time to join rotation and absorb load before the signal is
    # trusted again.
    cooldown_s: float = 5.0
    poll_s: float = 0.5
    # Deadline-miss rate only counts once this many admissions happened
    # within the window (a 1-request window is noise).
    miss_min_events: int = 8

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas={self.min_replicas} must "
                             f"be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas={self.max_replicas} must be >= "
                f"min_replicas={self.min_replicas}")
        if not 0 < self.restore_fraction <= self.engage_fraction <= 1:
            raise ValueError(
                f"need 0 < restore_fraction ({self.restore_fraction}) "
                f"<= engage_fraction ({self.engage_fraction}) <= 1")


class Autoscaler:
    """The control loop: reads ``router.fleet_pressure()``, applies the
    engage/restore hysteresis, and drives the launcher + router
    membership.  ``check()`` is one deterministic step (tests drive it
    with a fake clock and scripted pressure); ``start()`` runs it on a
    poll thread."""

    def __init__(self, router: FleetRouter, launcher: ReplicaLauncher,
                 cfg: AutoscaleConfig = AutoscaleConfig(),
                 clock: Callable[[], float] = time.monotonic,
                 pressure_fn: Optional[Callable[[], Optional[float]]]
                 = None):
        self.router = router
        self.launcher = launcher
        self.cfg = cfg
        self._clock = clock
        self._pressure_fn = pressure_fn    # test seam: scripted traces
        self._lock = threading.Lock()
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._last_action: Optional[float] = None
        self._counter = 0
        self._launched: List[str] = []       # scale-down candidates, LIFO
        self._draining: Dict[str, float] = {}
        self._prev_admitted: Optional[int] = None
        self._prev_missed: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        r = router.registry
        self.scale_ups = r.counter(
            "fleet_autoscale_up_total",
            "replicas launched by the pressure-driven autoscaler")
        self.scale_downs = r.counter(
            "fleet_autoscale_down_total",
            "replicas drained away by the autoscaler (always via "
            "handoff, never killed)")
        self.pressure_gauge = r.gauge(
            "fleet_autoscale_pressure",
            "composite autoscaling pressure in [0,1]: max(queued "
            "fraction, normalized brownout level, deadline-miss rate)")

    # ------------------------------------------------------------- pressure
    def _composite_pressure(self) -> Optional[float]:
        if self._pressure_fn is not None:
            return self._pressure_fn()
        sig = self.router.fleet_pressure()
        if sig["ready"] == 0:
            return None           # nothing measurable; never scale blind
        parts = []
        if sig["queued_fraction"] is not None:
            parts.append(min(1.0, float(sig["queued_fraction"])))
        bmax = max(1, int(sig["brownout_max_level"]))
        parts.append(min(1.0, sig["brownout_level"] / bmax))
        admitted = int(sig["admitted_total"])
        missed = int(sig["deadline_missed_total"])
        if self._prev_admitted is not None:
            d_adm = admitted - self._prev_admitted
            d_miss = missed - self._prev_missed
            if d_adm >= self.cfg.miss_min_events and d_miss >= 0:
                parts.append(min(1.0, d_miss / d_adm))
        self._prev_admitted, self._prev_missed = admitted, missed
        return max(parts) if parts else None

    # ----------------------------------------------------------------- step
    def check(self) -> Optional[str]:
        """One control step; returns "up"/"down" when an action fired,
        else None.  Reaps finished drains first, so a completed
        scale-down frees its membership slot before the next decision."""
        self._reap_drained()
        pressure = self._composite_pressure()
        if pressure is None:
            return None
        self.pressure_gauge.set(pressure)
        now = self._clock()
        action: Optional[str] = None
        with self._lock:
            cooling = (self._last_action is not None
                       and now - self._last_action < self.cfg.cooldown_s)
            count = len(self.router.replicas) - len(self._draining)
            if pressure >= self.cfg.engage_fraction:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                elif (now - self._pressure_since >= self.cfg.engage_s
                        and not cooling
                        and count < self.cfg.max_replicas):
                    action = "up"
                    self._pressure_since = now
                    self._last_action = now
            elif pressure <= self.cfg.restore_fraction:
                self._pressure_since = None
                if count > self.cfg.min_replicas and self._launched:
                    if self._calm_since is None:
                        self._calm_since = now
                    elif (now - self._calm_since >= self.cfg.restore_s
                            and not cooling):
                        action = "down"
                        self._calm_since = now
                        self._last_action = now
                else:
                    self._calm_since = None
            else:
                # Dead band between the watermarks: hold, reset both
                # timers — this is the hysteresis.
                self._pressure_since = None
                self._calm_since = None
        if action == "up":
            self._scale_up(pressure)
        elif action == "down":
            self._scale_down(pressure)
        return action

    def _scale_up(self, pressure: float) -> None:
        with self._lock:
            self._counter += 1
            name = f"auto{self._counter}"
        url = self.launcher.launch(name)
        self.router.add_replica(name, url)
        with self._lock:
            self._launched.append(name)
        self.scale_ups.inc()
        log.warning("autoscale UP: %s at %s (pressure %.2f, fleet now "
                    "%d)", name, url, pressure,
                    len(self.router.replicas))

    def _scale_down(self, pressure: float) -> None:
        with self._lock:
            if not self._launched:
                return
            victim = self._launched.pop()       # LIFO: newest first
            self._draining[victim] = self._clock()
        # Always a DRAIN: SIGTERM -> readyz flips -> session handoff ->
        # queued work finishes -> exit 0.  remove_replica happens at
        # reap time, after the process is gone.
        self.launcher.drain(victim)
        self.scale_downs.inc()
        log.warning("autoscale DOWN: draining %s (pressure %.2f)",
                    victim, pressure)

    def _reap_drained(self) -> None:
        with self._lock:
            draining = list(self._draining)
        for name in draining:
            code = self.launcher.poll(name)
            if code is None:
                continue
            if code != 0:
                log.warning("drained replica %s exited rc=%d (expected "
                            "0 from a graceful drain)", name, code)
            self.router.remove_replica(name)
            self.launcher.destroy(name)
            with self._lock:
                self._draining.pop(name, None)
            log.info("autoscale: %s fully drained and deregistered",
                     name)

    @property
    def draining(self) -> List[str]:
        with self._lock:
            return sorted(self._draining)

    @property
    def launched(self) -> List[str]:
        with self._lock:
            return list(self._launched)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-autoscaler")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.poll_s):
            try:
                self.check()
            except Exception:  # pragma: no cover — loop must not die
                log.exception("autoscaler step failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
