"""Metrics federation: one scrape surface for the whole fleet.

The router's ``GET /metrics/fleet`` re-exposes every in-rotation
replica's ``/metrics`` with a ``replica="<name>"`` constant label
injected into each sample — one Prometheus target instead of N, and the
label makes per-replica breakdowns a query-time ``by (replica)`` rather
than a scrape-config chore.

Two invariants the design is built around:

* **Never a scrape hang on the request path.**  ``MetricsFederator``
  scrapes on its own daemon thread at ``poll_s`` with a bounded
  per-replica timeout; ``render()`` only reads the cache.  A replica
  dying mid-scrape costs the poller one timeout, never a client request.
* **Stale is visible, not silent.**  Each replica contributes
  ``fleet_federation_up{replica=…}`` (1 scraped fresh, 0 down/stale) and
  ``fleet_federation_scrape_age_seconds{replica=…}``; a down replica's
  last-good series stay exposed (marked stale via those gauges) until
  ``stale_after_s`` ages them out entirely — matching how federation
  consumers reason about absent-vs-zero.

Because re-labelling is generic, NEW series federate with zero code
here: the round-24 quality families (``serve_confidence{tier=,model=}``
histograms with exemplars, ``serve_quality_good/bad_total``,
``serve_cascade_*_total``, ``serve_slo_burn_rate{dimension="quality"}``)
appear in ``/metrics/fleet`` with their ``replica=`` label the moment a
replica starts exposing them — scripts/quality_smoke.py pins exactly
that.

Re-labelling is a text transform on the exposition format, not a parse
into a metric model: each sample line gets ``replica="…"`` spliced into
its labelset (respecting quotes/escapes — label VALUES may contain
``{``/``}``/``,``), and ``# HELP``/``# TYPE`` headers are emitted once
per family across all replicas (first writer wins; Prometheus rejects
duplicate headers).  Replica names are escaped with the registry's own
``escape_label_value`` so arbitrary names round-trip.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from raft_stereo_tpu.telemetry.registry import escape_label_value

log = logging.getLogger(__name__)


def inject_label(sample_line: str, label: str, value: str) -> str:
    """Splice ``label="value"`` into one exposition sample line.

    ``metric{a="b"} 1`` → ``metric{replica="r0",a="b"} 1`` and
    ``metric 1`` → ``metric{replica="r0"} 1``.  The existing-labelset
    case walks the line quote-aware: a bare ``{`` inside a quoted label
    value (legal in the format) must not be mistaken for the labelset
    opener — only the first unquoted ``{`` is."""
    escaped = escape_label_value(value)
    in_quote = False
    backslash = False
    for i, ch in enumerate(sample_line):
        if backslash:
            backslash = False
            continue
        if ch == "\\":
            backslash = True
            continue
        if ch == '"':
            in_quote = not in_quote
            continue
        if in_quote:
            continue
        if ch == "{":
            rest = sample_line[i + 1:]
            comma = "" if rest.lstrip().startswith("}") else ","
            return (f'{sample_line[:i]}{{{label}="{escaped}"{comma}'
                    f'{rest}')
        if ch in (" ", "\t"):
            # No labelset on this sample — open one before the value.
            return (f'{sample_line[:i]}{{{label}="{escaped}"}}'
                    f'{sample_line[i:]}')
    return f'{sample_line}{{{label}="{escaped}"}}'


def relabel_exposition(text: str, label: str, value: str,
                       seen_families: Dict[str, str]) -> List[str]:
    """Re-emit one replica's exposition text with ``label="value"``
    injected into every sample.  ``seen_families`` (family name → owner)
    dedups ``# HELP``/``# TYPE`` headers across replicas — the first
    replica to expose a family owns its header; later replicas' copies
    of the SAME family drop theirs (the merge the federation contract
    requires: duplicate names across replicas appear under one header,
    distinguishable only by the ``replica=`` label)."""
    out: List[str] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                key = f"{parts[1]}:{parts[2]}"
                if key in seen_families:
                    continue
                seen_families[key] = value
            out.append(line)
            continue
        out.append(inject_label(line, label, value))
    return out


class MetricsFederator:
    """Background scraper + cache + renderer behind ``/metrics/fleet``.

    ``replicas_fn`` returns the current scrape set as ``(name, replica)``
    pairs (the router passes its in-rotation view); each poll pass
    scrapes every member with ``timeout_s`` bound and stores
    ``(text, monotonic_ts, ok)`` per name.  ``render()`` is pure cache —
    called on the router's HTTP request path, it never touches the
    network."""

    def __init__(self, replicas_fn, poll_s: float = 5.0,
                 timeout_s: float = 2.0, stale_after_s: float = 60.0,
                 clock=time.monotonic):
        if poll_s <= 0 or timeout_s <= 0:
            raise ValueError("federation poll_s and timeout_s must be "
                             "positive")
        self._replicas_fn = replicas_fn
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self.stale_after_s = float(stale_after_s)
        self._clock = clock
        self._lock = threading.Lock()
        # name -> (exposition_text, scraped_at, fresh)
        self._cache: Dict[str, Tuple[str, float, bool]] = {}
        self.scrapes_ok = 0
        self.scrapes_failed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------------- lifecycle
    def start(self) -> "MetricsFederator":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="metrics-federator")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + self.poll_s)
            self._thread = None

    def _run(self):
        while not self._stop.wait(self.poll_s):
            try:
                self.scrape_once()
            except Exception:  # pragma: no cover — poller must not die
                log.exception("federation scrape pass failed")

    # ------------------------------------------------------------------ scrape
    def scrape_once(self) -> Dict[str, bool]:
        """One bounded scrape pass over the current replica set; returns
        ``{name: ok}``.  Tests drive this directly for determinism; the
        daemon thread calls it on the poll cadence.  A replica that dies
        mid-scrape costs ONE ``timeout_s`` here and flips its cache
        entry to stale — nothing on the request path waits."""
        results: Dict[str, bool] = {}
        members = list(self._replicas_fn())
        for name, rep in members:
            try:
                text = rep.get_metrics(self.timeout_s)
            except Exception as e:
                results[name] = False
                with self._lock:
                    self.scrapes_failed += 1
                    prior = self._cache.get(name)
                    if prior is not None:
                        # Keep last-good text, mark stale.
                        self._cache[name] = (prior[0], prior[1], False)
                log.debug("federation scrape of %r failed: %s", name, e)
                continue
            results[name] = True
            with self._lock:
                self.scrapes_ok += 1
                self._cache[name] = (text, self._clock(), True)
        # Members that left the replica set keep their cache entry until
        # stale_after_s ages it out in render() — same absent-vs-down
        # story as a dead replica.
        return results

    # ------------------------------------------------------------------ render
    def render(self, own_text: str = "") -> str:
        """The federated exposition: router's own series first (no extra
        label — the router IS this scrape target), then every cached
        replica's series with ``replica=`` injected, plus the
        per-replica up/staleness meta-gauges.  Cache-only: safe on the
        request path."""
        now = self._clock()
        with self._lock:
            cache = dict(self._cache)
        out: List[str] = []
        seen_families: Dict[str, str] = {}
        if own_text:
            for line in own_text.splitlines():
                if not line.strip():
                    continue
                if line.startswith("#"):
                    parts = line.split(None, 3)
                    if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                        seen_families[f"{parts[1]}:{parts[2]}"] = ""
                out.append(line)
        out.append("# HELP fleet_federation_up Whether the last scrape "
                   "of this replica succeeded (0 = down or stale).")
        out.append("# TYPE fleet_federation_up gauge")
        up_lines: List[str] = []
        age_lines: List[str] = []
        body_lines: List[str] = []
        for name in sorted(cache):
            text, scraped_at, fresh = cache[name]
            age = max(0.0, now - scraped_at)
            escaped = escape_label_value(name)
            if age > self.stale_after_s:
                # Aged out entirely: the series vanish, only the down
                # marker remains.
                up_lines.append(f'fleet_federation_up{{replica='
                                f'"{escaped}"}} 0')
                age_lines.append(f'fleet_federation_scrape_age_seconds'
                                 f'{{replica="{escaped}"}} {age:.3f}')
                continue
            up_lines.append(f'fleet_federation_up{{replica="{escaped}"}}'
                            f' {1 if fresh else 0}')
            age_lines.append(f'fleet_federation_scrape_age_seconds'
                             f'{{replica="{escaped}"}} {age:.3f}')
            body_lines.extend(relabel_exposition(text, "replica", name,
                                                 seen_families))
        out.extend(up_lines)
        out.append("# HELP fleet_federation_scrape_age_seconds Seconds "
                   "since this replica's series were last refreshed.")
        out.append("# TYPE fleet_federation_scrape_age_seconds gauge")
        out.extend(age_lines)
        out.extend(body_lines)
        return "\n".join(out) + "\n"

    def status(self) -> Dict[str, object]:
        now = self._clock()
        with self._lock:
            return {
                "poll_s": self.poll_s, "timeout_s": self.timeout_s,
                "stale_after_s": self.stale_after_s,
                "scrapes_ok": self.scrapes_ok,
                "scrapes_failed": self.scrapes_failed,
                "replicas": {
                    name: {"fresh": fresh,
                           "age_s": round(max(0.0, now - ts), 3)}
                    for name, (_, ts, fresh) in self._cache.items()},
            }
