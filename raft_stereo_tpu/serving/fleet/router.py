"""The fleet router: N replica engines behind one front door.

One serving process is now crash-safe *internally* (round 13: supervised
recovery, breakers, brownout) — but the process itself is still a single
fault domain: a kill takes every in-flight request, every streaming
session, and a compile storm with it.  This module makes the REPLICA the
unit of failure:

* **Routing.**  Stateless ``/v1/disparity`` requests go to the
  least-loaded ready replica (queue depth, then inflight, from the last
  health probe; round-robin among equals).  Streaming ``/v1/stream/<id>``
  requests are STICKY: the session id consistent-hashes onto the ring of
  in-rotation replicas (fleet/ring.py), so every frame of one session
  lands on the engine holding its warm-start state, and replica loss
  remaps only ~1/N of the id space — the sessions that died with it.
* **Failover.**  A transport failure (connection refused/reset/timeout)
  on a forwarded request or ``fail_after`` consecutive health-probe
  failures takes the replica out of rotation immediately.  Stateless
  requests retry on the next replica — a disparity request is a pure
  function of its inputs, so the retry is safe and the client never sees
  the death.  The lost replica's sessions CANNOT fail over (their state
  is gone): each one fails typed with ``SessionLost`` (HTTP 410
  ``session_lost``) exactly once, then the id is forgotten so the
  client's reseed — its next frame, cold — routes to a surviving replica
  and starts a fresh chain.  The r14 tombstone contract, fleet-wide: a
  broken stream is always announced, never silently restarted.
* **Fleet brownout.**  Sustained aggregate queue pressure across the
  ready replicas raises one fleet-wide degradation level (hysteresis as
  in serving/resilience.py) and pushes it to every replica's
  ``POST /admin/brownout`` floor — the whole fleet degrades in lockstep
  instead of each replica flapping on its own local signal.
* **Recovery.**  A probe succeeding on a dead replica puts it back in
  rotation (and re-pushes the current brownout floor).  With the shared
  executable artifact store (serving/persist.py) a replacement replica
  boots warm, so rejoin cost is an artifact fetch, not a compile storm.

Pass-through contract: with every replica healthy the router adds no
behavior — request and response bytes are forwarded verbatim (hop-by-hop
headers aside), so a one-replica fleet is byte-identical to hitting the
engine directly (the bitwise solo-parity chain now extends client ->
router -> replica -> engine -> solo).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from raft_stereo_tpu.serving.fleet.replica import (Replica, ReplicaHealth,
                                                   ReplicaUnreachable)
from raft_stereo_tpu.serving.fleet.ring import DEFAULT_VNODES, HashRing
from raft_stereo_tpu.telemetry.registry import MetricsRegistry

log = logging.getLogger(__name__)


class NoReplicasAvailable(RuntimeError):
    """No ready replica can take this request right now (the fleet's
    503: every member is dead, warming, or draining)."""


class SessionLost(KeyError):
    """Typed fleet-level dead-session failure (HTTP 410
    ``session_lost``): the replica holding this session's warm-start
    state left the rotation, so the chain is unrecoverable.  Fired once
    per session; the client's next frame reseeds cold on a surviving
    replica."""

    def __init__(self, session_id: str, replica: str):
        super().__init__(
            f"session {session_id!r} lost with replica {replica!r}; "
            f"reseed on the next frame (it will cold-start)")
        self.session_id = session_id
        self.replica = replica


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Fleet-router knobs (cli/route.py maps flags here)."""

    health_poll_s: float = 0.25      # probe cadence per replica
    health_timeout_s: float = 1.0    # per-probe transport timeout
    # Consecutive failed PROBES before a replica is declared dead.  A
    # transport failure on real forwarded traffic kills it immediately
    # (stronger signal — a request already burned on it).
    fail_after: int = 2
    request_timeout_s: float = 600.0  # forwarded-request timeout (covers
    #                                   a first-request compile on a
    #                                   replica without prewarm)
    # Total stateless dispatch attempts across distinct replicas before
    # the router gives up with NoReplicasAvailable.
    route_retries: int = 3
    vnodes: int = DEFAULT_VNODES
    # Fleet-wide brownout: aggregate queued fraction (sum of ready
    # replicas' queue depths / sum of their limits) above the engage
    # watermark for engage_s raises the fleet level one rung; below the
    # restore watermark for restore_s lowers it.  Same hysteresis shape
    # as the per-engine BrownoutController, driven by the fleet signal.
    fleet_brownout: bool = True
    brownout_engage_fraction: float = 0.75
    brownout_engage_s: float = 0.5
    brownout_restore_fraction: float = 0.25
    brownout_restore_s: float = 2.0
    brownout_max_level: int = 2
    # Lost-session bookkeeping bound: ids older than this are forgotten
    # even if the client never came back for its 410.
    session_lost_ttl_s: float = 60.0

    def __post_init__(self):
        if self.fail_after < 1:
            raise ValueError(f"fail_after={self.fail_after} must be >= 1")
        if self.route_retries < 1:
            raise ValueError(
                f"route_retries={self.route_retries} must be >= 1")
        if not (0 < self.brownout_restore_fraction
                <= self.brownout_engage_fraction <= 1):
            raise ValueError(
                f"need 0 < brownout_restore_fraction "
                f"({self.brownout_restore_fraction}) <= "
                f"brownout_engage_fraction "
                f"({self.brownout_engage_fraction}) <= 1")


class FleetRouter:
    """Routing brain over a set of ``Replica`` clients.

    ``replicas`` maps name -> base URL.  ``start()`` runs one synchronous
    probe pass (so routing works immediately) and then the background
    health loop; ``stop()`` joins it.  All routing state (ring
    membership, session table, lost set, brownout level) is guarded by
    one lock — routing decisions are cheap; the forwarding I/O happens
    outside it.
    """

    def __init__(self, replicas: Dict[str, str],
                 cfg: RouterConfig = RouterConfig(),
                 registry: Optional[MetricsRegistry] = None,
                 clock=time.monotonic):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.cfg = cfg
        self._clock = clock
        self.replicas: Dict[str, Replica] = {
            name: Replica(name, url) for name, url in replicas.items()}
        self._lock = threading.Lock()
        # Ring membership == replicas currently IN ROTATION (alive and
        # ready).  Sessions route over this ring only.
        self.ring = HashRing(vnodes=cfg.vnodes)
        # sid -> replica name, for every session the router has routed;
        # the blast-radius ledger a replica death consults.
        self._session_table: Dict[str, str] = {}
        # sid -> (replica, t_lost): sessions owed one typed 410.
        self._lost: "OrderedDict[str, Tuple[str, float]]" = OrderedDict()
        self._rr = 0                       # round-robin tiebreak
        self._transitions: List[Dict[str, object]] = []   # audit trail
        # Fleet brownout state.
        self.brownout_level = 0
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # ---- metrics ----------------------------------------------------
        r = registry or MetricsRegistry()
        self.registry = r
        self.replicas_ready = r.gauge(
            "fleet_replicas_ready",
            "replicas currently in rotation (alive and ready)")
        self.replicas_total = r.gauge(
            "fleet_replicas_total", "replicas configured in the fleet")
        self.replicas_total.set(len(self.replicas))
        self.failovers = r.counter(
            "fleet_failovers_total",
            "replicas removed from rotation after transport failures "
            "(health probes or forwarded traffic)")
        self.sessions_lost = r.counter(
            "fleet_sessions_lost_total",
            "streaming sessions failed typed (410 session_lost) because "
            "their replica left the rotation")
        self.route_retries = r.counter(
            "fleet_route_retries_total",
            "stateless requests re-dispatched to another replica after "
            "a transport failure (the zero-loss failover path)")
        self.unroutable = r.counter(
            "fleet_requests_unroutable_total",
            "requests failed with no_replicas_ready (every fleet member "
            "dead, warming, or draining)")
        self.brownout_gauge = r.gauge(
            "fleet_brownout_level",
            "fleet-wide brownout degradation level pushed to every "
            "replica's /admin/brownout floor (0 = off)")
        self.brownout_pushes = r.counter(
            "fleet_brownout_pushes_total",
            "brownout floor updates pushed to replicas")
        self._routed_lock = threading.Lock()
        self._routed_by_kind: Dict[str, object] = {}
        self._per_replica_lock = threading.Lock()
        self._routed_by_replica: Dict[str, object] = {}

    # ---------------------------------------------------------------- metrics
    def _note_routed(self, kind: str, replica: str) -> None:
        with self._routed_lock:
            c = self._routed_by_kind.get(kind)
            if c is None:
                c = self.registry.counter(
                    "fleet_requests_routed_total",
                    "requests routed to a replica, by routing kind",
                    labels={"kind": kind})
                self._routed_by_kind[kind] = c
        c.inc()
        with self._per_replica_lock:
            c = self._routed_by_replica.get(replica)
            if c is None:
                c = self.registry.counter(
                    "fleet_replica_routed_total",
                    "requests routed per replica",
                    labels={"replica": replica})
                self._routed_by_replica[replica] = c
        c.inc()

    def routed(self, kind: str) -> int:
        with self._routed_lock:
            c = self._routed_by_kind.get(kind)
        return 0 if c is None else c.value

    # ----------------------------------------------------------- health loop
    def start(self) -> "FleetRouter":
        self.check_replicas()        # synchronous first pass: routable now
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-health")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.health_poll_s):
            try:
                self.check_replicas()
            except Exception:  # pragma: no cover — loop must not die
                log.exception("fleet health poll failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def check_replicas(self) -> None:
        """One probe pass over every replica (public: tests and the
        smoke call it directly for deterministic stepping).  Probes run
        OUTSIDE the lock; state transitions apply under it."""
        results: Dict[str, Optional[ReplicaHealth]] = {}
        for name, rep in self.replicas.items():
            try:
                results[name] = rep.probe(self.cfg.health_timeout_s)
            except ReplicaUnreachable:
                results[name] = None
        with self._lock:
            for name, health in results.items():
                rep = self.replicas[name]
                if health is None:
                    rep.consecutive_failures += 1
                    if (rep.alive
                            and rep.consecutive_failures
                            >= self.cfg.fail_after):
                        self._remove_from_rotation_locked(
                            rep, "health_probe_failures")
                    continue
                rep.consecutive_failures = 0
                rep.health = health
                was_dead = not rep.alive
                rep.alive = True
                in_ring = rep.name in self.ring
                if health.ready and not in_ring:
                    self.ring.add(rep.name)
                    self._transitions.append({
                        "t": self._clock(), "replica": rep.name,
                        "event": ("rejoined" if was_dead else "ready")})
                    log.info("replica %s in rotation (%d/%d ready)",
                             rep.name, len(self.ring),
                             len(self.replicas))
                    if self.brownout_level > 0:
                        self._push_brownout_locked((rep,))
                elif not health.ready and in_ring:
                    self._remove_from_rotation_locked(
                        rep, "draining" if health.draining
                        else "not_ready", dead=False)
            self._note_ready_locked()
        self._brownout_poll()

    def _note_ready_locked(self) -> None:
        self.replicas_ready.set(len(self.ring))

    def _remove_from_rotation_locked(self, rep: Replica, reason: str,
                                     dead: bool = True) -> None:
        """Take one replica out of rotation: ring membership drops (only
        ~1/N of session slots remap), its sessions become typed losses,
        and — when ``dead`` — it stays out until a probe succeeds."""
        if dead:
            rep.alive = False
        if rep.name not in self.ring and not dead:
            return
        self.ring.remove(rep.name)
        now = self._clock()
        lost = [sid for sid, owner in self._session_table.items()
                if owner == rep.name]
        for sid in lost:
            del self._session_table[sid]
            self._lost[sid] = (rep.name, now)
            self._lost.move_to_end(sid)
        self.sessions_lost.inc(len(lost))
        self.failovers.inc()
        self._transitions.append({
            "t": now, "replica": rep.name, "event": "removed",
            "reason": reason, "sessions_lost": len(lost)})
        self._note_ready_locked()
        log.warning("replica %s out of rotation (%s): %d session(s) "
                    "lost, %d/%d replicas ready", rep.name, reason,
                    len(lost), len(self.ring), len(self.replicas))

    def _expire_lost_locked(self, now: float) -> None:
        while self._lost:
            sid, (_rep, t) = next(iter(self._lost.items()))
            if now - t <= self.cfg.session_lost_ttl_s:
                break
            del self._lost[sid]

    # -------------------------------------------------------------- routing
    def _ready_replicas_locked(self) -> List[Replica]:
        return [r for r in self.replicas.values() if r.ready]

    def pick_stateless(self, exclude: Sequence[str] = ()) -> Replica:
        """Least-loaded ready replica (queue depth, then inflight, from
        the last probe), round-robin among equals; raises
        ``NoReplicasAvailable`` when the rotation is empty."""
        with self._lock:
            ready = [r for r in self._ready_replicas_locked()
                     if r.name not in exclude]
            if not ready:
                raise NoReplicasAvailable(
                    f"no ready replica (fleet of {len(self.replicas)}; "
                    f"excluded {sorted(exclude)})")
            key = lambda r: (r.health.load if r.health else (0, 0))
            best = min(key(r) for r in ready)
            tied = [r for r in ready if key(r) == best]
            self._rr += 1
            return tied[self._rr % len(tied)]

    def pick_session(self, session_id: str) -> Replica:
        """The ring's replica for this session id; raises ``SessionLost``
        (once) for ids whose replica left the rotation, and
        ``NoReplicasAvailable`` on an empty rotation."""
        with self._lock:
            self._expire_lost_locked(self._clock())
            entry = self._lost.pop(session_id, None)
            if entry is not None:
                # Fire-once: the id is forgotten now, so the client's
                # reseed (the next frame on this or a fresh id) routes
                # normally and cold-starts on a surviving replica.
                raise SessionLost(session_id, entry[0])
            name = self.ring.lookup(session_id)
            if name is None:
                raise NoReplicasAvailable(
                    "no ready replica to own this session")
            rep = self.replicas[name]
            self._session_table[session_id] = name
            return rep

    def forget_session(self, session_id: str) -> None:
        """Drop a session from the routing ledger (its replica answered
        a close, a 410, or the stream ended)."""
        with self._lock:
            self._session_table.pop(session_id, None)

    def note_transport_failure(self, rep: Replica) -> None:
        """A forwarded request hit a transport error on ``rep``: out of
        rotation immediately (a burned request outranks ``fail_after``
        probe patience); the health loop will re-admit it when it
        answers probes again."""
        with self._lock:
            if rep.alive or rep.name in self.ring:
                self._remove_from_rotation_locked(rep, "transport_error")

    # ----------------------------------------------------------- forwarding
    def forward_stateless(self, method: str, path_qs: str,
                          body: Optional[bytes],
                          headers: Sequence[Tuple[str, str]]
                          ) -> Tuple[int, List[Tuple[str, str]], bytes]:
        """Forward one stateless request with transport-level failover:
        a replica that dies mid-request burns one attempt, the request
        re-dispatches to the next ready replica (inference is a pure
        function of the request body — the retry is safe), and only
        ``route_retries`` exhausted or an empty rotation surfaces as an
        error.  HTTP error responses are answers, not failures — they
        forward verbatim, no retry."""
        tried: List[str] = []
        last: Optional[ReplicaUnreachable] = None
        for attempt in range(self.cfg.route_retries):
            try:
                rep = self.pick_stateless(exclude=tried)
            except NoReplicasAvailable:
                if last is None:
                    self.unroutable.inc()
                    raise
                break
            tried.append(rep.name)
            if attempt > 0:
                self.route_retries.inc()
            try:
                status, h, payload = rep.forward(
                    method, path_qs, body, headers,
                    self.cfg.request_timeout_s)
            except ReplicaUnreachable as e:
                last = e
                self.note_transport_failure(rep)
                log.warning("stateless %s %s: replica %s died "
                            "mid-request (attempt %d); failing over",
                            method, path_qs, rep.name, attempt + 1)
                continue
            self._note_routed("stateless", rep.name)
            return status, h, payload
        self.unroutable.inc()
        raise NoReplicasAvailable(
            f"all {len(tried)} dispatch attempt(s) hit transport "
            f"failures (tried {tried}): {last}")

    def forward_session(self, session_id: str, method: str, path_qs: str,
                        body: Optional[bytes],
                        headers: Sequence[Tuple[str, str]]
                        ) -> Tuple[int, List[Tuple[str, str]], bytes]:
        """Forward one session-sticky request.  No transport failover:
        the session's state lives on exactly one replica, so a transport
        failure there IS the loss of the session — the replica leaves
        the rotation and this request (and only this one) fails typed
        with ``SessionLost``."""
        rep = self.pick_session(session_id)   # SessionLost / NoReplicas
        try:
            status, h, payload = rep.forward(
                method, path_qs, body, headers,
                self.cfg.request_timeout_s)
        except ReplicaUnreachable:
            self.note_transport_failure(rep)
            with self._lock:
                # pick_session recorded the route; the death path above
                # may have tombstoned it already — pop either way so the
                # 410 fires exactly once, right now.
                self._session_table.pop(session_id, None)
                self._lost.pop(session_id, None)
            raise SessionLost(session_id, rep.name) from None
        self._note_routed("session", rep.name)
        if status == 410 or (method == "DELETE" and status == 200):
            self.forget_session(session_id)
        return status, h, payload

    # -------------------------------------------------------- fleet brownout
    def _fleet_pressure_locked(self) -> Optional[float]:
        """Aggregate queued fraction across ready replicas; None when no
        replica reports a queue limit (nothing to measure)."""
        depth = limit = 0
        for rep in self._ready_replicas_locked():
            if rep.health is None or rep.health.queue_limit <= 0:
                continue
            depth += rep.health.queue_depth
            limit += rep.health.queue_limit
        if limit <= 0:
            return None
        return depth / limit

    def _brownout_poll(self) -> None:
        if not self.cfg.fleet_brownout:
            return
        now = self._clock()
        push: Optional[Tuple[Replica, ...]] = None
        with self._lock:
            pressure = self._fleet_pressure_locked()
            if pressure is None:
                return
            level = self.brownout_level
            if pressure >= self.cfg.brownout_engage_fraction:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                elif (now - self._pressure_since
                        >= self.cfg.brownout_engage_s
                        and level < self.cfg.brownout_max_level):
                    self.brownout_level = level + 1
                    self._pressure_since = now
                    push = tuple(r for r in self.replicas.values()
                                 if r.alive)
            elif pressure <= self.cfg.brownout_restore_fraction:
                self._pressure_since = None
                if level > 0:
                    if self._calm_since is None:
                        self._calm_since = now
                    elif (now - self._calm_since
                            >= self.cfg.brownout_restore_s):
                        self.brownout_level = level - 1
                        self._calm_since = now
                        push = tuple(r for r in self.replicas.values()
                                     if r.alive)
                else:
                    self._calm_since = None
            else:
                self._pressure_since = None
                self._calm_since = None
            if push is not None:
                new_level = self.brownout_level
                self.brownout_gauge.set(new_level)
                log.warning("fleet brownout level %d -> %d (aggregate "
                            "queued fraction %.2f)", level, new_level,
                            pressure)
        if push is not None:
            self._push_brownout(push)

    def _push_brownout(self, reps: Sequence[Replica]) -> None:
        for rep in reps:
            try:
                if rep.post_brownout(self.brownout_level,
                                     self.cfg.health_timeout_s):
                    self.brownout_pushes.inc()
            except ReplicaUnreachable:
                pass    # the health loop will notice and re-push on rejoin

    def _push_brownout_locked(self, reps: Sequence[Replica]) -> None:
        """Re-push the current floor to a rejoining replica — fired from
        inside the lock; the actual I/O rides a short-lived thread so
        the probe pass is never blocked on a slow member."""
        threading.Thread(
            target=lambda: self._push_brownout(reps),
            daemon=True, name="fleet-brownout-push").start()

    # --------------------------------------------------------------- status
    def fleet_status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "replicas": {name: rep.stats()
                             for name, rep in self.replicas.items()},
                "in_rotation": list(self.ring.members),
                "ready": len(self.ring),
                "total": len(self.replicas),
                "sessions_routed": len(self._session_table),
                "sessions_pending_loss": len(self._lost),
                "brownout_level": self.brownout_level,
                "transitions": list(self._transitions[-50:]),
            }
