"""The fleet router: N replica engines behind one front door.

One serving process is now crash-safe *internally* (round 13: supervised
recovery, breakers, brownout) — but the process itself is still a single
fault domain: a kill takes every in-flight request, every streaming
session, and a compile storm with it.  This module makes the REPLICA the
unit of failure:

* **Routing.**  Stateless ``/v1/disparity`` requests go to the
  least-loaded ready replica (queue depth, then inflight, from the last
  health probe; round-robin among equals).  Streaming ``/v1/stream/<id>``
  requests are STICKY: the session id consistent-hashes onto the ring of
  in-rotation replicas (fleet/ring.py), so every frame of one session
  lands on the engine holding its warm-start state, and replica loss
  remaps only ~1/N of the id space — the sessions that died with it.
* **Failover.**  A transport failure (connection refused/reset/timeout)
  on a forwarded request or ``fail_after`` consecutive health-probe
  failures takes the replica out of rotation immediately.  Stateless
  requests retry on the next replica — a disparity request is a pure
  function of its inputs, so the retry is safe and the client never sees
  the death.  The lost replica's sessions CANNOT fail over (their state
  is gone): each one fails typed with ``SessionLost`` (HTTP 410
  ``session_lost``) exactly once, then the id is forgotten so the
  client's reseed — its next frame, cold — routes to a surviving replica
  and starts a fresh chain.  The r14 tombstone contract, fleet-wide: a
  broken stream is always announced, never silently restarted.
* **Fleet brownout.**  Sustained aggregate queue pressure across the
  ready replicas raises one fleet-wide degradation level (hysteresis as
  in serving/resilience.py) and pushes it to every replica's
  ``POST /admin/brownout`` floor — the whole fleet degrades in lockstep
  instead of each replica flapping on its own local signal.
* **Recovery.**  A probe succeeding on a dead replica puts it back in
  rotation (and re-pushes the current brownout floor).  With the shared
  executable artifact store (serving/persist.py) a replacement replica
  boots warm, so rejoin cost is an artifact fetch, not a compile storm.

Round 18 turns "the fleet survives faults" into "the fleet can be
OPERATED" (docs/architecture.md §Fleet):

* **Session handoff on graceful drain.**  A replica reporting
  ``draining`` is pulled from rotation WITHOUT typing its sessions lost:
  the router polls its ``GET /admin/handoff`` manifest (the draining
  engine published its live streams into the artifact store's
  ``sessions/`` namespace), remaps those ids, and tags each one's next
  frame with ``X-Handoff-Artifact`` so the inheriting replica imports
  the warm state lazily — a planned restart costs zero 410s and the
  first post-drain frame still dispatches warm.  A kill -9 keeps the
  r16 typed-loss path: handoff is for PLANNED drains only.
* **HA pair.**  Two routers share the deterministic ring by
  construction plus a fenced, replicated lost-session/handoff ledger
  (fleet/ledger.py) in the artifact store.  The primary holds a lease
  and appends ``lost``/``fired``/``handoff`` records; the standby
  serves traffic the whole time (stateless + ring-sticky sessions need
  no shared state) and takes over — bump the fencing epoch, replay the
  ledger — when the lease goes stale or the peer stops answering.  A
  loss is never un-typed and never double-fired for one id; a stale
  primary's appends are rejected.
* **Dynamic membership + pressure export.**  ``add_replica`` /
  ``remove_replica`` and ``fleet_pressure()`` are the seams the
  autoscaler (fleet/autoscaler.py) drives: scale-up registers a fresh
  replica (it joins rotation when its probes go ready), scale-down
  always DRAINS through the handoff path, never kills.
* **XL-capability routing.**  ``?tier=xl`` requests route only to
  replicas whose /healthz advertises the mesh tier; a fleet with none
  in rotation answers the typed 503 ``xl_unavailable`` with the
  capable-replica count instead of bouncing the request off a replica
  that will 400 it.

Pass-through contract: with every replica healthy the router adds no
behavior — request and response bytes are forwarded verbatim (hop-by-hop
headers aside), so a one-replica fleet is byte-identical to hitting the
engine directly (the bitwise solo-parity chain now extends client ->
router -> replica -> engine -> solo).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, urlparse

from raft_stereo_tpu.serving.fleet.federation import MetricsFederator
from raft_stereo_tpu.serving.fleet.ledger import FleetLedger
from raft_stereo_tpu.serving.fleet.replica import (Replica, ReplicaHealth,
                                                   ReplicaUnreachable)
from raft_stereo_tpu.serving.fleet.ring import DEFAULT_VNODES, HashRing
from raft_stereo_tpu.serving.fleet.rollout import (RolloutConfig,
                                                   RolloutPolicy)
from raft_stereo_tpu.telemetry.flight_recorder import FlightRecorder
from raft_stereo_tpu.telemetry.registry import MetricsRegistry
from raft_stereo_tpu.telemetry.slo import BurnRateTracker, SloWatchdog
from raft_stereo_tpu.telemetry.spans import (TRACE_CONTEXT_HEADER,
                                             SpanTracer, Trace,
                                             encode_traceparent)
from raft_stereo_tpu.telemetry.watchdog import AnomalySink

log = logging.getLogger(__name__)


class NoReplicasAvailable(RuntimeError):
    """No ready replica can take this request right now (the fleet's
    503: every member is dead, warming, or draining)."""


class XlUnavailable(NoReplicasAvailable):
    """Typed xl-capability failure (HTTP 503 ``xl_unavailable``): the
    request asked for the mesh-sharded xl tier but no replica currently
    in rotation advertises one.  ``capable_ready`` counts xl replicas
    in rotation (0 here by definition), ``capable_total`` counts
    configured replicas whose last probe advertised the tier."""

    def __init__(self, capable_ready: int, capable_total: int,
                 fleet_size: int):
        super().__init__(
            f"no xl-capable replica in rotation ({capable_ready} ready, "
            f"{capable_total} capable of {fleet_size} configured)")
        self.capable_ready = capable_ready
        self.capable_total = capable_total
        self.fleet_size = fleet_size


class SessionLost(KeyError):
    """Typed fleet-level dead-session failure (HTTP 410
    ``session_lost``): the replica holding this session's warm-start
    state left the rotation, so the chain is unrecoverable.  Fired once
    per session; the client's next frame reseeds cold on a surviving
    replica."""

    def __init__(self, session_id: str, replica: str):
        super().__init__(
            f"session {session_id!r} lost with replica {replica!r}; "
            f"reseed on the next frame (it will cold-start)")
        self.session_id = session_id
        self.replica = replica


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Fleet-router knobs (cli/route.py maps flags here)."""

    health_poll_s: float = 0.25      # probe cadence per replica
    health_timeout_s: float = 1.0    # per-probe transport timeout
    # Consecutive failed PROBES before a replica is declared dead.  A
    # transport failure on real forwarded traffic kills it immediately
    # (stronger signal — a request already burned on it).
    fail_after: int = 2
    request_timeout_s: float = 600.0  # forwarded-request timeout (covers
    #                                   a first-request compile on a
    #                                   replica without prewarm)
    # Total stateless dispatch attempts across distinct replicas before
    # the router gives up with NoReplicasAvailable.
    route_retries: int = 3
    vnodes: int = DEFAULT_VNODES
    # Fleet-wide brownout: aggregate queued fraction (sum of ready
    # replicas' queue depths / sum of their limits) above the engage
    # watermark for engage_s raises the fleet level one rung; below the
    # restore watermark for restore_s lowers it.  Same hysteresis shape
    # as the per-engine BrownoutController, driven by the fleet signal.
    fleet_brownout: bool = True
    brownout_engage_fraction: float = 0.75
    brownout_engage_s: float = 0.5
    brownout_restore_fraction: float = 0.25
    brownout_restore_s: float = 2.0
    brownout_max_level: int = 2
    # Lost-session bookkeeping bound: ids older than this are forgotten
    # even if the client never came back for its 410.
    session_lost_ttl_s: float = 60.0
    # Capacity cap on the lost-session AND handoff ledgers (the
    # SessionStore tombstone move, fleet-wide): a long-lived router
    # under session churn forgets the OLDEST owed 410s/handoffs past
    # this many, bounding memory; fleet_lost_ledger_size tracks it.
    session_lost_cap: int = 4096
    # Bounded wait for a draining replica's /admin/handoff manifest
    # when one of its sessions' frames arrives before the manifest was
    # fetched (the export runs at SIGTERM, so this is one export +
    # one store write away).
    handoff_fetch_timeout_s: float = 3.0
    # ---- HA pair (fleet/ledger.py) ------------------------------------
    # Shared ledger directory (inside the artifact store, e.g.
    # <store>/fleet).  None: single-router mode, no ledger, everything
    # below ignored.
    ha_dir: Optional[str] = None
    router_name: str = "router"
    # True: start PASSIVE — serve traffic (stateless + ring-sticky
    # sessions need no shared state) but hold no lease and append no
    # ledger records until the primary's lease goes stale (or the peer
    # stops answering) and this router takes over.
    standby: bool = False
    # Lease renewal happens every health poll; the standby takes over
    # once the lease has not been renewed for this long.
    lease_ttl_s: float = 3.0
    # Optional peer URL (the primary, from the standby's side): probing
    # it detects a kill -9 faster than lease staleness alone.
    peer_url: Optional[str] = None
    peer_fail_after: int = 2
    # ---- fleet observability (round 23) -------------------------------
    # Router-side span sampling.  0.0 (default) keeps the pass-through
    # contract BIT-EXACT: no route.request trace, no traceparent header
    # injected, request and response bytes forwarded verbatim.
    trace_sample_rate: float = 0.0
    # SLO objectives (GET /metrics/fleet burn-rate gauges).  slo_ms:
    # router-observed forward latency above this counts as an SLO error
    # (None: latency does not burn budget); slo_availability is the
    # objective the burn rate is measured against.
    slo_ms: Optional[float] = None
    slo_availability: float = 0.999
    # Multi-window burn thresholds the SloWatchdog pages on (fast=first
    # window, slow=last): both must breach simultaneously.
    slo_fast_burn: float = 14.4
    slo_slow_burn: float = 6.0
    # Metrics federation poller (GET /metrics/fleet): background scrape
    # cadence, per-replica scrape timeout, and how long a dead replica's
    # last-good series stay exposed (stale-marked) before vanishing.
    federation_poll_s: float = 5.0
    federation_timeout_s: float = 2.0
    federation_stale_s: float = 60.0
    # Router-side flight-recorder bundles + the coordinated fleet dump
    # manifests land here.  None: no recorder, SLO breaches still fire
    # anomaly events but capture nothing.
    flight_recorder_dir: Optional[str] = None

    def __post_init__(self):
        if self.fail_after < 1:
            raise ValueError(f"fail_after={self.fail_after} must be >= 1")
        if self.route_retries < 1:
            raise ValueError(
                f"route_retries={self.route_retries} must be >= 1")
        if not (0 < self.brownout_restore_fraction
                <= self.brownout_engage_fraction <= 1):
            raise ValueError(
                f"need 0 < brownout_restore_fraction "
                f"({self.brownout_restore_fraction}) <= "
                f"brownout_engage_fraction "
                f"({self.brownout_engage_fraction}) <= 1")
        if self.session_lost_cap < 1:
            raise ValueError(f"session_lost_cap={self.session_lost_cap} "
                             f"must be >= 1")
        if self.standby and self.ha_dir is None:
            raise ValueError("standby=True needs ha_dir (the shared "
                             "lease/ledger directory to watch)")
        if self.lease_ttl_s <= 0:
            raise ValueError(f"lease_ttl_s={self.lease_ttl_s} must be "
                             f"> 0")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError(f"trace_sample_rate="
                             f"{self.trace_sample_rate} must be in "
                             f"[0, 1]")
        if not 0.0 < self.slo_availability < 1.0:
            raise ValueError(f"slo_availability="
                             f"{self.slo_availability} must be in "
                             f"(0, 1)")
        if self.slo_ms is not None and self.slo_ms <= 0:
            raise ValueError(f"slo_ms={self.slo_ms} must be > 0")


class FleetRouter:
    """Routing brain over a set of ``Replica`` clients.

    ``replicas`` maps name -> base URL.  ``start()`` runs one synchronous
    probe pass (so routing works immediately) and then the background
    health loop; ``stop()`` joins it.  All routing state (ring
    membership, session table, lost set, brownout level) is guarded by
    one lock — routing decisions are cheap; the forwarding I/O happens
    outside it.
    """

    def __init__(self, replicas: Dict[str, str],
                 cfg: RouterConfig = RouterConfig(),
                 registry: Optional[MetricsRegistry] = None,
                 rollout_cfg: Optional[RolloutConfig] = None,
                 clock=time.monotonic):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.cfg = cfg
        self._clock = clock
        self.replicas: Dict[str, Replica] = {
            name: Replica(name, url) for name, url in replicas.items()}
        self._lock = threading.Lock()
        # Ring membership == replicas currently IN ROTATION (alive and
        # ready).  Sessions route over this ring only.
        self.ring = HashRing(vnodes=cfg.vnodes)
        # sid -> replica name, for every session the router has routed;
        # the blast-radius ledger a replica death consults.
        self._session_table: Dict[str, str] = {}
        # sid -> (replica, t_lost): sessions owed one typed 410.
        self._lost: "OrderedDict[str, Tuple[str, float]]" = OrderedDict()
        # sid -> (artifact_key, t): sessions a draining replica handed
        # off — their next frame is tagged X-Handoff-Artifact so the
        # inheriting replica imports the warm state (round 18).
        self._handoff: "OrderedDict[str, Tuple[str, float]]" = (
            OrderedDict())
        # name -> Replica currently draining whose handoff manifest has
        # not been fetched yet (polled every probe pass, and inline —
        # bounded — when one of their sessions' frames arrives first).
        self._drain_pending: Dict[str, Replica] = {}
        self._rr = 0                       # round-robin tiebreak
        self._transitions: List[Dict[str, object]] = []   # audit trail
        # Fleet brownout state.
        self.brownout_level = 0
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # ---- metrics ----------------------------------------------------
        r = registry or MetricsRegistry()
        self.registry = r
        self.replicas_ready = r.gauge(
            "fleet_replicas_ready",
            "replicas currently in rotation (alive and ready)")
        self.replicas_total = r.gauge(
            "fleet_replicas_total", "replicas configured in the fleet")
        self.replicas_total.set(len(self.replicas))
        self.failovers = r.counter(
            "fleet_failovers_total",
            "replicas removed from rotation after transport failures "
            "(health probes or forwarded traffic)")
        self.sessions_lost = r.counter(
            "fleet_sessions_lost_total",
            "streaming sessions failed typed (410 session_lost) because "
            "their replica left the rotation")
        self.route_retries = r.counter(
            "fleet_route_retries_total",
            "stateless requests re-dispatched to another replica after "
            "a transport failure (the zero-loss failover path)")
        self.unroutable = r.counter(
            "fleet_requests_unroutable_total",
            "requests failed with no_replicas_ready (every fleet member "
            "dead, warming, or draining)")
        self.brownout_gauge = r.gauge(
            "fleet_brownout_level",
            "fleet-wide brownout degradation level pushed to every "
            "replica's /admin/brownout floor (0 = off)")
        self.brownout_pushes = r.counter(
            "fleet_brownout_pushes_total",
            "brownout floor updates pushed to replicas")
        self.lost_ledger_size = r.gauge(
            "fleet_lost_ledger_size",
            "sessions currently owed a typed 410 in the router's "
            "lost-session ledger (TTL + capacity bounded)")
        self.handoff_sessions = r.counter(
            "fleet_handoff_sessions_total",
            "sessions remapped to survivors through a draining "
            "replica's handoff manifest (zero-loss planned restarts)")
        self.handoff_manifests = r.counter(
            "fleet_handoff_manifests_total",
            "drain handoff manifests fetched and applied")
        self.xl_unroutable = r.counter(
            "fleet_xl_unroutable_total",
            "xl-tier requests failed typed (503 xl_unavailable) with "
            "no xl-capable replica in rotation")
        self.active_gauge = r.gauge(
            "fleet_router_active",
            "1 when this router holds the HA lease (or runs without an "
            "HA pair), 0 for a passive standby")
        self.takeovers = r.counter(
            "fleet_router_takeovers_total",
            "standby takeovers: lease acquired + ledger replayed after "
            "the primary went stale/unreachable")
        # ---- HA pair state (fleet/ledger.py) --------------------------
        self.ledger: Optional[FleetLedger] = None
        self.active = True
        self._peer_failures = 0
        self._last_compact = 0.0
        if cfg.ha_dir:
            self.ledger = FleetLedger(cfg.ha_dir, cfg.router_name,
                                      clock=time.time)
            self.active = not cfg.standby
            if self.active:
                self.ledger.acquire()
                self._replay_ledger()
        self.active_gauge.set(1 if self.active else 0)
        # Canary/shadow rollout policy (fleet/rollout.py): always
        # present, disarmed by default — a disarmed policy makes zero
        # routing decisions, so the pass-through contract holds until
        # an operator arms it (--canary / POST /admin/rollout).
        self.rollout = RolloutPolicy(rollout_cfg or RolloutConfig(),
                                     registry=r, clock=clock)
        self._routed_lock = threading.Lock()
        self._routed_by_kind: Dict[str, object] = {}
        self._per_replica_lock = threading.Lock()
        self._routed_by_replica: Dict[str, object] = {}
        # ---- fleet observability (round 23) ---------------------------
        # Router-side spans: at the default sample rate 0 start_trace
        # returns None in constant time and every span call below is a
        # no-op — the pass-through contract stays bit-exact.
        self.tracer = SpanTracer(sample_rate=cfg.trace_sample_rate)
        self.recorder: Optional[FlightRecorder] = None
        if cfg.flight_recorder_dir:
            self.recorder = FlightRecorder(cfg.flight_recorder_dir,
                                           tracer=self.tracer,
                                           registry=r)
        # Typed fleet-level failures the replicas never see (503
        # no_replicas_ready / xl_unavailable, 410 session_lost) — these
        # MUST burn SLO budget too, or the burn rate only measures
        # replica-side badness and a dead fleet looks healthy.
        self.slo_errors = r.counter(
            "fleet_slo_errors_total",
            "router-typed request failures counted against the SLO "
            "error budget (no_replicas_ready, xl_unavailable, "
            "session_lost)")
        self.slo_slow = r.counter(
            "fleet_slo_slow_total",
            "forwarded requests whose router-observed latency exceeded "
            "the --slo_ms objective (counted against the error budget)")
        self.anomalies = r.counter(
            "fleet_anomalies_total",
            "fleet-level anomalies fired (SLO burn-rate breaches)")
        self.slo = BurnRateTracker(availability=cfg.slo_availability,
                                   latency_ms=cfg.slo_ms, registry=r,
                                   clock=clock)
        self._sink = AnomalySink(recorder=self.recorder,
                                 counter=self.anomalies)
        self.slo_watchdog = SloWatchdog(self.slo, self._sink,
                                        fast_burn=cfg.slo_fast_burn,
                                        slow_burn=cfg.slo_slow_burn,
                                        dump_fn=self.coordinated_dump)
        self.fleet_dumps: List[Dict[str, object]] = []
        self.federator = MetricsFederator(
            self._federation_members, poll_s=cfg.federation_poll_s,
            timeout_s=cfg.federation_timeout_s,
            stale_after_s=cfg.federation_stale_s)

    def _federation_members(self) -> List[Tuple[str, Replica]]:
        """The federation poller's scrape set: every ALIVE replica —
        in-rotation plus draining ones (their last metrics are exactly
        what a post-incident look wants); dead replicas age out of the
        cache instead of burning a scrape timeout every pass."""
        with self._lock:
            return [(name, rep) for name, rep in self.replicas.items()
                    if rep.alive]

    # ---------------------------------------------------------------- metrics
    def _note_routed(self, kind: str, replica: str) -> None:
        with self._routed_lock:
            c = self._routed_by_kind.get(kind)
            if c is None:
                c = self.registry.counter(
                    "fleet_requests_routed_total",
                    "requests routed to a replica, by routing kind",
                    labels={"kind": kind})
                self._routed_by_kind[kind] = c
        c.inc()
        with self._per_replica_lock:
            c = self._routed_by_replica.get(replica)
            if c is None:
                c = self.registry.counter(
                    "fleet_replica_routed_total",
                    "requests routed per replica",
                    labels={"replica": replica})
                self._routed_by_replica[replica] = c
        c.inc()

    def routed(self, kind: str) -> int:
        with self._routed_lock:
            c = self._routed_by_kind.get(kind)
        return 0 if c is None else c.value

    # ----------------------------------------------------------- health loop
    def start(self) -> "FleetRouter":
        self.check_replicas()        # synchronous first pass: routable now
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-health")
        self._thread.start()
        self.federator.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.health_poll_s):
            try:
                self.check_replicas()
            except Exception:  # pragma: no cover — loop must not die
                log.exception("fleet health poll failed")
            try:
                self._ha_tick()
            except Exception:  # pragma: no cover — loop must not die
                log.exception("fleet HA tick failed")
            try:
                # Hysteresis dwell: a sustained regression verdict must
                # demote even when no new evidence arrives to trigger
                # the inline poll.
                self.rollout.poll()
            except Exception:  # pragma: no cover — loop must not die
                log.exception("rollout poll failed")
            try:
                self.slo_tick()
            except Exception:  # pragma: no cover — loop must not die
                log.exception("SLO tick failed")

    def stop(self) -> None:
        self._stop.set()
        self.federator.stop()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def check_replicas(self) -> None:
        """One probe pass over every replica (public: tests and the
        smoke call it directly for deterministic stepping).  Probes run
        OUTSIDE the lock; state transitions apply under it."""
        with self._lock:
            members = list(self.replicas.items())   # autoscaler mutates
        results: Dict[str, Optional[ReplicaHealth]] = {}
        for name, rep in members:
            try:
                results[name] = rep.probe(self.cfg.health_timeout_s)
            except ReplicaUnreachable:
                results[name] = None
        with self._lock:
            for name, health in results.items():
                rep = self.replicas.get(name)
                if rep is None:         # removed mid-pass (autoscaler)
                    continue
                if health is None:
                    rep.consecutive_failures += 1
                    if (rep.alive
                            and rep.consecutive_failures
                            >= self.cfg.fail_after):
                        self._remove_from_rotation_locked(
                            rep, "health_probe_failures")
                    continue
                rep.consecutive_failures = 0
                rep.health = health
                was_dead = not rep.alive
                rep.alive = True
                in_ring = rep.name in self.ring
                if health.ready and not in_ring:
                    self.ring.add(rep.name)
                    self._drain_pending.pop(rep.name, None)
                    rep.last_state_change_ts = time.time()
                    self._transitions.append({
                        "t": self._clock(), "replica": rep.name,
                        "event": ("rejoined" if was_dead else "ready")})
                    log.info("replica %s in rotation (%d/%d ready)",
                             rep.name, len(self.ring),
                             len(self.replicas))
                    if self.brownout_level > 0:
                        self._push_brownout_locked((rep,))
                elif not health.ready and in_ring:
                    if health.draining:
                        # Planned drain (round 18): out of rotation but
                        # its sessions are NOT lost — the handoff
                        # manifest remaps them (fetched below, outside
                        # the lock).  A drain that dies before handing
                        # off falls through to the death path above.
                        self._begin_drain_locked(rep)
                    else:
                        self._remove_from_rotation_locked(
                            rep, "not_ready", dead=False)
            self._note_ready_locked()
            pending = list(self._drain_pending.values())
        for rep in pending:
            self._fetch_handoff(rep)
        self._brownout_poll()

    def _note_ready_locked(self) -> None:
        self.replicas_ready.set(len(self.ring))

    def _remove_from_rotation_locked(self, rep: Replica, reason: str,
                                     dead: bool = True) -> None:
        """Take one replica out of rotation: ring membership drops (only
        ~1/N of session slots remap), its sessions become typed losses,
        and — when ``dead`` — it stays out until a probe succeeds."""
        if dead:
            rep.alive = False
        self._drain_pending.pop(rep.name, None)
        if rep.name not in self.ring and not dead:
            return
        self.ring.remove(rep.name)
        now = self._clock()
        lost = [sid for sid, owner in self._session_table.items()
                if owner == rep.name]
        for sid in lost:
            del self._session_table[sid]
            self._lost[sid] = (rep.name, now)
            self._lost.move_to_end(sid)
        if lost:
            self._ledger_append("lost", sids=lost, replica=rep.name)
        self._bound_ledgers_locked()
        self.sessions_lost.inc(len(lost))
        self.failovers.inc()
        rep.last_state_change_ts = time.time()
        self._transitions.append({
            "t": now, "replica": rep.name, "event": "removed",
            "reason": reason, "sessions_lost": len(lost)})
        self._note_ready_locked()
        log.warning("replica %s out of rotation (%s): %d session(s) "
                    "lost, %d/%d replicas ready", rep.name, reason,
                    len(lost), len(self.ring), len(self.replicas))

    def _bound_ledgers_locked(self) -> None:
        """Capacity-cap the lost and handoff tables (oldest forgotten —
        the SessionStore tombstone bound, fleet-wide) and refresh the
        fleet_lost_ledger_size gauge."""
        while len(self._lost) > self.cfg.session_lost_cap:
            self._lost.popitem(last=False)
        while len(self._handoff) > self.cfg.session_lost_cap:
            self._handoff.popitem(last=False)
        self.lost_ledger_size.set(len(self._lost))

    def _expire_lost_locked(self, now: float) -> None:
        for table in (self._lost, self._handoff):
            while table:
                sid, (_x, t) = next(iter(table.items()))
                if now - t <= self.cfg.session_lost_ttl_s:
                    break
                del table[sid]
        self.lost_ledger_size.set(len(self._lost))

    # ------------------------------------------------------- drain handoff
    def _begin_drain_locked(self, rep: Replica) -> None:
        """A replica reported draining: out of rotation NOW (no new
        frames land on it), sessions kept — the handoff manifest remaps
        them; only if the process dies without one do they fall through
        to the typed-loss path."""
        if rep.name in self.ring:
            self.ring.remove(rep.name)
            self._note_ready_locked()
            rep.last_state_change_ts = time.time()
            self._transitions.append({
                "t": self._clock(), "replica": rep.name,
                "event": "draining"})
            log.info("replica %s draining: out of rotation, awaiting "
                     "session handoff manifest", rep.name)
        if rep.name not in self._drain_pending:
            self._drain_pending[rep.name] = rep

    def _fetch_handoff(self, rep: Replica) -> bool:
        """One attempt to fetch + apply a draining replica's handoff
        manifest (outside the lock; retried every probe pass while the
        replica keeps answering).  True once applied."""
        try:
            manifest = rep.get_handoff(self.cfg.health_timeout_s)
        except ReplicaUnreachable:
            # Gone already — the probe-failure path converts whatever
            # is left in the session table to typed losses.
            return False
        if manifest is None:
            return False            # not published yet; poll again
        sids = [str(s) for s in (manifest.get("sessions") or ())]
        key = manifest.get("artifact")
        now = self._clock()
        with self._lock:
            if rep.name not in self._drain_pending:
                return True         # a concurrent fetch won
            self._drain_pending.pop(rep.name, None)
            remapped = 0
            for sid in sids:
                self._session_table.pop(sid, None)
                if key:
                    self._handoff[sid] = (str(key), now)
                    self._handoff.move_to_end(sid)
                    remapped += 1
            self._bound_ledgers_locked()
            self._transitions.append({
                "t": now, "replica": rep.name, "event": "handoff",
                "sessions": remapped})
        if remapped:
            self._ledger_append("handoff", sids=sids,
                                artifact=str(key), replica=rep.name)
            self.handoff_sessions.inc(remapped)
        self.handoff_manifests.inc()
        log.info("replica %s handed off %d session(s) via artifact %s",
                 rep.name, remapped, key and str(key)[:12])
        return True

    def _await_drain_handoff(self, session_id: str) -> None:
        """A frame arrived for a session whose owner is draining but
        whose manifest has not been fetched yet: fetch it inline,
        bounded — the alternative is routing the frame cold and losing
        the warmth the drain carefully exported."""
        with self._lock:
            owner = self._session_table.get(session_id)
            rep = self._drain_pending.get(owner) if owner else None
        if rep is None:
            return
        deadline = self._clock() + self.cfg.handoff_fetch_timeout_s
        while self._clock() < deadline:
            if self._fetch_handoff(rep):
                return
            with self._lock:
                if rep.name not in self._drain_pending:
                    return
            time.sleep(0.05)

    def _handoff_key(self, session_id: str) -> Optional[str]:
        with self._lock:
            entry = self._handoff.get(session_id)
        return entry[0] if entry else None

    @staticmethod
    def _draining_503(status: int, payload: bytes) -> bool:
        """Whether a forwarded response IS the replica's typed draining
        shed — the race where a frame reached a replica between its
        SIGTERM and the router's next probe."""
        if status != 503:
            return False
        try:
            body = json.loads(payload)
        except ValueError:
            return False
        return bool(body.get("error") == "overloaded"
                    and body.get("draining"))

    # ------------------------------------------------------------- HA pair
    def _ledger_append(self, kind: str, **fields) -> bool:
        """Append one record when this router is the ACTIVE ledger
        writer; silently true in single-router mode (no ledger)."""
        if self.ledger is None:
            return True
        if not self.active:
            return False
        ok = self.ledger.append(kind, **fields)
        if not ok:
            # Fenced: the peer took over while we were serving.  Demote
            # — keep forwarding traffic, stop writing shared state.
            self.active = False
            self.active_gauge.set(0)
            log.warning("router %s fenced out of the ledger; demoted "
                        "to standby", self.cfg.router_name)
        return ok

    def _replay_ledger(self) -> None:
        """Rebuild the replicated session-loss/handoff state from the
        ledger (activation/takeover): owed losses minus fired ones
        re-arm, fired ones stay fired (never a second 410 for one id),
        handoffs re-arm the warm remap."""
        if self.ledger is None:
            return
        pending: "OrderedDict[str, str]" = OrderedDict()
        handoffs: "OrderedDict[str, str]" = OrderedDict()
        for rec in self.ledger.replay():
            kind = rec.get("kind")
            if kind == "lost":
                for sid in rec.get("sids") or ():
                    pending[str(sid)] = str(rec.get("replica"))
                    handoffs.pop(str(sid), None)
            elif kind == "fired":
                pending.pop(str(rec.get("sid")), None)
            elif kind == "handoff":
                for sid in rec.get("sids") or ():
                    handoffs[str(sid)] = str(rec.get("artifact"))
                    pending.pop(str(sid), None)
        now = self._clock()
        with self._lock:
            for sid, replica in pending.items():
                if sid not in self._lost:
                    self._lost[sid] = (replica, now)
            for sid, artifact in handoffs.items():
                if sid not in self._handoff:
                    self._handoff[sid] = (artifact, now)
            self._bound_ledgers_locked()
        log.info("ledger replayed: %d owed loss(es), %d handoff "
                 "remap(s) re-armed", len(pending), len(handoffs))

    def _probe_peer(self) -> bool:
        """One liveness poke at the peer router's /healthz (any HTTP
        answer counts — we only need to know the process is there)."""
        url = self.cfg.peer_url
        if not url:
            return True
        parsed = urlparse(url)
        conn = http.client.HTTPConnection(
            parsed.hostname or "127.0.0.1", parsed.port or 80,
            timeout=self.cfg.health_timeout_s)
        try:
            conn.request("GET", "/healthz")
            conn.getresponse().read()
            return True
        except (OSError, http.client.HTTPException):
            return False
        finally:
            conn.close()

    def _ha_tick(self) -> None:
        """One HA heartbeat, from the health loop: the active router
        renews its lease (and compacts the ledger occasionally); the
        standby watches lease staleness + the peer and takes over."""
        if self.ledger is None:
            return
        if self.active:
            if not self.ledger.renew():
                self.active = False
                self.active_gauge.set(0)
                log.warning("router %s lost the lease; now standby",
                            self.cfg.router_name)
                return
            now = time.time()
            if now - self._last_compact > 60.0:
                self._last_compact = now
                self.ledger.compact(
                    now - 4 * max(self.cfg.session_lost_ttl_s, 60.0))
            return
        # Standby: lease staleness is the authoritative signal; a peer
        # probe failing peer_fail_after times accelerates detection of
        # a hard kill (and is SAFE — taking over bumps the epoch, so a
        # merely-partitioned primary is fenced, not duplicated).
        stale = self.ledger.is_stale(self.cfg.lease_ttl_s)
        peer_dead = False
        if self.cfg.peer_url:
            if self._probe_peer():
                self._peer_failures = 0
            else:
                self._peer_failures += 1
                peer_dead = (self._peer_failures
                             >= self.cfg.peer_fail_after)
        if stale or peer_dead:
            self.takeover()

    def takeover(self) -> int:
        """Become the active ledger writer: bump the fencing epoch,
        replay the ledger, start appending.  Public for tests/ops;
        idempotent when already active."""
        if self.ledger is None or self.active:
            return self.ledger.epoch if self.ledger else 0
        epoch = self.ledger.acquire()
        self._replay_ledger()
        self.active = True
        self.active_gauge.set(1)
        self.takeovers.inc()
        self._peer_failures = 0
        with self._lock:
            self._transitions.append({
                "t": self._clock(), "replica": self.cfg.router_name,
                "event": "takeover", "epoch": epoch})
        log.warning("router %s TOOK OVER at epoch %d (lease stale or "
                    "peer dead); ledger replayed", self.cfg.router_name,
                    epoch)
        return epoch

    # ------------------------------------------------- fleet observability
    def slo_tick(self) -> Dict[str, float]:
        """One burn-rate sample + watchdog evaluation (public: the
        health loop drives it on the poll cadence; tests and the smoke
        call it directly for deterministic stepping).  Good = summed
        replica admissions; bad = summed replica deadline misses plus
        the router's OWN typed failures and slow forwards — the
        satellite-6 fix that makes a dead fleet burn budget even though
        no replica ever saw those requests."""
        with self._lock:
            admitted = missed = 0
            for rep in self.replicas.values():
                if rep.health is None:
                    continue
                admitted += rep.health.admitted
                missed += rep.health.deadline_missed
        bad = missed + self.slo_errors.value + self.slo_slow.value
        burns = self.slo.sample(float(admitted), float(bad))
        self.slo_watchdog.check(burns)
        return burns

    def note_latency(self, elapsed_ms: float) -> None:
        """Router-observed end-to-end latency for one forwarded request
        (fleet/http.py clocks it): above the ``--slo_ms`` objective it
        burns error budget like a failure — a fleet that answers
        everything slowly is NOT meeting its SLO."""
        if self.cfg.slo_ms is not None and elapsed_ms > self.cfg.slo_ms:
            self.slo_slow.inc()

    def coordinated_dump(self, trigger_trace_id: str,
                         detail: Optional[Dict] = None
                         ) -> Dict[str, object]:
        """The fleet-wide capture an SLO breach triggers: one router
        flight-recorder bundle + a forced ``POST /debug/flightrecorder``
        on every alive replica, linked by ONE manifest keyed on the
        trigger trace id — the post-incident artifact is a single file
        naming every bundle, not N directories to correlate by mtime.
        Bounded: each replica POST gets ``health_timeout_s``."""
        router_bundle = None
        if self.recorder is not None:
            router_bundle = self.recorder.dump(
                "fleet_coordinated", detail=detail, force=True)
        with self._lock:
            members = [(n, r) for n, r in self.replicas.items()
                       if r.alive]
        replica_bundles: Dict[str, object] = {}
        for name, rep in members:
            try:
                replica_bundles[name] = rep.post_flightrecorder(
                    self.cfg.health_timeout_s)
            except ReplicaUnreachable:
                replica_bundles[name] = None
        manifest: Dict[str, object] = {
            "trigger_trace_id": trigger_trace_id,
            "router": self.cfg.router_name,
            "router_bundle": router_bundle,
            "replicas": replica_bundles,
            "detail": detail or {},
        }
        if self.cfg.flight_recorder_dir:
            os.makedirs(self.cfg.flight_recorder_dir, exist_ok=True)
            path = os.path.join(self.cfg.flight_recorder_dir,
                                f"fleet-{trigger_trace_id}.json")
            with open(path, "w") as f:
                json.dump(manifest, f, indent=2, default=str)
            manifest["manifest_path"] = path
        self.fleet_dumps.append(manifest)
        log.warning("coordinated fleet dump (trigger trace %s): router "
                    "bundle %s, %d replica bundle(s)", trigger_trace_id,
                    router_bundle,
                    sum(1 for b in replica_bundles.values() if b))
        return manifest

    def federated_trace(self, trace_id: str) -> Dict[str, object]:
        """One trace id's spans merged across the fleet: the router's
        own ring plus every alive replica's ``GET /debug/spans?trace=``
        answer, each span tagged with its ``process`` — the whole
        cross-process story behind one id.  Replicas without the trace
        contribute nothing (the common case: only the owning replica
        holds the server-side half); an unreachable replica is recorded
        in ``sources`` as -1, never an error."""
        spans: List[Dict[str, object]] = []
        sources: Dict[str, int] = {}
        if self.tracer is not None:
            own = [dict(s.to_dict(), process="router")
                   for s in self.tracer.spans()
                   if s.trace_id == trace_id]
            spans.extend(own)
            sources["router"] = len(own)
        with self._lock:
            members = [(n, r) for n, r in self.replicas.items()
                       if r.alive]
        for name, rep in members:
            try:
                got = rep.get_spans(trace_id,
                                    self.cfg.health_timeout_s)
            except ReplicaUnreachable:
                sources[name] = -1
                continue
            sources[name] = len(got)
            spans.extend(dict(s, process=name) for s in got
                         if isinstance(s, dict))
        spans.sort(key=lambda s: (s.get("start_us") or 0.0))
        return {"trace_id": trace_id, "sources": sources,
                "spans": spans}

    # -------------------------------------------------------------- routing
    def _ready_replicas_locked(self) -> List[Replica]:
        return [r for r in self.replicas.values() if r.ready]

    def pick_stateless(self, exclude: Sequence[str] = (),
                       require_xl: bool = False) -> Replica:
        """Least-loaded ready replica (queue depth, then inflight, from
        the last probe), round-robin among equals; raises
        ``NoReplicasAvailable`` when the rotation is empty.  With
        ``require_xl`` only replicas whose last probe advertised the
        mesh tier qualify — none in rotation raises the typed
        ``XlUnavailable`` instead of bouncing the request off a replica
        that would 400 it."""
        with self._lock:
            ready = [r for r in self._ready_replicas_locked()
                     if r.name not in exclude]
            if require_xl:
                capable_total = sum(
                    1 for r in self.replicas.values()
                    if r.health is not None and r.health.xl_capable)
                ready = [r for r in ready
                         if r.health is not None and r.health.xl_capable]
                if not ready:
                    raise XlUnavailable(0, capable_total,
                                        len(self.replicas))
            if not ready:
                raise NoReplicasAvailable(
                    f"no ready replica (fleet of {len(self.replicas)}; "
                    f"excluded {sorted(exclude)})")
            key = lambda r: (r.health.load if r.health else (0, 0))
            best = min(key(r) for r in ready)
            tied = [r for r in ready if key(r) == best]
            self._rr += 1
            return tied[self._rr % len(tied)]

    def pick_session(self, session_id: str) -> Replica:
        """The ring's replica for this session id; raises ``SessionLost``
        (once) for ids whose replica left the rotation, and
        ``NoReplicasAvailable`` on an empty rotation."""
        with self._lock:
            self._expire_lost_locked(self._clock())
            entry = self._lost.pop(session_id, None)
            if entry is not None:
                # Fire-once: the id is forgotten now, so the client's
                # reseed (the next frame on this or a fresh id) routes
                # normally and cold-starts on a surviving replica.  The
                # ledger records the delivery FIRST, so an HA peer
                # replaying after a router kill never fires a second
                # 410 for this id.
                self.lost_ledger_size.set(len(self._lost))
                self._ledger_append("fired", sid=session_id,
                                    replica=entry[0])
                self.slo_errors.inc()
                raise SessionLost(session_id, entry[0])
            name = self.ring.lookup(session_id)
            if name is None:
                self.slo_errors.inc()
                raise NoReplicasAvailable(
                    "no ready replica to own this session")
            rep = self.replicas[name]
            self._session_table[session_id] = name
            return rep

    def forget_session(self, session_id: str) -> None:
        """Drop a session from the routing ledger (its replica answered
        a close, a 410, or the stream ended)."""
        with self._lock:
            self._session_table.pop(session_id, None)
            self._handoff.pop(session_id, None)

    def note_transport_failure(self, rep: Replica) -> None:
        """A forwarded request hit a transport error on ``rep``: out of
        rotation immediately (a burned request outranks ``fail_after``
        probe patience); the health loop will re-admit it when it
        answers probes again."""
        with self._lock:
            if rep.alive or rep.name in self.ring:
                self._remove_from_rotation_locked(rep, "transport_error")

    # ----------------------------------------------------------- forwarding
    @staticmethod
    def _wants_xl(path_qs: str,
                  headers: Sequence[Tuple[str, str]]) -> bool:
        """Whether this request names the xl tier (``?tier=xl`` or the
        ``X-Tier: xl`` header) — the routing-visible part of the r17
        tier selection; everything else about the request stays opaque
        to the router."""
        query = parse_qs(urlparse(path_qs).query)
        tiers = query.get("tier")
        if tiers and tiers[-1] == "xl":
            return True
        return any(k.lower() == "x-tier" and v.strip() == "xl"
                   for k, v in headers)

    @staticmethod
    def _names_model(path_qs: str,
                     headers: Sequence[Tuple[str, str]]) -> bool:
        """Whether the CLIENT already picked a model (``?model=`` or
        ``X-Model``) — the rollout policy never overrides an explicit
        choice, it only splits the default-model traffic."""
        query = parse_qs(urlparse(path_qs).query)
        if query.get("model"):
            return True
        return any(k.lower() == "x-model" and v.strip()
                   for k, v in headers)

    def forward_stateless(self, method: str, path_qs: str,
                          body: Optional[bytes],
                          headers: Sequence[Tuple[str, str]],
                          trace: Optional[Trace] = None
                          ) -> Tuple[int, List[Tuple[str, str]], bytes]:
        """Forward one stateless request with transport-level failover:
        a replica that dies mid-request burns one attempt, the request
        re-dispatches to the next ready replica (inference is a pure
        function of the request body — the retry is safe), and only
        ``route_retries`` exhausted or an empty rotation surfaces as an
        error.  HTTP error responses are answers, not failures — they
        forward verbatim, no retry.  Requests naming the xl tier route
        only to xl-capable replicas (typed ``XlUnavailable`` when the
        rotation has none).

        With a canary armed (fleet/rollout.py) a deterministic hash of
        the body routes the configured fraction of requests that named
        NO model themselves to the canary version (``X-Model`` injected
        before forwarding), and a sampled remainder is mirrored to it
        fire-and-forget for shadow comparison — the client always gets
        the primary answer."""
        require_xl = self._wants_xl(path_qs, headers)
        # Rollout split: inference POSTs only, and never a request that
        # already named a model.
        canary: Optional[str] = None
        shadow = False
        if (method == "POST" and body
                and urlparse(path_qs).path == "/v1/disparity"
                and self.rollout.active
                and not self._names_model(path_qs, headers)):
            split_t0 = time.perf_counter()
            canary = self.rollout.assign(body)
            if canary is not None:
                headers = list(headers) + [("X-Model", canary)]
            else:
                shadow = self.rollout.wants_shadow(body)
            self.tracer.add_span(
                "route.canary_split", trace, split_t0,
                time.perf_counter(),
                arm=("canary" if canary else
                     "shadow" if shadow else "baseline"))
        tried: List[str] = []
        last: Optional[ReplicaUnreachable] = None
        for attempt in range(self.cfg.route_retries):
            pick_t0 = time.perf_counter()
            try:
                rep = self.pick_stateless(exclude=tried,
                                          require_xl=require_xl)
            except XlUnavailable:
                self.xl_unroutable.inc()
                self.unroutable.inc()
                self.slo_errors.inc()
                raise
            except NoReplicasAvailable:
                if last is None:
                    self.unroutable.inc()
                    self.slo_errors.inc()
                    raise
                break
            tried.append(rep.name)
            if attempt > 0:
                self.route_retries.inc()
            self.tracer.add_span("route.pick", trace, pick_t0,
                                 time.perf_counter(), replica=rep.name,
                                 attempt=attempt)
            fwd_headers, fwd_span = self._traced_headers(
                headers, trace, rep, attempt)
            try:
                status, h, payload = rep.forward(
                    method, path_qs, body, fwd_headers,
                    self.cfg.request_timeout_s)
            except ReplicaUnreachable as e:
                if fwd_span is not None:
                    fwd_span.set_attr("error", "transport")
                    self.tracer.finish(fwd_span)
                last = e
                self.note_transport_failure(rep)
                log.warning("stateless %s %s: replica %s died "
                            "mid-request (attempt %d); failing over",
                            method, path_qs, rep.name, attempt + 1)
                continue
            if fwd_span is not None:
                fwd_span.set_attr("status", status)
                self.tracer.finish(fwd_span)
            self._note_routed("stateless", rep.name)
            if canary is not None:
                # 5xx means the canary arm failed the request; a 4xx is
                # the client's fault on either arm and says nothing
                # about the weights.
                self.rollout.note_canary_result(status < 500)
            elif shadow and status == 200:
                self._mirror_shadow(path_qs, body, headers, payload, h)
            return status, h, payload
        if canary is not None:
            # The canary arm never answered at all: transport-level
            # evidence against it (shared with the fleet-health path —
            # a dead fleet demotes nothing by itself thanks to
            # min_samples).
            self.rollout.note_canary_result(False)
        self.unroutable.inc()
        self.slo_errors.inc()
        raise NoReplicasAvailable(
            f"all {len(tried)} dispatch attempt(s) hit transport "
            f"failures (tried {tried}): {last}")

    def _traced_headers(self, headers: Sequence[Tuple[str, str]],
                        trace: Optional[Trace], rep: Replica,
                        attempt: int):
        """Per-attempt trace propagation: open one ``route.forward``
        span and attach ``traceparent`` naming it, so the replica's
        ``serve.request`` parents to the attempt that actually reached
        it (a failover shows two forward children, the survivor owning
        the server-side subtree).  The router OWNS the header while
        tracing (a client-supplied value must not graft onto our
        trace); untraced (sample rate 0) the headers pass through
        UNTOUCHED — byte-verbatim contract, and a client's own
        traceparent still reaches the replica."""
        if trace is None:
            return headers, None
        span = self.tracer.start_span("route.forward", trace,
                                      replica=rep.name, attempt=attempt)
        if span is None:
            return headers, None
        fwd = [(k, v) for k, v in headers
               if k.lower() != TRACE_CONTEXT_HEADER]
        fwd.append((TRACE_CONTEXT_HEADER,
                    encode_traceparent(trace.trace_id, span.span_id)))
        return fwd, span

    # ------------------------------------------------------- shadow mirror
    def _mirror_shadow(self, path_qs: str, body: bytes,
                       headers: Sequence[Tuple[str, str]],
                       primary_payload: bytes,
                       primary_headers: Sequence[Tuple[str, str]] = ()
                       ) -> None:
        """Fire-and-forget mirror of one baseline request to the canary
        version on a short-lived thread: the shadow answer is compared
        against the primary's disparity (mean EPE divergence) — and,
        when both arms answered with ``X-Confidence``, against the
        primary's confidence (round 24) — recorded into the rollout
        policy's regression windows, and DROPPED — never returned,
        never retried, never allowed to fail the client's request."""
        threading.Thread(
            target=self._shadow_once,
            args=(path_qs, body, list(headers), primary_payload,
                  list(primary_headers)),
            daemon=True, name="fleet-shadow").start()

    def _shadow_once(self, path_qs: str, body: bytes,
                     headers: List[Tuple[str, str]],
                     primary_payload: bytes,
                     primary_headers: List[Tuple[str, str]]) -> None:
        try:
            model = self.rollout.canary_model()
            if model is None:
                return
            fwd = [(k, v) for k, v in headers
                   if k.lower() != "x-model"]
            fwd.append(("X-Model", model[0]))
            rep = self.pick_stateless()
            status, h, payload = rep.forward(
                "POST", path_qs, body, fwd, self.cfg.request_timeout_s)
            if status != 200:
                self.rollout.note_canary_result(status < 500)
                return
            epe = self._payload_epe(primary_payload, payload)
            if epe is not None:
                self.rollout.note_shadow_epe(epe)
            delta = self._confidence_delta(primary_headers, h)
            if delta is not None:
                self.rollout.note_shadow_confidence(delta)
        except (ReplicaUnreachable, NoReplicasAvailable):
            pass        # no capacity for shadows is not canary evidence
        except Exception:  # pragma: no cover — mirror must never raise
            log.exception("shadow mirror failed")

    @staticmethod
    def _confidence_delta(primary_headers: Sequence[Tuple[str, str]],
                          shadow_headers: Sequence[Tuple[str, str]]
                          ) -> Optional[float]:
        """Primary minus canary mean confidence from the two responses'
        ``X-Confidence`` headers (positive = the canary is less sure);
        None unless BOTH arms served with confidence telemetry — absent
        headers are not evidence."""
        def _conf(hs):
            for k, v in hs:
                if k.lower() == "x-confidence":
                    try:
                        return float(v)
                    except ValueError:
                        return None
            return None

        a, b = _conf(primary_headers), _conf(shadow_headers)
        if a is None or b is None:
            return None
        return a - b

    @staticmethod
    def _payload_epe(primary: bytes, shadow: bytes) -> Optional[float]:
        """Mean |EPE| between two ``.npy`` disparity payloads; None when
        either payload is not a comparable array (png responses, shape
        mismatch) — the compare is evidence, not a contract."""
        import io

        import numpy as np
        try:
            a = np.load(io.BytesIO(primary), allow_pickle=False)
            b = np.load(io.BytesIO(shadow), allow_pickle=False)
        except Exception:
            return None
        if getattr(a, "shape", None) != getattr(b, "shape", None) \
                or a.shape == ():
            return None
        return float(np.mean(np.abs(np.asarray(a, np.float32)
                                    - np.asarray(b, np.float32))))

    def _forward_session_once(self, session_id: str, method: str,
                              path_qs: str, body: Optional[bytes],
                              headers: Sequence[Tuple[str, str]],
                              trace: Optional[Trace] = None
                              ) -> Tuple[Replica, int,
                                         List[Tuple[str, str]], bytes]:
        """One sticky dispatch: pick the owner, tag the frame with its
        handoff artifact when the id was handed off, forward."""
        pick_t0 = time.perf_counter()
        rep = self.pick_session(session_id)   # SessionLost / NoReplicas
        self.tracer.add_span("route.pick", trace, pick_t0,
                             time.perf_counter(), replica=rep.name,
                             session=session_id)
        key = self._handoff_key(session_id)
        # The router OWNS this header: a client-supplied value must not
        # reach a replica (it would point the import at an arbitrary
        # store key).
        fwd_headers = [(k, v) for k, v in headers
                       if k.lower() != "x-handoff-artifact"]
        if key is not None:
            fwd_headers.append(("X-Handoff-Artifact", key))
            self.tracer.add_span("route.handoff_remap", trace, pick_t0,
                                 time.perf_counter(),
                                 replica=rep.name,
                                 artifact=str(key)[:16])
        fwd_headers, fwd_span = self._traced_headers(
            fwd_headers, trace, rep, 0)
        try:
            status, h, payload = rep.forward(
                method, path_qs, body, fwd_headers,
                self.cfg.request_timeout_s)
        except ReplicaUnreachable:
            if fwd_span is not None:
                fwd_span.set_attr("error", "transport")
                self.tracer.finish(fwd_span)
            self.note_transport_failure(rep)
            with self._lock:
                # pick_session recorded the route; the death path above
                # may have tombstoned it already — pop either way so the
                # 410 fires exactly once, right now.
                self._session_table.pop(session_id, None)
                self._lost.pop(session_id, None)
                self._handoff.pop(session_id, None)
                self.lost_ledger_size.set(len(self._lost))
            self._ledger_append("fired", sid=session_id,
                                replica=rep.name)
            self.slo_errors.inc()
            raise SessionLost(session_id, rep.name) from None
        if fwd_span is not None:
            fwd_span.set_attr("status", status)
            self.tracer.finish(fwd_span)
        if key is not None and status == 200:
            # Adopted: the inheriting replica now owns the live state.
            with self._lock:
                self._handoff.pop(session_id, None)
        return rep, status, h, payload

    def forward_session(self, session_id: str, method: str, path_qs: str,
                        body: Optional[bytes],
                        headers: Sequence[Tuple[str, str]],
                        trace: Optional[Trace] = None
                        ) -> Tuple[int, List[Tuple[str, str]], bytes]:
        """Forward one session-sticky request.  No transport failover:
        the session's state lives on exactly one replica, so a transport
        failure there IS the loss of the session — the replica leaves
        the rotation and this request (and only this one) fails typed
        with ``SessionLost``.  Planned drains are different: a frame
        that races the drain (typed 503 draining answer, or an owner
        whose manifest is still in flight) waits for the handoff
        manifest — bounded — and retries ONCE on the inheriting replica,
        so a rolling restart is zero-loss even for frames already in
        the air."""
        self._await_drain_handoff(session_id)
        rep, status, h, payload = self._forward_session_once(
            session_id, method, path_qs, body, headers, trace=trace)
        if self._draining_503(status, payload):
            # The frame beat the router's probe to a draining replica.
            # Treat the typed shed AS the drain signal: out of
            # rotation, fetch the manifest (bounded), re-pick — the
            # ring now maps the id to a survivor — and retry the frame
            # there with its handoff tag.  The draining replica never
            # admitted it, so the retry cannot double-dispatch.
            with self._lock:
                self._begin_drain_locked(rep)
            remap_t0 = time.perf_counter()
            self._await_drain_handoff_for(rep)
            self.tracer.add_span("route.handoff_remap", trace, remap_t0,
                                 time.perf_counter(), replica=rep.name,
                                 reason="drain_race")
            retry_rep, status, h, payload = self._forward_session_once(
                session_id, method, path_qs, body, headers, trace=trace)
            log.info("session %s frame raced replica %s's drain; "
                     "re-routed to %s", session_id, rep.name,
                     retry_rep.name)
            rep = retry_rep
        self._note_routed("session", rep.name)
        if status == 410 or (method == "DELETE" and status == 200):
            self.forget_session(session_id)
        return status, h, payload

    def _await_drain_handoff_for(self, rep: Replica) -> None:
        """Bounded manifest wait for one specific draining replica."""
        deadline = self._clock() + self.cfg.handoff_fetch_timeout_s
        while self._clock() < deadline:
            with self._lock:
                if rep.name not in self._drain_pending:
                    return
            if self._fetch_handoff(rep):
                return
            time.sleep(0.05)

    # ----------------------------------------------------- fleet membership
    def add_replica(self, name: str, url: str) -> Replica:
        """Register a new fleet member at runtime (the autoscaler's
        scale-up seam).  It joins the rotation when its probes go ready
        — no traffic lands on it before /readyz opens."""
        with self._lock:
            if name in self.replicas:
                raise ValueError(f"replica {name!r} already registered")
            rep = Replica(name, url)
            rep.alive = False        # in rotation only after a probe
            self.replicas[name] = rep
            self.replicas_total.set(len(self.replicas))
            self._transitions.append({
                "t": self._clock(), "replica": name, "event": "added"})
        log.info("replica %s added at %s (%d configured)", name, url,
                 len(self.replicas))
        return rep

    def remove_replica(self, name: str) -> None:
        """Deregister a fleet member (the autoscaler's post-drain
        cleanup).  Sessions still mapped to it — there should be none
        after a handoff — fail typed, never silently."""
        with self._lock:
            rep = self.replicas.get(name)
            if rep is None:
                return
            self._remove_from_rotation_locked(rep, "deregistered")
            del self.replicas[name]
            self.replicas_total.set(len(self.replicas))
            self._note_ready_locked()

    def fleet_pressure(self) -> Dict[str, object]:
        """The aggregate pressure signal the autoscaler consumes:
        queued fraction across ready replicas (None when nothing
        reports a limit), the fleet brownout level, and the summed
        admitted/deadline-miss totals (the caller differences them
        into a rate)."""
        with self._lock:
            admitted = missed = 0
            for rep in self._ready_replicas_locked():
                if rep.health is None:
                    continue
                admitted += rep.health.admitted
                missed += rep.health.deadline_missed
            return {
                "queued_fraction": self._fleet_pressure_locked(),
                "brownout_level": self.brownout_level,
                "brownout_max_level": self.cfg.brownout_max_level,
                "admitted_total": admitted,
                "deadline_missed_total": missed,
                "ready": len(self.ring),
                "total": len(self.replicas),
            }

    # -------------------------------------------------------- fleet brownout
    def _fleet_pressure_locked(self) -> Optional[float]:
        """Aggregate queued fraction across ready replicas; None when no
        replica reports a queue limit (nothing to measure)."""
        depth = limit = 0
        for rep in self._ready_replicas_locked():
            if rep.health is None or rep.health.queue_limit <= 0:
                continue
            depth += rep.health.queue_depth
            limit += rep.health.queue_limit
        if limit <= 0:
            return None
        return depth / limit

    def _brownout_poll(self) -> None:
        if not self.cfg.fleet_brownout:
            return
        now = self._clock()
        push: Optional[Tuple[Replica, ...]] = None
        with self._lock:
            pressure = self._fleet_pressure_locked()
            if pressure is None:
                return
            level = self.brownout_level
            if pressure >= self.cfg.brownout_engage_fraction:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                elif (now - self._pressure_since
                        >= self.cfg.brownout_engage_s
                        and level < self.cfg.brownout_max_level):
                    self.brownout_level = level + 1
                    self._pressure_since = now
                    push = tuple(r for r in self.replicas.values()
                                 if r.alive)
            elif pressure <= self.cfg.brownout_restore_fraction:
                self._pressure_since = None
                if level > 0:
                    if self._calm_since is None:
                        self._calm_since = now
                    elif (now - self._calm_since
                            >= self.cfg.brownout_restore_s):
                        self.brownout_level = level - 1
                        self._calm_since = now
                        push = tuple(r for r in self.replicas.values()
                                     if r.alive)
                else:
                    self._calm_since = None
            else:
                self._pressure_since = None
                self._calm_since = None
            if push is not None:
                new_level = self.brownout_level
                self.brownout_gauge.set(new_level)
                log.warning("fleet brownout level %d -> %d (aggregate "
                            "queued fraction %.2f)", level, new_level,
                            pressure)
        if push is not None:
            self._push_brownout(push)

    def _push_brownout(self, reps: Sequence[Replica]) -> None:
        for rep in reps:
            try:
                if rep.post_brownout(self.brownout_level,
                                     self.cfg.health_timeout_s):
                    self.brownout_pushes.inc()
            except ReplicaUnreachable:
                pass    # the health loop will notice and re-push on rejoin

    def _push_brownout_locked(self, reps: Sequence[Replica]) -> None:
        """Re-push the current floor to a rejoining replica — fired from
        inside the lock; the actual I/O rides a short-lived thread so
        the probe pass is never blocked on a slow member."""
        threading.Thread(
            target=lambda: self._push_brownout(reps),
            daemon=True, name="fleet-brownout-push").start()

    # --------------------------------------------------------------- status
    def fleet_status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "replicas": {name: rep.stats()
                             for name, rep in self.replicas.items()},
                "in_rotation": list(self.ring.members),
                "ready": len(self.ring),
                "total": len(self.replicas),
                "sessions_routed": len(self._session_table),
                "sessions_pending_loss": len(self._lost),
                "sessions_pending_handoff": len(self._handoff),
                "draining_replicas": sorted(self._drain_pending),
                "brownout_level": self.brownout_level,
                "role": ("single" if self.ledger is None
                         else "primary" if self.active else "standby"),
                "epoch": self.ledger.epoch if self.ledger else None,
                "rollout": self.rollout.status(),
                "slo": self.slo.status(),
                "federation": self.federator.status(),
                "fleet_dumps": len(self.fleet_dumps),
                "transitions": list(self._transitions[-50:]),
            }
