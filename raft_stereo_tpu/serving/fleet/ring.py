"""Consistent-hash ring: session ids -> replicas, with minimal remap.

Streaming sessions (serving/sessions.py) are sticky per-replica state —
a session's warm-start chain lives in exactly one engine's SessionStore,
so the router must send every frame of one session to the same replica.
A modulo hash would do that too, but replica loss under mod-N remaps
(N-1)/N of ALL sessions (every surviving stream breaks because an
unrelated replica died).  Consistent hashing (Karger et al., STOC '97)
bounds the blast radius: each member owns ``vnodes`` pseudo-random
points on a 2^64 ring, a key maps to the first member point at or after
its own hash, and removing a member only reassigns the keys that hashed
to ITS points — ~1/N of the keyspace, the sessions that were already
lost with the replica.  Re-adding the member restores its points (they
are a pure function of the member name), so the original assignment
comes back exactly.

SHA-256 everywhere for the same reason as serving/chaos.py: the mapping
must be identical across processes, platforms, and PYTHONHASHSEED — a
router restart must not reshuffle live sessions, and two routers in
front of one fleet must agree.

Pure data structure, no I/O, no threads (the router serializes access);
tests/test_fleet.py pins the invariants.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

# Points per member.  At 64 vnodes the max/mean keyspace-share ratio
# across members stays within ~2x for small fleets — good enough for a
# load split the stateless path doesn't even use (it balances by
# measured queue depth; the ring only pins SESSIONS).
DEFAULT_VNODES = 64


def _point(name: str, vnode: int) -> int:
    digest = hashlib.sha256(f"{name}#{vnode}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


def _key_point(key: str) -> int:
    digest = hashlib.sha256(f"key:{key}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


class HashRing:
    """Members (replica names) on a 2^64 consistent-hash ring.

    ``lookup`` maps a key to a live member; ``remove``/``add`` change
    membership with the ~1/N remap guarantee.  An empty ring looks up to
    None.  Member points are deterministic in the member NAME alone, so
    add(remove(x)) restores the exact prior assignment.
    """

    def __init__(self, members: Sequence[str] = (),
                 vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError(f"vnodes={vnodes} must be >= 1")
        self.vnodes = vnodes
        self._members: Dict[str, Tuple[int, ...]] = {}
        self._points: List[int] = []      # sorted ring points
        self._owner: List[str] = []       # _owner[i] owns _points[i]
        for m in members:
            self.add(m)

    # ------------------------------------------------------------ membership
    @property
    def members(self) -> Tuple[str, ...]:
        return tuple(sorted(self._members))

    def __contains__(self, name: str) -> bool:
        return name in self._members

    def __len__(self) -> int:
        return len(self._members)

    def _rebuild(self) -> None:
        pairs = sorted((p, name) for name, pts in self._members.items()
                       for p in pts)
        self._points = [p for p, _ in pairs]
        self._owner = [name for _, name in pairs]

    def add(self, name: str) -> None:
        """Add a member (idempotent).  Only keys falling into the new
        member's arcs move — everything else keeps its owner."""
        if name in self._members:
            return
        self._members[name] = tuple(_point(name, v)
                                    for v in range(self.vnodes))
        self._rebuild()

    def remove(self, name: str) -> None:
        """Remove a member (idempotent).  Keys it owned fall through to
        the next point on the ring; other keys are untouched — the
        ~1/N-remap property tests/test_fleet.py pins."""
        if self._members.pop(name, None) is not None:
            self._rebuild()

    # ---------------------------------------------------------------- lookup
    def lookup(self, key: str) -> Optional[str]:
        """The member owning ``key``, or None on an empty ring."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._points, _key_point(key))
        if i == len(self._points):      # wrap past the top of the ring
            i = 0
        return self._owner[i]

    def assignment(self, keys: Sequence[str]) -> Dict[str, Optional[str]]:
        """Bulk ``{key: member}`` snapshot (test/report helper)."""
        return {k: self.lookup(k) for k in keys}
