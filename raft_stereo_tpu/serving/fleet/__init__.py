"""Fleet-scale serving: replicated engines behind a session-sticky
router with failover, fleet-wide brownout, the shared executable
artifact store (serving/persist.py), and — round 18 — the operations
layer: session handoff on graceful drain, an HA router pair over a
fenced replicated ledger, and pressure-driven replica autoscaling.
See docs/architecture.md §Fleet.

* ``ring``       — consistent-hash ring (session id -> replica, ~1/N remap)
* ``replica``    — one fleet member: HTTP client + health state
* ``router``     — routing, failover, lost-session ledger, fleet brownout,
                   drain handoff, HA roles, xl-capability routing
* ``ledger``     — the HA pair's fenced lease + append-only ledger
* ``autoscaler`` — the pressure -> fleet-size control loop + launchers
* ``rollout``    — canary/shadow rollout policy (deterministic traffic
                   split onto a registered model version + hysteresis
                   auto-demotion; round 21 multi-model serving)
* ``federation`` — metrics federation: background replica scraper +
                   ``replica=``-labelled re-exposition (round 23
                   fleet observability)
* ``http``       — the router's HTTP front end (``raft-route``)
"""

from raft_stereo_tpu.serving.fleet.autoscaler import (AutoscaleConfig,
                                                      Autoscaler,
                                                      LocalProcessLauncher,
                                                      ReplicaLauncher,
                                                      serve_argv_template)
from raft_stereo_tpu.serving.fleet.federation import (MetricsFederator,
                                                      inject_label,
                                                      relabel_exposition)
from raft_stereo_tpu.serving.fleet.http import (RouterHTTPServer,
                                                make_router_handler,
                                                retry_after_jittered)
from raft_stereo_tpu.serving.fleet.ledger import FleetLedger
from raft_stereo_tpu.serving.fleet.replica import (Replica, ReplicaHealth,
                                                   ReplicaUnreachable)
from raft_stereo_tpu.serving.fleet.ring import DEFAULT_VNODES, HashRing
from raft_stereo_tpu.serving.fleet.rollout import (RolloutConfig,
                                                   RolloutPolicy)
from raft_stereo_tpu.serving.fleet.router import (FleetRouter,
                                                  NoReplicasAvailable,
                                                  RouterConfig, SessionLost,
                                                  XlUnavailable)

__all__ = ["DEFAULT_VNODES", "HashRing", "Replica", "ReplicaHealth",
           "ReplicaUnreachable", "FleetRouter", "NoReplicasAvailable",
           "RouterConfig", "SessionLost", "XlUnavailable",
           "RouterHTTPServer", "make_router_handler",
           "retry_after_jittered", "FleetLedger", "Autoscaler",
           "AutoscaleConfig", "ReplicaLauncher", "LocalProcessLauncher",
           "serve_argv_template", "RolloutConfig", "RolloutPolicy",
           "MetricsFederator", "inject_label", "relabel_exposition"]
