"""Fleet-scale serving: replicated engines behind a session-sticky
router with failover, fleet-wide brownout, and the shared executable
artifact store (serving/persist.py).  See docs/architecture.md §Fleet.

* ``ring``    — consistent-hash ring (session id -> replica, ~1/N remap)
* ``replica`` — one fleet member: HTTP client + health state
* ``router``  — routing, failover, lost-session ledger, fleet brownout
* ``http``    — the router's HTTP front end (``raft-route``)
"""

from raft_stereo_tpu.serving.fleet.http import (RouterHTTPServer,
                                                make_router_handler)
from raft_stereo_tpu.serving.fleet.replica import (Replica, ReplicaHealth,
                                                   ReplicaUnreachable)
from raft_stereo_tpu.serving.fleet.ring import DEFAULT_VNODES, HashRing
from raft_stereo_tpu.serving.fleet.router import (FleetRouter,
                                                  NoReplicasAvailable,
                                                  RouterConfig, SessionLost)

__all__ = ["DEFAULT_VNODES", "HashRing", "Replica", "ReplicaHealth",
           "ReplicaUnreachable", "FleetRouter", "NoReplicasAvailable",
           "RouterConfig", "SessionLost", "RouterHTTPServer",
           "make_router_handler"]
