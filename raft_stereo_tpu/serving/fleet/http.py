"""HTTP front end of the fleet router — the one address clients use.

The request surface is the replica surface (serving/http.py): ``POST
/v1/disparity`` and ``POST|DELETE /v1/stream/<id>`` forward verbatim —
body bytes, query string, ``X-*`` headers, typed error bodies and all —
to the replica the router picks, so a client cannot tell the router from
a single engine (the pass-through-parity contract tests/test_fleet.py
pins byte-for-byte).  On top of that the router adds its own fleet-level
surface:

* ``GET /healthz`` — router liveness + per-replica rotation summary.
* ``GET /readyz`` — 200 once at least one replica is in rotation (the
  fleet can answer SOMETHING), 503 otherwise; orchestrators point
  traffic here.
* ``GET /metrics`` — the router's own Prometheus registry
  (``fleet_replicas_ready``, ``fleet_failovers_total``,
  ``fleet_sessions_lost_total``, routing-decision counters).
* ``GET /fleet`` — full JSON status: replica states, ring membership,
  session ledger sizes, brownout level, rollout policy, recent
  transitions.
* ``GET|POST /admin/rollout`` — the canary/shadow rollout policy
  (fleet/rollout.py): ``{"action": "set", "model": "name@version",
  "fraction": F, "shadow_fraction": S}`` arms a deterministic traffic
  split onto a registered canary version; ``{"action": "clear"}``
  disarms; GET returns the live status (fractions, shadow-EPE window,
  demotion state).
* ``GET /metrics/fleet`` — the federated exposition (fleet/federation.py):
  the router's own registry plus every replica's last-scraped series
  re-labelled ``replica="<name>"``, with per-replica up/staleness
  gauges.  Cache-only on this path — the background poller does the
  scraping, so a dead replica can never hang a federation request.
* ``GET /debug/spans?trace=<id>`` — the FEDERATED trace view: the
  router's own spans for that id merged with every replica's
  (``route.request`` parent, ``serve.request`` child — the whole
  cross-process story under one trace id).  Without ``?trace=`` the
  router's own ring renders as Chrome trace JSON, and ``/debug/stacks``
  + ``/debug/flightrecorder`` expose the router process itself — the
  same per-process debug surface replicas carry.

When router-side tracing is on (``--trace_sample_rate``), sampled
requests answer with ``X-Trace-Id`` — including the router-originated
error responses below, so a client quoting a failure quotes the id that
finds it.  At the default rate 0 no header is added anywhere and
forwarding stays byte-verbatim.

Fleet-level typed errors (these are the ONLY responses the router
originates on the request path):

* 503 ``{"error": "no_replicas_ready"}`` + ``Retry-After`` — every
  replica is dead, warming, or draining (stateless retries exhausted).
* 410 ``{"error": "session_lost", "replica": ...}`` — this session's
  replica left the rotation; its warm-start chain is unrecoverable.
  Fired once per session: the client's next frame reseeds cold on a
  surviving replica (the r14 410 contract, fleet-wide).

Both count toward the SLO error totals (router.slo_errors) — fleet-typed
failures burn error budget exactly like replica-side ones.
"""

from __future__ import annotations

import json
import logging
import math
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from raft_stereo_tpu.serving.fleet.router import (FleetRouter,
                                                  NoReplicasAvailable,
                                                  SessionLost,
                                                  XlUnavailable)
from raft_stereo_tpu.serving.http import MAX_BODY_BYTES, _stream_session_id
from raft_stereo_tpu.telemetry.http import (handle_debug_get,
                                            handle_debug_post)

log = logging.getLogger(__name__)


def retry_after_jittered(base_s: float = 1.0) -> Tuple[float, str]:
    """A jittered retry hint for the router's 503s: ``(retry_after_s,
    header_value)``.  The body carries the precise float in
    [0.5*base, 1.5*base]; the Retry-After header (integer seconds per
    RFC 9110) rounds UP so header-only clients never retry early.  The
    spread exists so N clients that all hit the same no-capacity window
    do not re-arrive in lockstep and recreate it (the r13 typed-overload
    contract, plus thundering-herd dispersion)."""
    retry_s = round(random.uniform(0.5 * base_s, 1.5 * base_s), 2)
    return retry_s, str(max(1, math.ceil(retry_s)))


def make_router_handler(router: FleetRouter):
    """Handler class closed over the router (instantiated per request by
    the server, like serving/http.py's)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Under the pooled server a keep-alive connection occupies one
        # worker until it closes; an idle read past this bound drops the
        # connection (handle_one_request treats the socket timeout as
        # close_connection) so parked clients cannot starve the pool.
        timeout = 30.0

        def log_message(self, fmt, *args):
            log.debug("%s " + fmt, self.client_address[0], *args)

        # ------------------------------------------------------- responses
        def _reply(self, code: int, body: bytes, content_type: str,
                   extra_headers=()):
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in extra_headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, code: int, obj, extra_headers=()):
            self._reply(code, (json.dumps(obj) + "\n").encode(),
                        "application/json", extra_headers)

        def _reply_forwarded(self, status: int,
                             headers: List[Tuple[str, str]],
                             body: bytes):
            """Relay a replica response verbatim: the replica's own
            header set (hop-by-hop stripped by Replica.forward) plus a
            recomputed Content-Length — no router fingerprints on the
            pass-through path."""
            self.send_response(status)
            have_length = False
            for k, v in headers:
                if k.lower() == "content-length":
                    have_length = True
                self.send_header(k, v)
            if not have_length:
                self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        # ---------------------------------------------------------- routes
        def _forward(self, method: str, body: Optional[bytes]):
            url = urlparse(self.path)
            path_qs = url.path + (f"?{url.query}" if url.query else "")
            headers = list(self.headers.items())
            session_id = _stream_session_id(url.path, self.headers)
            if session_id == "":
                self._reply_json(400, {
                    "error": "stream requests need a session "
                             "id: /v1/stream/<id> or "
                             "X-Session-Id"})
                return
            # Sampling decision for the whole routed request; at the
            # default rate 0 this is None in constant time and nothing
            # below adds a span or touches a header.
            trace = router.tracer.start_trace(
                "route.request", method=method, path=url.path,
                **({"session": session_id} if session_id else {}))
            trace_hdrs = ([("X-Trace-Id", trace.trace_id)]
                          if trace is not None else [])
            t0 = time.perf_counter()
            status_out: Optional[int] = None
            try:
                try:
                    if session_id is not None:
                        status, h, payload = router.forward_session(
                            session_id, method, path_qs, body, headers,
                            trace=trace)
                    else:
                        status, h, payload = router.forward_stateless(
                            method, path_qs, body, headers, trace=trace)
                except SessionLost as e:
                    status_out = 410
                    self._reply_json(410, {
                        "error": "session_lost",
                        "session_id": e.session_id,
                        "replica": e.replica,
                        "detail": str(e)},
                        extra_headers=trace_hdrs)
                    return
                except XlUnavailable as e:
                    status_out = 503
                    retry_s, header = retry_after_jittered()
                    self._reply_json(
                        503, {"error": "xl_unavailable",
                              "capable_replicas": e.capable_ready,
                              "capable_total": e.capable_total,
                              "retry_after_s": retry_s,
                              "detail": str(e)},
                        extra_headers=[("Retry-After", header)]
                        + trace_hdrs)
                    return
                except NoReplicasAvailable as e:
                    # The r13 typed-overload contract at fleet level:
                    # the machine-readable body plus a JITTERED
                    # Retry-After so synchronized clients do not retry
                    # in lockstep.
                    status_out = 503
                    retry_s, header = retry_after_jittered()
                    self._reply_json(
                        503, {"error": "no_replicas_ready",
                              "retry_after_s": retry_s,
                              "detail": str(e)},
                        extra_headers=[("Retry-After", header)]
                        + trace_hdrs)
                    return
                status_out = status
                respond_t0 = time.perf_counter()
                if trace is not None and not any(
                        k.lower() == "x-trace-id" for k, _v in h):
                    # Surface the id to the client; the replica usually
                    # already stamped the same one (it adopted our
                    # context), in which case its header relays as-is.
                    h = list(h) + [("X-Trace-Id", trace.trace_id)]
                self._reply_forwarded(status, h, payload)
                router.tracer.add_span("route.respond", trace,
                                       respond_t0, time.perf_counter(),
                                       status=status)
            finally:
                router.note_latency((time.perf_counter() - t0) * 1e3)
                if trace is not None:
                    if trace.root is not None and status_out is not None:
                        trace.root.set_attr("status", status_out)
                    router.tracer.finish_trace(trace)

        def do_GET(self):
            url = urlparse(self.path)
            path = url.path
            if path == "/metrics":
                self._reply(200, router.registry.render_text().encode(),
                            "text/plain; version=0.0.4")
            elif path == "/metrics/fleet":
                text = router.federator.render(
                    own_text=router.registry.render_text())
                self._reply(200, text.encode(),
                            "text/plain; version=0.0.4")
            elif path == "/debug/spans" and parse_qs(url.query).get(
                    "trace", [None])[0]:
                trace_id = parse_qs(url.query)["trace"][0]
                self._reply_json(200, router.federated_trace(trace_id))
            elif handle_debug_get(path, url.query, router.tracer,
                                  router.recorder, router.registry,
                                  self._reply, self._reply_json):
                pass
            elif path == "/healthz":
                status = router.fleet_status()
                self._reply_json(200, {
                    "status": "ok",
                    "role": status["role"],
                    "epoch": status["epoch"],
                    "ready_replicas": status["ready"],
                    "total_replicas": status["total"],
                    "in_rotation": status["in_rotation"],
                    "brownout_level": status["brownout_level"],
                    "sessions_routed": status["sessions_routed"],
                    "sessions_pending_handoff":
                        status["sessions_pending_handoff"]})
            elif path == "/readyz":
                status = router.fleet_status()
                ready = status["ready"] > 0
                self._reply_json(200 if ready else 503, {
                    "status": "ready" if ready else "no_replicas",
                    "ready": ready,
                    "ready_replicas": status["ready"],
                    "total_replicas": status["total"]})
            elif path == "/fleet":
                self._reply_json(200, router.fleet_status())
            elif path == "/admin/rollout":
                self._reply_json(200, router.rollout.status())
            else:
                self._reply_json(404, {"error": f"no route {path!r}"})

        def _handle_rollout_post(self):
            """``POST /admin/rollout`` — arm/disarm the canary split
            (fleet/rollout.py): ``{"action": "set", "model":
            "name@version", "fraction": 0.05, "shadow_fraction": 0.0}``
            arms (re-arming clears a previous demotion — an operator
            decision, never automatic); ``{"action": "clear"}``
            disarms.  200 with the policy status either way."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length)) if length \
                    else {}
                action = body["action"]
                if action == "set":
                    out = router.rollout.set_canary(
                        str(body["model"]),
                        float(body["fraction"]),
                        shadow_fraction=float(
                            body.get("shadow_fraction", 0.0)))
                elif action == "clear":
                    out = router.rollout.clear_canary()
                else:
                    raise ValueError(f"unknown action {action!r}")
            except (ValueError, KeyError, TypeError) as e:
                self._reply_json(400, {
                    "error": 'need a JSON body {"action": "set"|"clear",'
                             ' ...}',
                    "detail": str(e)})
                return
            self._reply_json(200, {"status": "ok", "rollout": out})

        def do_POST(self):
            url = urlparse(self.path)
            if url.path == "/admin/rollout":
                self._handle_rollout_post()
                return
            if handle_debug_post(url.path, router.recorder,
                                 self._reply_json):
                return
            if (url.path != "/v1/disparity"
                    and _stream_session_id(url.path, self.headers)
                    is None):
                self._reply_json(404, {"error": f"no route {url.path!r}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                if not 0 < length <= MAX_BODY_BYTES:
                    raise ValueError(
                        f"Content-Length {length} out of range")
                body = self.rfile.read(length)
            except (ValueError, OSError) as e:
                self._reply_json(400, {"error": str(e)})
                return
            self._forward("POST", body)

        def do_DELETE(self):
            if _stream_session_id(urlparse(self.path).path,
                                  self.headers) is None:
                self._reply_json(404,
                                 {"error": f"no route {self.path!r}"})
                return
            self._forward("DELETE", None)

    return Handler


class _PooledHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer spawns one OS thread PER CONNECTION — at 10k
    concurrent sessions that is 10k stacks (~80 GB of virtual address
    space and a scheduler meltdown before the router does any work).
    This variant services connections from a bounded ThreadPoolExecutor:
    accepts queue in the kernel backlog (``request_queue_size``), at
    most ``max_workers`` requests execute concurrently, and an idle
    keep-alive is reaped by the handler timeout so a parked client
    releases its worker.  bench_fleet.py is the receipt: the 5k/10k
    session legs run against exactly this server."""

    request_queue_size = 1024
    daemon_threads = True

    def __init__(self, addr, handler, max_workers: int = 128):
        super().__init__(addr, handler)
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="fleet-http")

    def process_request(self, request, client_address):
        # ThreadingMixIn's per-connection Thread(), routed through the
        # bounded pool instead; process_request_thread still owns
        # finish_request + shutdown_request error handling.
        self._pool.submit(self.process_request_thread, request,
                          client_address)

    def server_close(self):
        super().server_close()
        self._pool.shutdown(wait=False)


class RouterHTTPServer:
    """Owns the router's HTTP server (bounded-pool variant); same
    lifecycle surface as serving/http.StereoHTTPServer (``port=0`` for
    tests, ``start`` for a daemon thread, ``serve_forever`` for the
    CLI)."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 8550, max_workers: int = 128):
        self.router = router
        self.server = _PooledHTTPServer((host, port),
                                        make_router_handler(router),
                                        max_workers=max_workers)
        self._thread = None

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def serve_forever(self):
        self.server.serve_forever()

    def start(self) -> "RouterHTTPServer":
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="fleet-http")
        self._thread.start()
        return self

    def shutdown(self):
        self.server.shutdown()
        self.server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
