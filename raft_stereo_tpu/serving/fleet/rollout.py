"""Canary/shadow rollout policy — the router-level half of multi-model
serving (round 21; serving/models.py is the replica-level half).

A new model version never meets live traffic all at once.  The operator
registers it on the replicas (``POST /admin/models``), then arms a
**canary** here: a configurable fraction of STATELESS requests is routed
to the canary version by tagging them ``X-Model`` before forwarding —
the replicas' registry does the actual weight selection, the router only
decides WHICH requests carry the tag.  Two invariants the split keeps:

* **Deterministic assignment.**  The canary decision is a pure hash of
  the request body (salted SHA-256 against a threshold), not a coin
  flip: the same request replays onto the same arm, a router restart
  (or the HA standby) makes identical decisions, and tests can pin the
  split exactly.
* **Sessions never split.**  Only stateless ``/v1/disparity`` traffic
  participates.  A streaming session pins the model its first frame
  resolved (serving/sessions.py) and the router's sticky path never
  consults this policy — no stream ever receives frames from two
  versions (the acceptance invariant).

**Shadow mirroring** is the read-only sibling: a sampled fraction of
baseline requests is ALSO forwarded to the canary version
fire-and-forget — the shadow answer is compared against the primary's
(mean end-point-error between the two disparity maps), recorded into
the regression window, and dropped, never returned to the client.
Shadow EPE is the strongest regression signal: it measures the canary
against the incumbent on identical live inputs.  When both arms serve
with confidence telemetry (round 24, ``--confidence``) the compare also
diffs the two answers' ``X-Confidence`` headers — a canary that matches
the incumbent's disparity but is systematically LESS SURE of it is an
early regression the EPE diff cannot see.

**Auto-demotion** closes the loop with the brownout hysteresis shape
(serving/resilience.py): a regression signal — canary transport/HTTP
error rate or mean shadow EPE divergence over the rolling window —
sustained for ``demote_after_s`` drops the canary fraction to ZERO,
emits the typed ``canary_demoted`` event, and bumps
``fleet_canary_demotions_total``.  Demotion is one-way: re-arming is an
operator decision (``POST /admin/rollout``), never automatic, so a
flapping canary cannot oscillate back into traffic.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from raft_stereo_tpu.serving.models import parse_model_spec
from raft_stereo_tpu.telemetry.registry import MetricsRegistry

log = logging.getLogger(__name__)


def _hash_fraction(salt: bytes, key: bytes) -> float:
    """Deterministic uniform draw in [0, 1): the salted SHA-256 of the
    request key, top 8 bytes.  Pure — same (salt, key) always lands on
    the same side of any threshold."""
    digest = hashlib.sha256(salt + key).digest()
    return int.from_bytes(digest[:8], "big") / 2.0 ** 64


@dataclasses.dataclass(frozen=True)
class RolloutConfig:
    """Regression/demotion knobs (cli/route.py maps flags here)."""

    # Rolling evidence window (shadow compares + canary outcomes each
    # keep this many recent samples).
    window: int = 64
    # Samples required before the regression verdict may fire at all —
    # one unlucky compare must never demote.
    min_samples: int = 8
    # Mean shadow EPE divergence (px) between canary and primary
    # answers on identical inputs above which the canary is regressing.
    epe_threshold: float = 1.0
    # Canary error-rate (transport + HTTP >= 500) above which the
    # canary is regressing even without shadow evidence.
    error_threshold: float = 0.5
    # Mean confidence DROP (primary minus canary, from the replicas'
    # X-Confidence headers on identical inputs) above which the canary
    # is regressing — the round-24 quality signal: a canary that
    # answers with the same EPE but systematically less confidence is
    # drifting toward the failure the drift watchdog pages on.  Only
    # fed when BOTH arms serve with confidence telemetry; same
    # window/min_samples/dwell hysteresis as the EPE verdict.
    confidence_threshold: float = 0.2
    # The hysteresis dwell: the regression verdict must hold
    # continuously this long before demotion fires (brownout pattern —
    # a single bad window never flips the fleet).
    demote_after_s: float = 2.0

    def __post_init__(self):
        if not 0 < self.window <= 65536:
            raise ValueError(f"window={self.window} out of range")
        if self.min_samples < 1:
            raise ValueError(f"min_samples={self.min_samples} must be >= 1")
        if self.epe_threshold <= 0:
            raise ValueError(
                f"epe_threshold={self.epe_threshold} must be > 0")
        if not 0 < self.error_threshold <= 1:
            raise ValueError(
                f"error_threshold={self.error_threshold} not in (0, 1]")
        if not 0 < self.confidence_threshold <= 1:
            raise ValueError(
                f"confidence_threshold={self.confidence_threshold} "
                f"not in (0, 1]")
        if self.demote_after_s < 0:
            raise ValueError(
                f"demote_after_s={self.demote_after_s} must be >= 0")


class RolloutPolicy:
    """One canary arm at a time, with deterministic traffic splitting,
    shadow-compare bookkeeping, and hysteresis auto-demotion.  All state
    under one lock; every decision method is cheap and pure given the
    armed state (the I/O — forwarding, mirroring — is the router's)."""

    _CANARY_SALT = b"raft-canary:"
    _SHADOW_SALT = b"raft-shadow:"

    def __init__(self, cfg: RolloutConfig = RolloutConfig(),
                 registry: Optional[MetricsRegistry] = None,
                 clock=time.monotonic):
        self.cfg = cfg
        self._clock = clock
        self._lock = threading.Lock()
        # Armed canary: (name, version) + fractions; None = no rollout.
        self._model: Optional[Tuple[str, str]] = None
        self._fraction = 0.0
        self._shadow_fraction = 0.0
        self._demoted = False
        self._demoted_reason: Optional[str] = None
        self._bad_since: Optional[float] = None
        # Rolling evidence.
        self._epe_window: Deque[float] = deque(maxlen=cfg.window)
        self._outcome_window: Deque[bool] = deque(maxlen=cfg.window)
        self._conf_window: Deque[float] = deque(maxlen=cfg.window)
        self._transitions = []
        r = registry or MetricsRegistry()
        self.registry = r
        self.canary_requests = r.counter(
            "fleet_canary_requests_total",
            "stateless requests the rollout policy split onto the "
            "canary model version")
        self.shadow_requests = r.counter(
            "fleet_shadow_requests_total",
            "baseline requests mirrored fire-and-forget to the canary "
            "version (answers compared and dropped, never returned)")
        self.shadow_compares = r.counter(
            "fleet_shadow_compares_total",
            "shadow answers successfully compared against their "
            "primary (mean-EPE divergence recorded)")
        self.demotions = r.counter(
            "fleet_canary_demotions_total",
            "canary arms auto-demoted to 0% after a sustained "
            "regression verdict (typed canary_demoted event)")
        self.fraction_gauge = r.gauge(
            "fleet_canary_fraction",
            "current canary traffic fraction (0 when disarmed or "
            "demoted)")
        self.shadow_epe_gauge = r.gauge(
            "fleet_shadow_epe_mean",
            "mean |EPE| divergence between canary and primary answers "
            "over the rolling shadow-compare window")
        self.shadow_confidence_gauge = r.gauge(
            "fleet_shadow_confidence_delta_mean",
            "mean confidence drop (primary minus canary, X-Confidence "
            "headers) over the rolling shadow-compare window")

    # ------------------------------------------------------------- arming
    def set_canary(self, spec: str, fraction: float,
                   shadow_fraction: float = 0.0) -> Dict[str, object]:
        """Arm (or re-arm) the canary: ``spec`` is ``name@version`` —
        the version is REQUIRED here; an operator rolling out "whatever
        latest resolves to" would make the demotion record ambiguous.
        Re-arming clears a previous demotion and its evidence windows
        (the operator looked; the new arm starts clean)."""
        name, version = parse_model_spec(spec)
        if version is None:
            raise ValueError(
                f"canary spec {spec!r} needs an explicit version "
                f"(name@version): demotion records must name the exact "
                f"weights they demoted")
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction={fraction} not in [0, 1]")
        if not 0.0 <= shadow_fraction <= 1.0:
            raise ValueError(
                f"shadow_fraction={shadow_fraction} not in [0, 1]")
        with self._lock:
            self._model = (name, version)
            self._fraction = float(fraction)
            self._shadow_fraction = float(shadow_fraction)
            self._demoted = False
            self._demoted_reason = None
            self._bad_since = None
            self._epe_window.clear()
            self._outcome_window.clear()
            self._conf_window.clear()
            self._note_event_locked("canary_armed", fraction=fraction,
                                    shadow_fraction=shadow_fraction)
            self.fraction_gauge.set(fraction)
        log.info("canary armed: %s@%s at %.1f%% traffic (%.1f%% shadow)",
                 name, version, fraction * 100, shadow_fraction * 100)
        return self.status()

    def clear_canary(self) -> Dict[str, object]:
        """Disarm: no traffic splits, no mirroring, windows dropped."""
        with self._lock:
            self._model = None
            self._fraction = self._shadow_fraction = 0.0
            self._demoted = False
            self._demoted_reason = None
            self._bad_since = None
            self._epe_window.clear()
            self._outcome_window.clear()
            self._conf_window.clear()
            self._note_event_locked("canary_cleared")
            self.fraction_gauge.set(0)
        return self.status()

    def _note_event_locked(self, event: str, **fields) -> None:
        entry = {"t": self._clock(), "event": event}
        if self._model is not None:
            entry["model"] = f"{self._model[0]}@{self._model[1]}"
        entry.update(fields)
        self._transitions.append(entry)
        if len(self._transitions) > 50:
            self._transitions = self._transitions[-50:]

    # ---------------------------------------------------------- decisions
    @property
    def active(self) -> bool:
        with self._lock:
            return (self._model is not None and not self._demoted
                    and (self._fraction > 0 or self._shadow_fraction > 0))

    def canary_model(self) -> Optional[Tuple[str, str]]:
        with self._lock:
            return self._model

    def assign(self, request_key: bytes) -> Optional[str]:
        """The split decision for one stateless request that named NO
        model itself: the canary model NAME to tag it with (the replica
        registry resolves the weights), or None for the baseline arm.
        Deterministic in ``request_key`` (the request body)."""
        with self._lock:
            if (self._model is None or self._demoted
                    or self._fraction <= 0):
                return None
            if _hash_fraction(self._CANARY_SALT,
                              request_key) >= self._fraction:
                return None
            self.canary_requests.inc()
            return self._model[0]

    def wants_shadow(self, request_key: bytes) -> bool:
        """Whether this BASELINE request should also be mirrored to the
        canary (fire-and-forget).  Independent salt from ``assign`` so
        the shadow sample is uncorrelated with the canary split."""
        with self._lock:
            if (self._model is None or self._demoted
                    or self._shadow_fraction <= 0):
                return False
            if _hash_fraction(self._SHADOW_SALT,
                              request_key) >= self._shadow_fraction:
                return False
            self.shadow_requests.inc()
            return True

    # ----------------------------------------------------------- evidence
    def note_canary_result(self, ok: bool) -> None:
        """One canary-arm request finished: ``ok`` is transport success
        AND status < 500 (4xx is the CLIENT's fault on either arm)."""
        with self._lock:
            self._outcome_window.append(bool(ok))
        self.poll()

    def note_shadow_epe(self, epe: float) -> None:
        """One shadow pair compared: ``epe`` is the mean end-point-error
        divergence (px) between the canary and primary disparity maps
        on the SAME input."""
        with self._lock:
            self._epe_window.append(float(epe))
            self.shadow_compares.inc()
            vals = list(self._epe_window)
            self.shadow_epe_gauge.set(sum(vals) / len(vals))
        self.poll()

    def note_shadow_confidence(self, delta: float) -> None:
        """One shadow pair's confidence compared: ``delta`` is the
        primary's mean confidence minus the canary's on the SAME input
        (positive = the canary is LESS sure of its answer).  Fed by the
        router only when both arms answered with ``X-Confidence``."""
        with self._lock:
            self._conf_window.append(float(delta))
            vals = list(self._conf_window)
            self.shadow_confidence_gauge.set(sum(vals) / len(vals))
        self.poll()

    def _regression_locked(self) -> Optional[str]:
        """The current regression verdict, or None: which signal says
        the canary is worse than the incumbent."""
        if len(self._epe_window) >= self.cfg.min_samples:
            mean_epe = sum(self._epe_window) / len(self._epe_window)
            if mean_epe > self.cfg.epe_threshold:
                return (f"shadow_epe mean {mean_epe:.3f}px > "
                        f"{self.cfg.epe_threshold}px over "
                        f"{len(self._epe_window)} compares")
        if len(self._outcome_window) >= self.cfg.min_samples:
            errs = sum(1 for ok in self._outcome_window if not ok)
            rate = errs / len(self._outcome_window)
            if rate > self.cfg.error_threshold:
                return (f"canary error rate {rate:.2f} > "
                        f"{self.cfg.error_threshold} over "
                        f"{len(self._outcome_window)} requests")
        if len(self._conf_window) >= self.cfg.min_samples:
            mean_drop = sum(self._conf_window) / len(self._conf_window)
            if mean_drop > self.cfg.confidence_threshold:
                return (f"shadow confidence drop {mean_drop:.3f} > "
                        f"{self.cfg.confidence_threshold} over "
                        f"{len(self._conf_window)} compares")
        return None

    def poll(self) -> bool:
        """One hysteresis evaluation (called after every evidence note
        and from the router's health loop).  Returns True when THIS call
        demoted the canary."""
        now = self._clock()
        with self._lock:
            if self._model is None or self._demoted:
                return False
            reason = self._regression_locked()
            if reason is None:
                self._bad_since = None
                return False
            if self._bad_since is None:
                self._bad_since = now
            if now - self._bad_since < self.cfg.demote_after_s:
                return False
            # Sustained regression: demote to 0%, one-way.
            self._demoted = True
            self._demoted_reason = reason
            self._fraction = 0.0
            self._shadow_fraction = 0.0
            self.demotions.inc()
            self.fraction_gauge.set(0)
            self._note_event_locked("canary_demoted", reason=reason)
            name, version = self._model
        log.warning("canary %s@%s DEMOTED to 0%%: %s", name, version,
                    reason)
        return True

    # ------------------------------------------------------------- status
    def status(self) -> Dict[str, object]:
        with self._lock:
            vals = list(self._epe_window)
            confs = list(self._conf_window)
            return {
                "model": (f"{self._model[0]}@{self._model[1]}"
                          if self._model else None),
                "fraction": self._fraction,
                "shadow_fraction": self._shadow_fraction,
                "demoted": self._demoted,
                "demoted_reason": self._demoted_reason,
                "canary_requests": self.canary_requests.value,
                "shadow_requests": self.shadow_requests.value,
                "shadow_compares": self.shadow_compares.value,
                "shadow_epe_mean": (round(sum(vals) / len(vals), 4)
                                    if vals else None),
                "shadow_confidence_delta_mean": (
                    round(sum(confs) / len(confs), 4) if confs
                    else None),
                "canary_errors": sum(
                    1 for ok in self._outcome_window if not ok),
                "demotions": self.demotions.value,
                "transitions": list(self._transitions[-20:]),
            }
