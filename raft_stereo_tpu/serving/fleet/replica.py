"""One fleet member as the router sees it: an HTTP client plus the
health/readiness state the routing decisions read.

The router never shares application state with a replica — the ONLY
coupling is the replica's public HTTP surface (serving/http.py):
``/healthz`` (liveness + load signals: queue depth/limit, inflight,
brownout level), ``/readyz`` (the warm-ladder gate), the ``/v1/*``
request routes forwarded verbatim, and ``POST /admin/brownout`` (the
fleet-wide degradation floor).  That keeps a replica process free to
crash, restart, or be replaced by anything that speaks the same
protocol.

Transport failures (connection refused, reset, timeout, a blackholed
health check that never answers) raise ``ReplicaUnreachable``; the
router converts those into failover decisions.  HTTP-level error
responses are NOT failures at this layer — a 429 or a typed 410 is a
replica ANSWERING, and the router forwards it byte-for-byte.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import quote, urlparse

# EWMA smoothing for probe round-trip latency: ~0.3 weights the last
# probe enough to track a degrading replica within a few health passes
# without one GC pause dominating the estimate.
_PROBE_EWMA_ALPHA = 0.3


class ReplicaUnreachable(ConnectionError):
    """Transport-level failure talking to one replica (refused / reset /
    timeout / torn response).  The router's failover trigger."""

    def __init__(self, name: str, detail: str):
        super().__init__(f"replica {name!r} unreachable: {detail}")
        self.name = name


@dataclasses.dataclass
class ReplicaHealth:
    """One successful health probe, parsed: what the routing decisions
    read.  ``ready`` is the /readyz verdict (warm ladder compiled, not
    draining); the load fields come from /healthz."""

    ready: bool
    draining: bool = False
    queue_depth: int = 0
    queue_limit: int = 0
    inflight: int = 0
    brownout_level: int = 0
    sessions_active: Optional[int] = None
    # XL topology (round 17 /healthz "xl" field): None when this replica
    # serves without the mesh tier — the router's xl-capability routing
    # (round 18) keys off this.
    xl: Optional[Dict] = None
    # Running totals the autoscaler differences into rates.
    admitted: int = 0
    deadline_missed: int = 0

    @property
    def xl_capable(self) -> bool:
        return self.xl is not None

    @property
    def queue_fraction(self) -> float:
        """Queue pressure in [0, 1] — the fleet brownout signal."""
        if self.queue_limit <= 0:
            return 0.0
        return min(1.0, self.queue_depth / self.queue_limit)

    @property
    def load(self) -> Tuple[int, int]:
        """Least-loaded-first sort key for stateless routing: queued
        work first (it is what a new request waits behind), then
        inflight."""
        return (self.queue_depth, self.inflight)


# Hop-by-hop headers never forwarded in either direction (RFC 9110
# §7.6.1) plus the ones the transport layer recomputes itself.
_HOP_HEADERS = frozenset({
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailer", "transfer-encoding",
    "upgrade", "host", "content-length"})


class Replica:
    """One backend engine process: name, base URL, an HTTP client, and
    the mutable routing state the FleetRouter maintains under its own
    lock (this class only guards its counters).

    ``alive``/``health`` are the router's last verdicts: ``alive=False``
    means the replica failed ``fail_after`` consecutive probes (or a
    forwarded request hit a transport error) and is out of rotation
    until a probe succeeds again.
    """

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        parsed = urlparse(self.url)
        if parsed.scheme not in ("http", ""):
            raise ValueError(f"replica {name!r}: only http:// URLs are "
                             f"supported, got {url!r}")
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or 80
        # Routing state, owned by the router (mutated under its lock).
        self.alive = True
        self.health: Optional[ReplicaHealth] = None
        self.consecutive_failures = 0
        # Stamped by the router whenever alive/ready flips — `/fleet`
        # surfaces it so a flapping replica is visible as a recent
        # timestamp, not hidden behind a binary up/down.
        self.last_state_change_ts: Optional[float] = None
        self._lock = threading.Lock()
        self.requests_forwarded = 0
        self.transport_errors = 0
        self.probe_latency_ms: Optional[float] = None

    def __repr__(self) -> str:
        return (f"Replica({self.name!r}, {self.url!r}, alive={self.alive}, "
                f"ready={self.ready})")

    @property
    def ready(self) -> bool:
        """Routable right now: alive and the last probe said ready."""
        return self.alive and self.health is not None and self.health.ready

    # ------------------------------------------------------------- transport
    def _request(self, method: str, path: str, body: Optional[bytes],
                 headers: Dict[str, str], timeout: float
                 ) -> Tuple[int, List[Tuple[str, str]], bytes]:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            return resp.status, resp.getheaders(), payload
        except (OSError, socket.timeout,
                http.client.HTTPException) as e:
            with self._lock:
                self.transport_errors += 1
            raise ReplicaUnreachable(
                self.name, f"{type(e).__name__}: {e}") from e
        finally:
            conn.close()

    def forward(self, method: str, path_qs: str, body: Optional[bytes],
                headers: Sequence[Tuple[str, str]], timeout: float
                ) -> Tuple[int, List[Tuple[str, str]], bytes]:
        """Forward one client request verbatim; returns ``(status,
        headers, body)`` with hop-by-hop headers stripped on both legs —
        everything else (the typed error bodies, the ``X-*`` provenance
        headers, ``Retry-After``) passes through untouched, which is
        what keeps the router pass-through-equivalent to hitting the
        replica directly (tests/test_fleet.py pins byte equality)."""
        fwd = {k: v for k, v in headers
               if k.lower() not in _HOP_HEADERS}
        status, resp_headers, payload = self._request(
            method, path_qs, body, fwd, timeout)
        with self._lock:
            self.requests_forwarded += 1
        kept = [(k, v) for k, v in resp_headers
                if k.lower() not in _HOP_HEADERS
                and k.lower() not in ("server", "date")]
        return status, kept, payload

    # ----------------------------------------------------------- health pokes
    def probe(self, timeout: float) -> ReplicaHealth:
        """One liveness + readiness probe; raises ``ReplicaUnreachable``
        on any transport failure (including a health-check blackhole —
        a replica that accepts the connection but never answers)."""
        t0 = time.monotonic()
        status_h, _, body_h = self._request("GET", "/healthz", None, {},
                                            timeout)
        if status_h != 200:
            raise ReplicaUnreachable(self.name,
                                     f"/healthz answered {status_h}")
        try:
            h = json.loads(body_h)
        except ValueError as e:
            raise ReplicaUnreachable(
                self.name, f"/healthz body unparseable: {e}") from e
        status_r, _, body_r = self._request("GET", "/readyz", None, {},
                                            timeout)
        rtt_ms = (time.monotonic() - t0) * 1e3
        with self._lock:
            if self.probe_latency_ms is None:
                self.probe_latency_ms = rtt_ms
            else:
                self.probe_latency_ms += _PROBE_EWMA_ALPHA * (
                    rtt_ms - self.probe_latency_ms)
        try:
            r = json.loads(body_r)
        except ValueError:
            r = {}
        return ReplicaHealth(
            ready=(status_r == 200 and bool(r.get("ready", False))
                   and h.get("status") != "draining"),
            draining=h.get("status") == "draining",
            queue_depth=int(h.get("queue_depth") or 0),
            queue_limit=int(h.get("queue_limit") or 0),
            inflight=int(h.get("inflight") or 0),
            brownout_level=int(h.get("brownout_level") or 0),
            sessions_active=h.get("sessions_active"),
            xl=h.get("xl") or None,
            admitted=int(h.get("admitted") or 0),
            deadline_missed=int(h.get("deadline_missed") or 0))

    def get_handoff(self, timeout: float) -> Optional[Dict]:
        """The draining replica's session-handoff manifest (``GET
        /admin/handoff``): the artifact key + session ids the router
        remaps to survivors.  None while the replica has not published
        yet (404 — poll again next pass); raises ``ReplicaUnreachable``
        on transport failure (the replica may already be gone — the
        death path takes over)."""
        status, _, body = self._request("GET", "/admin/handoff", None,
                                        {}, timeout)
        if status != 200:
            return None
        try:
            return json.loads(body)
        except ValueError as e:
            raise ReplicaUnreachable(
                self.name, f"/admin/handoff body unparseable: {e}") from e

    def post_brownout(self, level: int, timeout: float) -> bool:
        """Push the fleet brownout floor; True when the replica applied
        it (False: replica runs without a brownout controller — typed
        409 — or answered any other non-200)."""
        body = json.dumps({"level": int(level)}).encode()
        status, _, _ = self._request(
            "POST", "/admin/brownout", body,
            {"Content-Type": "application/json"}, timeout)
        return status == 200

    # -------------------------------------------------- observability fetches
    def get_metrics(self, timeout: float) -> str:
        """This replica's ``GET /metrics`` Prometheus text, verbatim —
        the federation poller's scrape unit."""
        status, _, body = self._request("GET", "/metrics", None, {},
                                        timeout)
        if status != 200:
            raise ReplicaUnreachable(self.name,
                                     f"/metrics answered {status}")
        return body.decode("utf-8", errors="replace")

    def get_spans(self, trace_id: str, timeout: float) -> List[Dict]:
        """One trace's span records from this replica's ring
        (``GET /debug/spans?trace=<id>``) — the federated trace view's
        per-replica half.  Empty list when the replica has no spans for
        that id (or span tracing is off: typed 404)."""
        status, _, body = self._request(
            "GET", f"/debug/spans?trace={quote(trace_id)}", None, {},
            timeout)
        if status != 200:
            return []
        try:
            doc = json.loads(body)
        except ValueError:
            return []
        spans = doc.get("spans") if isinstance(doc, dict) else None
        return spans if isinstance(spans, list) else []

    def post_flightrecorder(self, timeout: float) -> Optional[Dict]:
        """Force a flight-recorder bundle dump on this replica (``POST
        /debug/flightrecorder``) — the coordinated fleet dump's fan-out
        leg.  Returns the replica's bundle record, or None when the
        replica runs without a recorder (typed 404)."""
        status, _, body = self._request("POST", "/debug/flightrecorder",
                                        None, {}, timeout)
        if status != 200:
            return None
        try:
            doc = json.loads(body)
        except ValueError:
            return None
        return doc if isinstance(doc, dict) else None

    def stats(self) -> Dict[str, object]:
        with self._lock:
            forwarded = self.requests_forwarded
            errors = self.transport_errors
            probe_ms = self.probe_latency_ms
        h = self.health
        return {
            "name": self.name, "url": self.url, "alive": self.alive,
            "ready": self.ready,
            "consecutive_failures": self.consecutive_failures,
            "probe_latency_ms": (round(probe_ms, 3)
                                 if probe_ms is not None else None),
            "last_state_change_ts": self.last_state_change_ts,
            "requests_forwarded": forwarded,
            "transport_errors": errors,
            "queue_depth": h.queue_depth if h else None,
            "brownout_level": h.brownout_level if h else None,
            "sessions_active": h.sessions_active if h else None,
        }
