"""Replicated router state: a fenced lease plus an append-only ledger,
both living in the shared artifact store (``<store>/fleet/``).

The r16 router kept two pieces of state that die with its process: the
lost-session set (ids owed exactly one typed 410) and — new this round —
the handoff map (ids whose warm state a draining replica published).
Losing either across a router failover breaks a client-visible contract:
a forgotten loss silently un-types a broken stream, a re-armed one fires
the 410 twice, and a forgotten handoff cold-starts a stream whose warm
state is sitting in the store.  This module replicates exactly that
state between a primary/standby ``raft-route`` pair:

* **Lease** (``lease.json``) — ``{"epoch": E, "owner": name, "t":
  wall}``, rewritten atomically.  The ACTIVE router renews ``t`` on its
  health-loop cadence; the standby watches staleness (and optionally
  probes the primary's URL) and takes over by bumping the epoch.  The
  epoch is the FENCE: every ledger append re-reads the lease and a
  writer holding a stale epoch is rejected — a partitioned ex-primary
  can keep serving reads, but it can never corrupt the replicated
  session-loss record (tests/test_fleet.py pins the rejection).
* **Ledger** (``ledger.jsonl``) — append-only JSON lines, each carrying
  the writer's epoch and a per-writer sequence number.  Record kinds:
  ``lost`` (a replica death tombstoned these sids — the 410s are OWED),
  ``fired`` (one sid's 410 was actually delivered), ``handoff`` (these
  sids' warm state lives at this artifact key).  The standby replays on
  takeover: owed losses minus fired ones re-arm (a client that never
  got its 410 still gets exactly one), fired ones stay fired (never a
  second 410 for one id), handoffs re-arm the warm remap.  Torn tails
  and corrupt lines are skipped — the ledger is an at-least-once
  record, and the in-memory tables it rebuilds are TTL/capacity-bounded
  anyway (``fleet_lost_ledger_size``).

File-level simplicity is deliberate: one writer at a time (the lease
holder), atomic lease replacement, O_APPEND line writes, and replay that
tolerates anything.  The same directory works over the NFS/object-store
mounts the artifact store already assumes.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

LEASE_FILE = "lease.json"
LEDGER_FILE = "ledger.jsonl"


class FleetLedger:
    """Fenced append-only ledger + lease for one router pair.

    ``owner`` names this writer (appears in the lease and every record).
    ``clock`` is wall time (the lease must compare across processes).
    Thread-safe; the router's health loop calls ``renew``/``is_stale``
    every poll and the request path calls ``append``.
    """

    def __init__(self, ledger_dir: str, owner: str, clock=time.time):
        self.dir = os.path.abspath(os.path.expanduser(ledger_dir))
        os.makedirs(self.dir, exist_ok=True)
        self.owner = owner
        self._clock = clock
        self._lock = threading.Lock()
        self.epoch: Optional[int] = None     # held epoch; None = not active
        self._seq = 0
        self.rejected_appends = 0

    # ----------------------------------------------------------------- lease
    @property
    def _lease_path(self) -> str:
        return os.path.join(self.dir, LEASE_FILE)

    @property
    def _ledger_path(self) -> str:
        return os.path.join(self.dir, LEDGER_FILE)

    def read_lease(self) -> Optional[Dict[str, object]]:
        try:
            with open(self._lease_path) as f:
                lease = json.load(f)
            return {"epoch": int(lease["epoch"]),
                    "owner": str(lease["owner"]),
                    "t": float(lease["t"])}
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _write_lease(self, epoch: int) -> None:
        tmp = f"{self._lease_path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "owner": self.owner,
                       "t": self._clock()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._lease_path)

    def acquire(self) -> int:
        """Take (or take OVER) the lease: epoch becomes max(existing)+1,
        which fences every append the previous holder might still
        attempt.  Returns the new epoch."""
        with self._lock:
            lease = self.read_lease()
            epoch = (lease["epoch"] if lease else 0) + 1
            self._write_lease(epoch)
            self.epoch = epoch
            log.info("ledger lease acquired by %s at epoch %d "
                     "(previous: %s)", self.owner, epoch,
                     lease and f"{lease['owner']}@{lease['epoch']}")
            return epoch

    def renew(self) -> bool:
        """Refresh the lease timestamp; False (and the held epoch drops)
        when someone else took the lease — this writer is fenced out and
        must stop appending."""
        with self._lock:
            if self.epoch is None:
                return False
            lease = self.read_lease()
            if lease is None or lease["epoch"] != self.epoch \
                    or lease["owner"] != self.owner:
                log.warning("ledger lease lost by %s (now %s); fenced",
                            self.owner,
                            lease and f"{lease['owner']}@{lease['epoch']}")
                self.epoch = None
                return False
            self._write_lease(self.epoch)
            return True

    def is_stale(self, ttl_s: float) -> bool:
        """Whether the CURRENT lease (whoever holds it) has not been
        renewed within ``ttl_s`` — the standby's takeover trigger.  An
        absent/unreadable lease counts as stale (nobody is active)."""
        lease = self.read_lease()
        if lease is None:
            return True
        if lease["owner"] == self.owner:
            return False
        return self._clock() - lease["t"] > ttl_s

    @property
    def active(self) -> bool:
        return self.epoch is not None

    # ---------------------------------------------------------------- ledger
    def append(self, kind: str, **fields) -> bool:
        """Append one fenced record; False when this writer no longer
        holds the lease epoch (the record is NOT written — the fencing
        contract a stale router's append must hit)."""
        with self._lock:
            if self.epoch is None:
                self.rejected_appends += 1
                return False
            lease = self.read_lease()
            if lease is None or lease["epoch"] != self.epoch:
                self.rejected_appends += 1
                self.epoch = None
                log.warning("ledger append %r rejected: %s holds a "
                            "stale epoch (lease now %s)", kind,
                            self.owner,
                            lease and f"{lease['owner']}@{lease['epoch']}")
                return False
            self._seq += 1
            record = {"epoch": self.epoch, "seq": self._seq,
                      "owner": self.owner, "t": self._clock(),
                      "kind": kind, **fields}
            line = json.dumps(record, sort_keys=True, default=str) + "\n"
            try:
                with open(self._ledger_path, "a") as f:
                    f.write(line)
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:
                log.warning("ledger append failed", exc_info=True)
                return False
            return True

    def replay(self) -> List[Dict[str, object]]:
        """Every parseable ledger record in append order; torn tails and
        corrupt lines are skipped (the ledger can always be read)."""
        out: List[Dict[str, object]] = []
        try:
            with open(self._ledger_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue    # torn/corrupt line: skip, keep going
        except OSError:
            return out
        return out

    def compact(self, keep_after_t: float) -> int:
        """Rewrite the ledger keeping only records newer than
        ``keep_after_t`` (the active writer's housekeeping so weeks of
        session churn do not grow the file without bound).  Returns the
        number of records dropped; a fenced or failed compaction is a
        no-op."""
        with self._lock:
            if self.epoch is None:
                return 0
            records = self.replay()
            kept = [r for r in records
                    if float(r.get("t", 0)) >= keep_after_t]
            if len(kept) == len(records):
                return 0
            tmp = f"{self._ledger_path}.tmp-{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    for r in kept:
                        f.write(json.dumps(r, sort_keys=True,
                                           default=str) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self._ledger_path)
            except OSError:
                log.warning("ledger compaction failed", exc_info=True)
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return 0
            return len(records) - len(kept)
