"""The batch-N serving engine: compile cache, scheduler, and cost
telemetry in one place.

This replaced the round-6 split of ``StereoService`` + ``MicroBatcher`` +
per-worker ``InferenceRunner``.  That stack's best throughput was 1.015x
solo inference (BENCH_SERVE_r06.json): its default "chain" mode dispatched
the batch-1 program serially per request, its "stack" mode re-padded the
batch axis to the next power of two and lost more than it gained, and its
timed flush left the device idle while requests aged toward
``max_wait_ms``.  The engine fixes all three:

* **True batch-N bucket executables** — one compiled program per
  (padded shape, batch size) for the configured ``batch_sizes``
  (default 1/2/4/8), image buffers donated (``donate_argnums``), built by
  the same ``eval.runner.make_forward`` the solo runner uses — so the
  batch-1 bucket is **bitwise-equal** to solo inference by construction
  (the old chain mode survives as exactly that bucket).  Compiles route
  through the ``CompileRegistry`` AOT path when cost telemetry is on, and
  ``prewarm`` builds a shape's whole bucket ladder at boot.
* **Continuous batching** (`serving/batcher.py BucketQueue`) — no flush
  thread, no ``max_wait`` stall: an idle worker pops immediately and takes
  the largest compiled batch size the queue depth fills; a partial batch
  dispatches at the next size down (7 queued -> 4 + 2 + 1), never padded
  up.  Occupancy is set by queue pressure: below capacity every request
  dispatches the moment a worker frees (batch 1, minimum latency); at
  pressure the pops grab 4s and 8s.
* **Waste-driven bucket selection** (``BucketPolicy``) — the measured
  ``serve_padding_waste`` / ``serve_bucket_*_pixels_total`` accounting
  feeds back into the spatial padding policy: in adaptive mode shapes
  start at the coarsest pad grid (maximal executable reuse) and a bucket
  is refined toward the /32 floor once its observed waste fraction
  crosses ``max_padding_waste``.  The static /32 rule remains the default
  (the reference's padding semantics; parity tests require it).

Shutdown mirrors the train loop's preemption story
(training/train_loop.py): ``drain()`` refuses new work with the typed
``Overloaded``, lets the workers finish the queue, and only then stops
them.

Round 13 adds the failure story (docs/architecture.md §Resilience):

* **Supervised recovery** — a crashed dispatch no longer silently fails
  its whole batch: the requests requeue (ahead of fresh work, with
  exponential backoff) for bounded retries, the worker thread is
  restarted by the supervisor, and a request whose dispatch crashes
  ``max_dispatch_attempts`` times fails individually with the typed
  ``RequestPoisoned`` instead of retrying forever.  Every request
  admitted terminates — success or typed error, never silence.
* **Per-device circuit breakers** (serving/resilience.py) — K
  consecutive failures quarantine a device (its worker stops popping);
  after a cooldown one half-open probe batch decides whether it is back.
* **Brownout degradation** — sustained queue-saturation /
  deadline-miss pressure pushes eligible requests down the round-12
  tier ladder (quality -> balanced -> interactive) instead of shedding;
  hysteresis on restore.  Cheaper answers before no answers.
* **Fault injection** (serving/chaos.py, ``ServeConfig.chaos``) —
  deterministic seeded worker crashes / device OOM / latency / compile
  failures prove all of the above in scripts/chaos_smoke.py; off by
  default with the dispatch path bitwise-unchanged.
* **Persistent executable cache** (serving/persist.py,
  ``executable_cache_dir``) — compiled bucket executables serialize to
  disk keyed by (config, shape, batch, tier, backend fingerprint), so a
  restarted process prewarm is disk-bound, not compile-bound, and the
  ``ready`` gate (/readyz) opens in seconds.

Round 15 adds the int8 turbo tier and the per-session context cache
(docs/architecture.md §Quantization, §Streaming sessions):

* **Int8 tiers** — a tier with ``RequestTier.quant == "int8"`` (the
  "turbo" preset) compiles against the quantized variable tree
  (``_vars_for``: host-quantized once, device-put per worker; the fp32
  tree and every full-precision tier are untouched) with the int8
  correlation pyramid in its programs; its executables carry distinct
  compile-cost keys (``...,quant=int8``) and persistent-cache keys, join
  prewarm + /readyz, and sort to the BOTTOM of the brownout cost ladder.
* **Session ctx cache** (``session_ctx_cache``) — static-camera streams
  reuse the session's cnet context bundle: cold frames run the
  ``state_ctx`` family (also returns the bundle), coherent warm frames
  run ``warm_ctx`` (the context encoder never executes); invalidated by
  scene cuts, the keyframe guard, and any frame past the
  ``ctx_cache_threshold`` static-scene gate.

Round 16 makes the whole PROCESS a routine fault domain
(serving/fleet/, docs/architecture.md §Fleet): ``begin_shutdown`` is
the graceful-SIGTERM readiness flip the fleet router keys off,
``set_brownout_floor`` applies the router's fleet-wide degradation
level, the executable cache is a shareable content-addressed artifact
store with max-bytes GC (tools/compile_farm.py populates it once for
every replica), and a crashed dispatch carrying a SESSION frame demotes
its requeue to a cold start + invalidates the session's warm state
(``_invalidate_crashed_session_frame``) so no frame chains across a
crash gap.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_stereo_tpu import profiling
from raft_stereo_tpu.config import (RaftStereoConfig, RequestTier,
                                    parse_tier)
from raft_stereo_tpu.eval.runner import (early_exit_enabled,
                                         effective_inference_config,
                                         make_forward, make_forward_mesh)
from raft_stereo_tpu.models.raft_stereo import RAFTStereo
from raft_stereo_tpu.ops.padding import InputPadder
from raft_stereo_tpu.serving.batcher import (BucketQueue, Overloaded,
                                             Request, RequestPoisoned,
                                             decompose_batch)
from raft_stereo_tpu.serving.chaos import ChaosConfig, ChaosInjector
from raft_stereo_tpu.serving.metrics import MetricsRegistry, ServingMetrics
from raft_stereo_tpu.serving.models import (ModelStore, ModelUnknown,
                                            RegisteredModel, model_coord,
                                            parse_model_spec)
from raft_stereo_tpu.serving.resilience import (CIRCUIT_CLOSED,
                                                BrownoutController,
                                                CircuitBreaker,
                                                circuit_state_name,
                                                cost_ladder)
from raft_stereo_tpu.serving.sessions import (SessionsDisabled, SessionStore,
                                              frame_delta, frame_thumbnail,
                                              handoff_session_ids,
                                              parse_handoff_blob)

log = logging.getLogger(__name__)

# The model's divisibility constraint: every pad grid must be a multiple
# of this, and the adaptive policy can never refine below it.
MODEL_DIVIS = 32

# Executable families a (bucket, batch, tier) compiles under
# (eval/runner.make_forward): the base sessionless program, the
# state-returning program session cold frames run (same math, one extra
# low-res output), and the warm program that also consumes a flow_init.
# The *_CTX variants (round 15, ``ServeConfig.session_ctx_cache``) add
# the per-session CONTEXT cache: cold frames run "state_ctx" (also
# returns the context bundle) and coherent warm frames run "warm_ctx"
# (consumes the bundle and SKIPS the context encoder — cnet is the
# dominant per-frame encoder cost at streaming shapes).
FAMILY_BASE = None
FAMILY_STATE = "state"
FAMILY_WARM = "warm"
FAMILY_STATE_CTX = "state_ctx"
FAMILY_WARM_CTX = "warm_ctx"
# The warm-h families (round 19, ``ServeConfig.session_hidden``): the
# ``_h`` variants additionally RETURN the multi-level GRU hidden-state
# tree (cold frames) and CONSUME it as an extra traced input (warm
# frames) — eval/runner.make_forward ``hidden_init``/``return_hidden``.
# Same surface pattern as flow_init (r14) and ctx_init (r15): distinct
# executable families with their own compile-cost and persist keys.
FAMILY_STATE_H = "state_h"
FAMILY_WARM_H = "warm_h"
FAMILY_STATE_CTX_H = "state_ctx_h"
FAMILY_WARM_CTX_H = "warm_ctx_h"
# The xl family (round 17): a fixed-depth base-arity program SHARDED over
# a rows/corr device-group mesh (eval/runner.make_forward_mesh) — one
# full-resolution pair answered by several devices.  Only xl device-group
# workers pop these groups (BucketQueue.pop ``want`` filter); executables
# carry distinct ",mesh=rowsN" compile-cost and persist keys.
FAMILY_XL = "xl"

# Families that consume a flow_init input / reuse a context bundle.
_WARM_FAMILIES = (FAMILY_WARM, FAMILY_WARM_CTX, FAMILY_WARM_H,
                  FAMILY_WARM_CTX_H)
# Hidden-tree plumbing (round 19): _H_IN consume the previous frame's
# hidden tree as a traced input; _H_OUT return this frame's final tree.
_H_IN_FAMILIES = (FAMILY_WARM_H, FAMILY_WARM_CTX_H)
_H_OUT_FAMILIES = (FAMILY_STATE_H, FAMILY_WARM_H, FAMILY_STATE_CTX_H,
                   FAMILY_WARM_CTX_H)
# Context-bundle plumbing: _CTX_SAVE also return the bundle (cold ctx
# frames), _CTX_REUSE consume it and skip the context encoder.
_CTX_SAVE_FAMILIES = (FAMILY_STATE_CTX, FAMILY_STATE_CTX_H)
_CTX_REUSE_FAMILIES = (FAMILY_WARM_CTX, FAMILY_WARM_CTX_H)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving knobs (model architecture stays in RaftStereoConfig)."""

    max_batch: int = 8           # occupancy ceiling per device dispatch
    # Batch sizes compiled per shape bucket; capped at max_batch, must
    # include 1 (the solo-parity bucket).  The scheduler dispatches the
    # largest size the queue depth fills and decomposes remainders
    # (7 queued -> 4+2+1) — the batch axis never carries filler frames.
    batch_sizes: Tuple[int, ...] = (1, 2, 4, 8)
    # RETIRED (round 11): the engine's continuous batching dispatches the
    # moment a worker is free, so there is no timed flush to bound.  The
    # field is accepted for compatibility and ignored.
    max_wait_ms: float = 0.0
    max_queue: int = 64          # admission bound; beyond it -> Overloaded
    data_parallel: int = 1       # device workers (<= local device count)
    iters: int = 32              # GRU iterations per request (the depth
    #                              CAP for early-exit tiers)
    # Named latency tiers (config.py REQUEST_TIERS / inline
    # "name:threshold_px[:min_iters]" specs): each tier is an early-exit
    # knob setting the engine compiles a SEPARATE bucket-executable family
    # for, and requests select one by name (HTTP ?tier= / X-Tier).  A tier
    # whose threshold is <= 0 ("quality") runs the fixed-depth program and
    # shares the base executables — the bitwise-parity bucket.  Empty
    # (default): no tiers, exactly the pre-tier engine.
    tiers: Tuple[str, ...] = ()
    # Tier for requests that name none; None = "quality" when configured,
    # else the first tier.  Ignored without tiers.
    default_tier: Optional[str] = None
    shape_bucket: Optional[int] = None   # static coarser-than-/32 pad grid
    # Waste-driven spatial bucket selection: start shapes at the coarsest
    # grid in bucket_grids and refine a bucket toward the /32 floor once
    # its measured padding-waste fraction exceeds max_padding_waste.
    # Off by default: the static /32 rule is the reference's padding
    # semantics and the bitwise parity tests require it.
    adaptive_buckets: bool = False
    bucket_grids: Tuple[int, ...] = (128, 64, 32)
    max_padding_waste: float = 0.10
    # Raw (H, W) shapes whose bucket ladder (all batch sizes) is compiled
    # at boot — cold-start work moved out of the first requests' path.
    # Also the READINESS target: /readyz reports ready only once every
    # (worker, bucket, batch, tier-family) entry of this surface has
    # dispatched once.
    warmup_shapes: Tuple[Tuple[int, int], ...] = ()
    # False: declare the warm surface (readiness gates on it) but let the
    # caller drive ``prewarm`` itself — the CLI does this so the HTTP
    # server answers /readyz "warming" DURING the warm-up and so compile
    # events land in the run-event log wired after construction.
    prewarm_on_init: bool = True
    max_cached_shapes: int = 16  # per-worker (bucket, batch) executables
    fetch_dtype: Optional[str] = None    # "fp16" | "bf16" half fetch
    default_deadline_ms: Optional[float] = None  # per-request override wins
    # Donate the image buffers to every bucket executable (and declare the
    # same on the solo runner): the runtime may reclaim/alias them the
    # moment the program consumes them.  Numerics-neutral (tested).
    donate_buffers: bool = True
    # Fraction of requests whose span tree is recorded (telemetry/spans.py:
    # admission -> queue -> dispatch -> fetch -> respond, exported as
    # Chrome trace JSON via GET /debug/spans).  0.0 (default) disables
    # tracing entirely — every span site takes the constant-time None exit.
    trace_sample_rate: float = 0.0
    # Compile-cost telemetry (telemetry/costs.py): route every bucket
    # compile through the AOT path so GET /debug/compiles lists each
    # executable's flops/bytes/memory and the MFU gauges get their flops
    # numerator.  False (default) keeps the plain jax.jit dispatch.
    cost_telemetry: bool = False
    # MFU denominator override (TFLOP/s); None = the auto table keyed by
    # the local device kind (costs.DEVICE_PEAK_TFLOPS).
    device_peak_tflops: Optional[float] = None
    # ---- Resilience (round 13; docs/architecture.md §Resilience) -------
    # Deterministic fault injection (serving/chaos.py).  None (default):
    # chaos off, the dispatch path is a single attribute check away from
    # the round-12 program — bitwise-unchanged, tested.
    chaos: Optional[ChaosConfig] = None
    # Supervised recovery: a request whose dispatch crashes requeues
    # (ahead of fresh work) until it has been attempted this many times,
    # then fails with the typed RequestPoisoned.  1 = no retries.
    max_dispatch_attempts: int = 2
    # Backoff before a crashed batch's requests re-enter the queue:
    # retry_backoff_ms * 2^(attempt-1), so a flapping device is not
    # hammered by its own bounce-backs.
    retry_backoff_ms: float = 20.0
    # Per-device circuit breaker: this many CONSECUTIVE dispatch failures
    # quarantine the device; after breaker_cooldown_s one half-open probe
    # batch decides recovery (serving/resilience.py).
    breaker_failures: int = 3
    breaker_cooldown_s: float = 1.0
    # Brownout degradation: under sustained queue-saturation or
    # deadline-miss pressure, push eligible requests down the tier
    # ladder (cheapest tier = highest early-exit threshold) instead of
    # shedding; restore with hysteresis.  Requires tiers.
    brownout: bool = False
    brownout_engage_fraction: float = 0.75
    brownout_engage_s: float = 0.5
    brownout_restore_fraction: float = 0.25
    brownout_restore_s: float = 2.0
    brownout_poll_s: float = 0.1
    # Tiers that must NEVER be degraded (the per-tier opt-out; clients
    # additionally opt out per request via submit(degradable=False) /
    # the X-No-Degrade header).
    brownout_exempt_tiers: Tuple[str, ...] = ()
    # Persistent AOT executable cache directory (serving/persist.py):
    # compiled bucket executables serialize here keyed by (config, shape,
    # batch, tier, executable family — warm programs have a different
    # arity — and backend fingerprint) so a restarted process prewarm
    # loads from disk instead of recompiling.  None (default) = off.
    # The directory may be SHARED fleet-wide (an NFS mount / synced
    # object store tools/compile_farm.py populated): keys are pure
    # content hashes, so replicas coordinate for free.
    executable_cache_dir: Optional[str] = None
    # Store bound: beyond this many bytes the least-recently-USED
    # entries are evicted (atime LRU; config / jax-fingerprint churn
    # ages out instead of growing without bound).  None = unbounded.
    executable_cache_max_bytes: Optional[int] = None
    # Replica role against a SHARED store: fetch warm artifacts but
    # never write (a misconfigured replica cannot pollute the fleet's
    # cache; the compile farm is the only writer).
    executable_cache_read_only: bool = False
    # ---- Streaming sessions (round 14; serving/sessions.py) ------------
    # Stateful video serving: POST /v1/stream/<id> frames warm-start the
    # GRU from the session's previous low-res disparity, so with an
    # early-exit tier the convergence gate stalls after a fraction of the
    # cold iterations.  False (default): no session store, no warm
    # executable families — the engine is exactly the stateless round-13
    # build (bitwise-pinned by tests/test_sessions.py).
    sessions: bool = False
    # Idle seconds before a session's state expires (typed 410 on the
    # next frame; the client must open a fresh session).
    session_ttl_s: float = 30.0
    # Live-session ceiling; beyond it the least-recently-used session is
    # evicted (410 on its next frame).
    session_capacity: int = 256
    # Scene-cut fallback: a frame whose mean |Δintensity| vs the previous
    # frame's thumbnail exceeds this (0..255 units) cold-starts instead
    # of warm-starting from a disparity field the cut invalidated.
    # <= 0 disables the check (every in-session frame warm-starts).
    scene_cut_threshold: float = 40.0
    # Keyframe guard: a WARM frame on an early-exit tier that runs to the
    # iteration cap never satisfied the convergence gate — its output may
    # be drifting (warm-start chains accumulate error when the GRU is
    # not contracting; measured in STREAM_r14.json), so its state is not
    # trusted and the NEXT frame cold-starts, re-seeding the chain from
    # a clean zero-init (the video-codec I-frame move).  Cold frames at
    # the cap stay trusted: that is the stateless baseline by
    # definition.  No effect on fixed-depth tiers (every frame runs the
    # cap there by construction).
    session_reseed_on_cap: bool = True
    # Hidden-state warm start (round 19): carry the multi-level GRU
    # hidden-state tree frame to frame alongside the disparity, so a
    # warm frame resumes the GRU's own trajectory instead of re-deriving
    # it from the context encoder (the half of RAFT's temporal state the
    # r14 flow-only warm start left cold — STREAM_r14 measured tight
    # convergence gates DIVERGING from cold-h warm starts).  Swaps the
    # state/warm executable families for their ``_h`` variants (distinct
    # compile-cost + persist keys); the scene-cut fallback, keyframe
    # guard, and crash demotion invalidate the h-tree in lockstep with
    # the flow state.  False (default): the r14 flow-only families,
    # byte-for-byte.  Requires ``sessions``.
    session_hidden: bool = False
    # Per-session CONTEXT-feature cache (round 15): for streams whose
    # inter-frame thumbnail delta stays tiny (static camera), reuse the
    # session's cnet context bundle instead of re-encoding it every
    # frame — cold frames run the "state_ctx" family (also returns the
    # bundle), coherent warm frames run "warm_ctx" (consumes it; the
    # context encoder never executes).  Invalidated by scene cuts, the
    # keyframe guard, and any frame whose delta exceeds the gate below
    # (the bundle re-establishes at the next cold frame).  Requires
    # ``sessions``; unsupported with shared_backbone (fnet is computed
    # FROM the cnet trunk there).  Responses carry X-Ctx-Cached and
    # hits count into serve_session_ctx_cache_hits_total.
    session_ctx_cache: bool = False
    # Mean inter-frame |Δintensity| (0..255) at or below which a warm
    # frame may reuse the cached context.  Far below the scene-cut
    # threshold by design: context reuse assumes the SCENE is static,
    # not merely continuous.
    ctx_cache_threshold: float = 2.0
    # ---- EDF cross-session frame scheduler (round 19) ------------------
    # Deadline-aware pop policy (serving/batcher.py): requests carrying
    # a per-frame deadline are ordered earliest-deadline-first, and an
    # idle worker whose chosen group cannot yet fill the largest
    # compiled batch size WAITS a bounded slack — never more than
    # edf_max_slack_ms past the head frame's arrival, never closer to
    # the nearest deadline than the bucket's measured dispatch latency —
    # to deliberately coalesce N concurrent streams' frames into one
    # batch-N dispatch.  Deadline-less requests keep the immediate-pop
    # behavior either way; False (default) leaves the scheduler the
    # exact r11 continuous-batching pop (pinned by tests/test_edf.py).
    edf_scheduler: bool = False
    edf_max_slack_ms: float = 50.0
    # ---- Int8 turbo tier (round 15; quant/) ----------------------------
    # Checkpoint-adjacent calibration scale file (quant/calibrate.py):
    # when set, tiers on the int8 path compile with the calibrated
    # percentile-clipped correlation-pyramid scales instead of dynamic
    # in-graph max-abs scales.  None = dynamic scales.
    quant_scales_path: Optional[str] = None
    # ---- XL tier: mesh-sharded big-image serving (round 17) ------------
    # Mesh topology one xl worker's bucket executables shard over, e.g.
    # "rows=4" (image-row context parallelism through the WHOLE forward —
    # the validated rows_gru loop) or "rows=2,corr=2" (rows-sharded
    # encoders x disparity-sharded correlation volume).  One xl worker
    # owns rows*corr devices (parallel.distributed.device_groups,
    # allocated AFTER the data_parallel solo workers) and answers one
    # request with all of them — per-device HBM drops ~1/N
    # (ROWSGRU_MEMORY_r05.json: 141 GiB at rows=1 -> 13.8 GiB/device at
    # 16 ways).  None (default): no xl tier; a replica whose device
    # count cannot supply the mesh SKIPS the tier with a typed log line
    # instead of failing at boot (compile-farm/fleet contract).  XL
    # programs are fixed-depth, full-precision, and stateless (no
    # sessions) — the early-exit/quant/warm knobs do not compose with
    # the sharded executors (config.py).
    xl_mesh: Optional[str] = None
    # Independent xl device groups (each of rows*corr devices).
    xl_workers: int = 1
    # Requests whose padded BUCKET exceeds this many pixels route to the
    # xl family automatically (clients can force any compatible request
    # with ?tier=xl).  Default ~2 MP: about where a 32-iteration
    # full-resolution pair stops being a sensible single-device dispatch
    # (FULLRES_EVAL_r05.json: 16.5 s/image at 5.7 MP on one device).
    xl_threshold_pixels: int = 2_000_000
    # The mesh's own ceiling: buckets past this many pixels exceed what
    # the declared device group can hold (size it from the mesh's
    # measured per-device HBM at your largest warm bucket), so they fall
    # through to halo-overlap tiling — "beyond any mesh still runs
    # through the same bucket engine".  None = the mesh takes
    # everything above the threshold.
    xl_max_pixels: Optional[int] = None
    # Batch ladder compiled per xl bucket; (1,) by default — megapixel
    # pairs are latency-bound, and the mesh already uses the devices.
    xl_batch_sizes: Tuple[int, ...] = (1,)
    # ---- Halo-overlap tiling fallback (serving/tiles.py) ---------------
    # Requests whose padded bucket exceeds this many pixels (and did not
    # take the xl route) are split into equal-height overlapping row
    # tiles, dispatched as ORDINARY bucket requests (tiles of one image
    # share a bucket and batch together — no new scheduler), and
    # stitched by center-crop; the measured tile disagreement lands in
    # serve_tile_seam_epe and on the result (``ServeResult.seam_epe``).
    # None (default): never tile.
    tile_threshold_pixels: Optional[int] = None
    # Owned rows per tile; each tile additionally carries tile_halo
    # context rows on both sides (the per-iteration receptive-field
    # margin the rows_gru halo-exchange contract sizes — tiling cannot
    # refresh halos mid-loop, so it over-provisions 4x and measures the
    # residual as seam error).
    tile_rows: int = 512
    tile_halo: int = 64
    # ---- Model registry (round 21; serving/models.py) ------------------
    # Registered model versions to load at boot from the artifact
    # store's models/ namespace: "name@version" specs (bare "name" =
    # the newest complete version).  Requests pick one with ?model= /
    # X-Model; each registered model carries its OWN RaftStereoConfig
    # and compiles its own executable ladder (distinct compile-cost,
    # persist, and dispatch-group keys — models never share a batch).
    # Empty (default): exactly today's single implicit constructor
    # model — every key, program, and wire byte unchanged.
    models: Tuple[str, ...] = ()
    # Root of the model store; defaults to executable_cache_dir (the
    # weights live NEXT to the executables they compile into).  Required
    # when ``models`` is non-empty or hot registration is wanted.
    model_store_dir: Optional[str] = None
    # The registered model unnamed requests run (the default pointer a
    # hot swap flips); None = the implicit constructor model.
    default_model: Optional[str] = None
    # ---- Quality observability (round 24; telemetry/quality.py) --------
    # Compile the ``return_confidence`` program variants: every non-xl
    # executable additionally returns the per-pixel confidence element
    # derived from the refinement loop's own convergence signals
    # (models/raft_stereo.py), results carry the unpadded full-res map +
    # its mean, and each answered request lands in the
    # serve_confidence{tier,model} histograms, the quality good/bad SLO
    # counters, and the PSI drift watchdog.  False (default): no
    # tracker, no new series, and every program / cost key / persist
    # key / wire byte stays identical to the pre-confidence build
    # (pinned by tests).
    confidence: bool = False
    # Mean confidence below which a request counts AGAINST the quality
    # SLO budget (serve_quality_bad_total) — the split a quality
    # BurnRateTracker burns on.
    confidence_floor: float = 0.5
    # PSI drift watchdog knobs (telemetry/quality.QualityDriftWatchdog):
    # the index threshold that fires the typed quality_drift anomaly
    # (0.25 = the classic "act" band), the healthy-reference sample
    # count frozen at warm-up, and the rolling recent-window length.
    quality_drift_threshold: float = 0.25
    quality_drift_reference: int = 256
    quality_drift_window: int = 128
    # Quality SLO objective: the fraction of requests that may fall
    # below the confidence floor before the quality error budget burns
    # (0.99 = 1% of answers may be low-confidence).  Burns on the same
    # multi-window machinery as availability (telemetry/slo.py,
    # dimension="quality").
    quality_availability: float = 0.99
    # Brownout victim selection (serving/resilience.py): requests whose
    # tier's recent rolling mean confidence sits below this are SPARED
    # from degradation — they already need the expensive program.  0.0
    # (default) keeps the unconditional ladder.  Requires confidence.
    brownout_spare_below: float = 0.0
    # ---- Confidence-gated cascade: the "auto" pseudo-tier --------------
    # Requests naming ?tier=auto run the DRAFT tier first (default: the
    # cheapest rung of the cost ladder, e.g. turbo) and escalate to the
    # ESCALATE tier (default: the most expensive rung, e.g. quality)
    # only when the draft's mean confidence falls below
    # cascade_threshold — "turbo drafts, quality verifies" (ROADMAP
    # item 2).  Oversized requests cascade per halo tile: only the
    # low-confidence tiles re-run expensive.  Requires ``confidence``
    # and at least two configured tiers.
    cascade: bool = False
    cascade_draft: Optional[str] = None
    cascade_escalate: Optional[str] = None
    cascade_threshold: float = 0.5

    def __post_init__(self):
        if self.data_parallel < 1:
            raise ValueError(f"data_parallel={self.data_parallel} must be "
                             f">= 1")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError(f"trace_sample_rate={self.trace_sample_rate} "
                             f"must be in [0, 1]")
        sizes = tuple(sorted(set(int(s) for s in self.batch_sizes)))
        if not sizes or sizes[0] < 1:
            raise ValueError(
                f"batch_sizes={self.batch_sizes} must be positive ints")
        if 1 not in sizes:
            raise ValueError(
                f"batch_sizes={self.batch_sizes} must include 1 (the "
                f"solo-parity bucket every partial batch bottoms out at)")
        if self.shape_bucket is not None and self.shape_bucket % MODEL_DIVIS:
            raise ValueError(
                f"shape_bucket={self.shape_bucket} must be a multiple of "
                f"the model's /{MODEL_DIVIS} divisibility requirement")
        if not 0.0 < self.max_padding_waste < 1.0:
            raise ValueError(f"max_padding_waste={self.max_padding_waste} "
                             f"must be in (0, 1)")
        if self.fetch_dtype not in (None, "fp16", "bf16"):
            raise ValueError(f"fetch_dtype={self.fetch_dtype!r}: use "
                             f"'fp16', 'bf16', or None (full fp32 fetch)")
        for g in self.bucket_grids:
            if g < MODEL_DIVIS or g % MODEL_DIVIS:
                raise ValueError(
                    f"bucket_grids={self.bucket_grids}: every grid must be "
                    f"a multiple of /{MODEL_DIVIS}")
        parsed = tuple(parse_tier(s) for s in self.tiers)  # raises on bad
        names = [t.name for t in parsed]
        if len(set(names)) != len(names):
            raise ValueError(f"tiers={self.tiers}: duplicate tier names")
        if self.default_tier is not None and self.default_tier not in names:
            raise ValueError(
                f"default_tier={self.default_tier!r} is not one of the "
                f"configured tiers {names}")
        if self.max_dispatch_attempts < 1:
            raise ValueError(f"max_dispatch_attempts="
                             f"{self.max_dispatch_attempts} must be >= 1")
        if self.retry_backoff_ms < 0:
            raise ValueError(f"retry_backoff_ms={self.retry_backoff_ms} "
                             f"must be >= 0")
        if self.breaker_failures < 1:
            raise ValueError(f"breaker_failures={self.breaker_failures} "
                             f"must be >= 1")
        if self.breaker_cooldown_s <= 0:
            raise ValueError(f"breaker_cooldown_s="
                             f"{self.breaker_cooldown_s} must be > 0")
        if self.brownout:
            if len(names) < 2:
                raise ValueError(
                    "brownout=True needs at least two configured tiers — "
                    "the degradation ladder IS the tier ladder")
            if not (0 < self.brownout_restore_fraction
                    <= self.brownout_engage_fraction <= 1):
                raise ValueError(
                    f"need 0 < brownout_restore_fraction "
                    f"({self.brownout_restore_fraction}) <= "
                    f"brownout_engage_fraction "
                    f"({self.brownout_engage_fraction}) <= 1")
        for t in self.brownout_exempt_tiers:
            if t not in names:
                raise ValueError(
                    f"brownout_exempt_tiers={self.brownout_exempt_tiers}: "
                    f"{t!r} is not one of the configured tiers {names}")
        if self.sessions:
            if self.session_ttl_s <= 0:
                raise ValueError(f"session_ttl_s={self.session_ttl_s} "
                                 f"must be > 0")
            if self.session_capacity < 1:
                raise ValueError(f"session_capacity="
                                 f"{self.session_capacity} must be >= 1")
        if self.session_hidden and not self.sessions:
            raise ValueError(
                "session_hidden=True needs sessions=True — the hidden "
                "tree is per-stream state")
        if self.edf_max_slack_ms < 0:
            raise ValueError(f"edf_max_slack_ms={self.edf_max_slack_ms} "
                             f"must be >= 0")
        if self.session_ctx_cache:
            if not self.sessions:
                raise ValueError(
                    "session_ctx_cache=True needs sessions=True — the "
                    "context bundle is per-stream state")
            if self.ctx_cache_threshold <= 0:
                raise ValueError(
                    f"ctx_cache_threshold={self.ctx_cache_threshold} "
                    f"must be > 0 (the static-scene gate)")
        if self.xl_mesh is not None:
            # Spec validity is a CONFIG error (fatal at construction);
            # insufficient devices is a REPLICA condition (typed skip at
            # engine boot) — the split the fleet contract needs.
            from raft_stereo_tpu.parallel.mesh import parse_mesh_spec
            parse_mesh_spec(self.xl_mesh)
            if self.xl_workers < 1:
                raise ValueError(f"xl_workers={self.xl_workers} must be "
                                 f">= 1")
            if self.xl_threshold_pixels < 1:
                raise ValueError(f"xl_threshold_pixels="
                                 f"{self.xl_threshold_pixels} must be "
                                 f">= 1")
            xl_sizes = tuple(sorted(set(int(s)
                                        for s in self.xl_batch_sizes)))
            if not xl_sizes or xl_sizes[0] != 1:
                raise ValueError(
                    f"xl_batch_sizes={self.xl_batch_sizes} must be "
                    f"positive ints including 1 (the partial-batch "
                    f"floor)")
            if (self.xl_max_pixels is not None
                    and self.xl_max_pixels <= self.xl_threshold_pixels):
                raise ValueError(
                    f"xl_max_pixels={self.xl_max_pixels} must exceed "
                    f"xl_threshold_pixels={self.xl_threshold_pixels} "
                    f"(the xl routing band would be empty)")
        if self.tile_threshold_pixels is not None \
                and self.tile_threshold_pixels < 1:
            raise ValueError(f"tile_threshold_pixels="
                             f"{self.tile_threshold_pixels} must be >= 1")
        if self.tile_rows < MODEL_DIVIS:
            raise ValueError(
                f"tile_rows={self.tile_rows} must be >= {MODEL_DIVIS} "
                f"(a tile is an ordinary /{MODEL_DIVIS}-padded bucket "
                f"dispatch)")
        if self.tile_halo < 0:
            raise ValueError(f"tile_halo={self.tile_halo} must be >= 0")
        model_names = [parse_model_spec(s)[0] for s in self.models]
        if len(set(model_names)) != len(model_names):
            raise ValueError(f"models={self.models}: duplicate model "
                             f"names (one served version per name)")
        if self.models and not (self.model_store_dir
                                or self.executable_cache_dir):
            raise ValueError(
                "ServeConfig.models needs a store to load from: set "
                "model_store_dir (or executable_cache_dir — the shared "
                "artifact store holds the models/ namespace)")
        if (self.default_model is not None
                and self.default_model not in model_names):
            raise ValueError(
                f"default_model={self.default_model!r} is not one of the "
                f"registered model names {model_names}")
        if not 0.0 <= self.confidence_floor <= 1.0:
            raise ValueError(f"confidence_floor={self.confidence_floor} "
                             f"must be in [0, 1]")
        if self.quality_drift_threshold <= 0:
            raise ValueError(
                f"quality_drift_threshold={self.quality_drift_threshold} "
                f"must be > 0")
        if not 0.0 < self.quality_availability < 1.0:
            raise ValueError(
                f"quality_availability={self.quality_availability} must "
                f"be in (0, 1) — 1.0 leaves no quality budget to burn")
        if self.brownout_spare_below and not self.confidence:
            raise ValueError(
                "brownout_spare_below needs confidence=True — the spare "
                "signal IS the rolling confidence telemetry")
        if not 0.0 <= self.brownout_spare_below <= 1.0:
            raise ValueError(
                f"brownout_spare_below={self.brownout_spare_below} must "
                f"be in [0, 1]")
        if self.cascade:
            if not self.confidence:
                raise ValueError("cascade=True needs confidence=True — "
                                 "the escalation gate IS the confidence "
                                 "signal")
            if len(names) < 2:
                raise ValueError(
                    "cascade=True needs at least two configured tiers "
                    "(a draft and an escalation target)")
            for field_name, value in (("cascade_draft",
                                       self.cascade_draft),
                                      ("cascade_escalate",
                                       self.cascade_escalate)):
                if value is not None and value not in names:
                    raise ValueError(
                        f"{field_name}={value!r} is not one of the "
                        f"configured tiers {names}")
            if (self.cascade_draft is not None
                    and self.cascade_draft == self.cascade_escalate):
                raise ValueError(
                    f"cascade_draft and cascade_escalate are both "
                    f"{self.cascade_draft!r} — the cascade would never "
                    f"change programs")
            if not 0.0 <= self.cascade_threshold <= 1.0:
                raise ValueError(
                    f"cascade_threshold={self.cascade_threshold} must "
                    f"be in [0, 1]")
        elif self.cascade_draft is not None \
                or self.cascade_escalate is not None:
            raise ValueError("cascade_draft/cascade_escalate need "
                             "cascade=True")

    def parsed_tiers(self) -> Tuple[RequestTier, ...]:
        return tuple(parse_tier(s) for s in self.tiers)


@dataclasses.dataclass
class ServeResult:
    """One answered request: the flow plus its latency decomposition."""

    flow: np.ndarray             # (H, W) x-flow (= -disparity), float32
    queue_wait_s: float          # admission -> worker pickup
    device_s: float              # dispatch -> outputs ready (advisory
    #                              behind an async tunnel; see metrics.py)
    fetch_s: float               # device->host result transfer
    total_s: float               # admission -> result ready
    batch_size: int              # occupancy of the dispatch it rode in
    iters_used: Optional[int] = None  # GRU trip count of the dispatch
    #                              (the worst batch member's depth; the
    #                              configured depth on fixed-iters paths)
    tier: Optional[str] = None   # latency tier the request RAN at
    # Brownout provenance: the tier the client asked for when it differs
    # from ``tier`` (None = served as requested).  The HTTP layer renders
    # this as the X-Degraded header.
    requested_tier: Optional[str] = None
    attempts: int = 1            # dispatch attempts including the one
    #                              that succeeded (> 1 = recovered crash)
    # Streaming-session provenance (engine.submit_session): the session
    # this frame belonged to, its index in the stream, whether the GRU
    # warm-started from the previous frame's disparity, whether the
    # scene-cut gate forced a cold start, and the measured inter-frame
    # delta.  ``flow_low`` is the PADDED low-res x-flow the session
    # carries forward — surfaced so benches/tests can chain manually.
    session_id: Optional[str] = None
    frame_index: Optional[int] = None
    warm: bool = False
    scene_cut: bool = False
    frame_delta: Optional[float] = None
    flow_low: Optional[np.ndarray] = None
    # Context-cache provenance (session_ctx_cache): ``ctx_cached`` — this
    # frame REUSED the session's context bundle (the context encoder
    # never ran; X-Ctx-Cached header); ``ctx`` — the bundle a cold
    # state_ctx frame computed, folded back into the session.
    ctx_cached: bool = False
    ctx: Optional[object] = None
    # Hidden-state provenance (round 19, ``ServeConfig.session_hidden``):
    # the frame's FINAL per-level GRU hidden tree (batch-axis-free host
    # arrays) the session chains into the next frame's warm-h dispatch,
    # and whether THIS frame consumed one (``warm_hidden`` — the warm-h
    # families).
    hidden: Optional[object] = None
    warm_hidden: bool = False
    # XL/tiling provenance (round 17): ``mesh`` — the compact mesh label
    # ("rows4") when this request ran as a mesh-sharded xl dispatch
    # (``tier`` reads "xl" then); ``tiles`` — how many halo-overlap tile
    # dispatches a stitched answer rode (X-Tiles header); ``seam_epe`` —
    # the tiles' measured mean overlap disagreement in px (None for
    # untiled requests and single-overlap-free stitches).
    mesh: Optional[str] = None
    tiles: Optional[int] = None
    seam_epe: Optional[float] = None
    # Model provenance (round 21, serving/models.py): which registered
    # model answered — None/None for the implicit constructor model
    # (wire bytes unchanged); the HTTP layer renders these as
    # X-Model / X-Model-Version.
    model: Optional[str] = None
    model_version: Optional[str] = None
    # Trace provenance (round 23 fleet observability): the sampled trace
    # this request recorded spans under — None when unsampled (the
    # common case).  The HTTP layer surfaces it as X-Trace-Id so a
    # client can quote the exact id that finds the request's timeline in
    # /debug/spans (and, across the router hop, the federated view).
    trace_id: Optional[str] = None
    # Quality provenance (round 24, ``ServeConfig.confidence``): the
    # unpadded full-resolution (H, W) float32 confidence map in (0, 1]
    # (None with confidence off and on xl/mesh dispatches), its mean
    # (the scalar the telemetry, SLO, and cascade gate consume), and —
    # cascade requests only — whether this answer came from the
    # escalation tier, which tier drafted it, and the draft's mean
    # confidence that triggered (or cleared) the escalation.
    confidence: Optional[np.ndarray] = None
    confidence_mean: Optional[float] = None
    escalated: bool = False
    draft_tier: Optional[str] = None
    draft_confidence: Optional[float] = None

    @property
    def degraded(self) -> bool:
        return self.requested_tier is not None

    @property
    def disparity(self) -> np.ndarray:
        """Positive disparity (the user-facing convention, cli/demo.py)."""
        return -self.flow


@dataclasses.dataclass
class _Payload:
    """What the engine parks in Request.payload: padded inputs + unpadder,
    plus (session frames only) the warm-start init and the state the
    completion callback folds back into the session."""

    left: np.ndarray             # (Hp, Wp, 3) host-padded
    right: np.ndarray
    padder: InputPadder
    flow_init: Optional[np.ndarray] = None   # (Hp/f, Wp/f) f32, warm only
    hidden_init: Optional[object] = None     # warm-h: per-level hidden tree
    session: Optional[object] = None         # sessions.StereoSession
    thumb: Optional[np.ndarray] = None       # THIS frame's thumbnail
    raw_shape: Optional[Tuple[int, int]] = None
    frame_index: Optional[int] = None
    scene_cut: bool = False
    frame_delta: Optional[float] = None
    ctx_init: Optional[object] = None        # warm_ctx: the session's
    #                                          cached context bundle


@dataclasses.dataclass
class _XlGroup:
    """One xl worker's device group: the mesh its bucket executables
    shard over, the variables replicated onto it, and the replicated
    NamedSharding the dispatch path uploads image buffers with."""

    devices: Tuple
    mesh: object          # jax.sharding.Mesh (1, corr, rows)
    variables: object     # params replicated over the group
    sharding: object      # NamedSharding(mesh, P()) for uploads

    @property
    def label(self) -> str:
        return "+".join(str(getattr(d, "id", i))
                        for i, d in enumerate(self.devices))


@dataclasses.dataclass
class _EngineModel:
    """One served model's engine-side state: the identity coordinate
    plus everything the dispatch path reads per model — the effective
    config, the per-tier model objects, the per-worker resident fp32
    trees, and the lazily quantized int8 trees.  The implicit
    constructor model is the ``name=None`` bundle; its fields are
    exactly the attributes the pre-registry engine kept flat on
    ``self`` (which stay as aliases — same objects, zero behavior
    drift)."""

    name: Optional[str]          # None = the implicit constructor model
    version: Optional[str]
    config: RaftStereoConfig
    effective_config: RaftStereoConfig
    model: RAFTStereo
    tier_models: Dict[Optional[str], RAFTStereo]
    host_variables: object
    worker_vars: List
    qvars_host: object = None
    qvars: Dict[int, object] = dataclasses.field(default_factory=dict)
    # Retirement latch: resolve_model refuses a retiring model (typed
    # 404) while its in-flight dispatches drain.
    retiring: bool = False

    @property
    def coord(self) -> Optional[str]:
        """``name@version``, or None for the implicit model — the tag
        compile-cost keys, persist keys, and metric labels carry."""
        if self.name is None:
            return None
        return model_coord(self.name, self.version or "0")


@dataclasses.dataclass
class _XlTier:
    """Engine-side state of the xl serving tier (``ServeConfig.xl_mesh``):
    the parsed topology, the model whose config carries the sharding
    knobs (rows_shards / corr_w2_shards / rows_gru — same parameter tree
    as the base model, different compiled programs), and the device
    groups that serve it."""

    spec: Dict[str, int]       # {"rows": r, "corr": c}
    label: str                 # compact key/metric tag, e.g. "rows4"
    size: int                  # devices per group (rows * corr)
    model: RAFTStereo          # the xl-config model (shared params)
    groups: List[_XlGroup]


class BucketPolicy:
    """Maps a raw image (H, W) to its padded dispatch bucket (Hp, Wp).

    Static mode (``grids`` of length 1): the fixed grid — /32 by default,
    or ``ServeConfig.shape_bucket`` — exactly the reference's padding
    semantics.

    Adaptive mode: a shape starts at the COARSEST grid (coarse buckets
    collapse more raw shapes into one compiled ladder, so compiles and
    cold starts are fewest), and ``note`` — fed the same per-dispatch
    real/padding pixel counts as the ``serve_bucket_*_pixels_total``
    counters — refines a bucket to the next finer grid once its measured
    cumulative waste fraction exceeds ``max_waste``.  Refinement is
    monotonic and bottoms out at the /32 floor, which the model's
    divisibility constraint makes irreducible.
    """

    def __init__(self, grids: Sequence[int] = (MODEL_DIVIS,),
                 max_waste: float = 0.10, min_observe_px: int = 0,
                 refinements_counter=None):
        grids = sorted(set(int(g) for g in grids), reverse=True)
        if not grids or any(g % MODEL_DIVIS or g < MODEL_DIVIS
                            for g in grids):
            raise ValueError(f"grids={grids} must be multiples of "
                             f"/{MODEL_DIVIS}")
        self.grids = tuple(grids)         # coarsest first
        self.max_waste = max_waste
        self.min_observe_px = min_observe_px
        self._lock = threading.Lock()
        self._px: Dict[Tuple[int, int], List[int]] = {}  # bucket -> [real,
        #                                                   dispatched]
        self._refined: set = set()        # buckets past the waste bound
        self._refinements = refinements_counter
        self.adaptive = len(self.grids) > 1

    @staticmethod
    def _pad_to(h: int, w: int, grid: int) -> Tuple[int, int]:
        return (-(-h // grid) * grid, -(-w // grid) * grid)

    def bucket_for(self, h: int, w: int) -> Tuple[int, int, int]:
        """The (Hp, Wp, grid) this raw shape dispatches at: the coarsest
        grid whose bucket has not been refined away (the finest grid is
        always accepted)."""
        with self._lock:
            for g in self.grids[:-1]:
                bucket = self._pad_to(h, w, g)
                if bucket not in self._refined:
                    return bucket + (g,)
            g = self.grids[-1]
            return self._pad_to(h, w, g) + (g,)

    def note(self, bucket: Tuple[int, int], real_px: int,
             dispatched_px: int) -> None:
        """Per-dispatch waste feedback (the engine calls this alongside
        ``ServingMetrics.observe_padding``).  Crossing ``max_waste``
        refines the bucket: subsequent shapes that would have used it route
        to the next finer grid instead."""
        if not self.adaptive or dispatched_px <= 0:
            return
        with self._lock:
            if bucket in self._refined:
                return
            acc = self._px.setdefault(tuple(bucket), [0, 0])
            acc[0] += real_px
            acc[1] += dispatched_px
            if acc[1] < max(self.min_observe_px, 1):
                return
            waste = 1.0 - acc[0] / acc[1]
            if waste > self.max_waste:
                self._refined.add(tuple(bucket))
                log.info(
                    "bucket %sx%s refined: measured padding waste %.1f%% "
                    "> %.1f%% over %d dispatched pixels — shapes re-route "
                    "to the next finer pad grid",
                    bucket[0], bucket[1], waste * 100,
                    self.max_waste * 100, acc[1])
                if self._refinements is not None:
                    self._refinements.inc()

    @property
    def refined_buckets(self) -> Tuple[Tuple[int, int], ...]:
        with self._lock:
            return tuple(sorted(self._refined))


class _SinkRef:
    """Late-bound anomaly-sink handle: the brownout controller (and any
    other long-lived component) holds this instead of the sink itself,
    because the CLI attaches the sink after the engine is constructed."""

    def __init__(self, engine: "ServingEngine"):
        self._engine = engine

    def fire(self, kind: str, **detail):
        sink = self._engine.sink
        if sink is not None:
            return sink.fire(kind, **detail)
        return None


class ServingEngine:
    """The unified serving engine: one object owning the batch-N compile
    cache, the continuous-batching scheduler, the device worker pool, and
    the cost/waste telemetry loop.

    ``devices`` defaults to the first ``serve_cfg.data_parallel`` local JAX
    devices; each gets a worker thread with the variables resident on that
    device.  The public surface is unchanged from the round-6
    ``StereoService`` (``submit``/``infer``/``drain``/``close``), which
    remains as an alias.
    """

    def __init__(self, config: RaftStereoConfig, variables,
                 serve_cfg: ServeConfig = ServeConfig(),
                 devices: Optional[Sequence] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer=None):
        import jax

        from raft_stereo_tpu.telemetry.spans import SpanTracer

        self.serve_cfg = serve_cfg
        # Request-path span tracer (telemetry/spans.py).  At the default
        # sample rate 0.0 every start_trace returns None and the span
        # plumbing below is a handful of no-op attribute checks per
        # request — serving numerics and dispatch behavior are untouched.
        self.tracer = (tracer if tracer is not None
                       else SpanTracer(serve_cfg.trace_sample_rate))
        if devices is None:
            # The ONE device-discovery helper the engine and the parallel
            # runtime share (parallel/distributed.py): a stable id-sorted
            # order, so the solo worker pool and the xl mesh groups below
            # partition the same list instead of each trusting
            # jax.local_devices() ordering independently.
            from raft_stereo_tpu.parallel.distributed import device_groups
            solo = device_groups(1, serve_cfg.data_parallel)
            if not solo:
                raise ValueError(
                    f"data_parallel={serve_cfg.data_parallel} exceeds the "
                    f"{len(jax.local_devices())} local devices")
            devices = [g[0] for g in solo]
        self.devices = list(devices)
        self.metrics = ServingMetrics(registry,
                                      max_batch=serve_cfg.max_batch)
        # Compile-cost registry (telemetry/costs.py): one per engine,
        # shared by all workers — same bucket => same program => one cost
        # record per (shape, batch) key.  None (default) leaves the jit
        # dispatch untouched.
        self.costs = None
        self._mfu = None
        if serve_cfg.cost_telemetry:
            from raft_stereo_tpu.telemetry.costs import (CompileRegistry,
                                                         MfuMeter)
            self.costs = CompileRegistry(
                registry=self.metrics.registry,
                device_peak_tflops=serve_cfg.device_peak_tflops)
            self._mfu = MfuMeter(
                self.metrics.mfu, self.costs.peak_flops,
                achieved_gauge=self.metrics.achieved_flops_per_s)
        # The spatial padding policy: static /32 (or shape_bucket) unless
        # adaptive_buckets turns on the waste feedback loop.
        if serve_cfg.adaptive_buckets:
            grids = tuple(serve_cfg.bucket_grids) + (
                serve_cfg.shape_bucket or MODEL_DIVIS,)
        else:
            grids = (serve_cfg.shape_bucket or MODEL_DIVIS,)
        self.policy = BucketPolicy(
            grids=grids, max_waste=serve_cfg.max_padding_waste,
            refinements_counter=self.metrics.bucket_refinements)
        # The model, with the same deep-iteration corr_fp32 guard the solo
        # runner applies — both paths compile the identical program.
        self.config = config
        # Calibrated correlation scales for int8 tiers (quant/calibrate):
        # loaded once and swapped into every quant tier's effective
        # config, so the compiled programs carry the percentile-clipped
        # constants instead of dynamic in-graph reductions.
        self._quant_corr_scales = None
        # Calibrated per-conv activation scales for int8_mxu tiers
        # (quant/calibrate.conv_input_scales): baked into the packs the
        # lazy host quantization builds (_vars_for); None (no scale
        # file, or a pre-r22 record without qin sites) leaves the
        # int8_mxu convs on the dynamic in-graph max-abs fallback.
        self._quant_act_scales = None
        if serve_cfg.quant_scales_path:
            from raft_stereo_tpu.quant import (conv_input_scales,
                                               corr_scales, load_scales)
            _scale_record = load_scales(serve_cfg.quant_scales_path)
            self._quant_corr_scales = corr_scales(_scale_record)
            self._quant_act_scales = (conv_input_scales(_scale_record)
                                      or None)

        # Latency tiers: one effective config / model per tier (the
        # early-exit + quant knobs swapped into the SAME architecture —
        # the parameter tree is shared, only the compiled program
        # differs).  A tier whose effective config equals the base one
        # (threshold <= 0, e.g. "quality") maps to the base model so its
        # requests share the base executables — the bitwise-parity
        # bucket stays one program.  Int8 tiers ("turbo") get their own
        # model AND their own quantized variable tree (_vars_for).
        self.tiers: Dict[str, RequestTier] = {
            t.name: t for t in serve_cfg.parsed_tiers()}
        self.default_tier: Optional[str] = None
        if self.tiers:
            self.default_tier = serve_cfg.default_tier or (
                "quality" if "quality" in self.tiers
                else next(iter(self.tiers)))
        if serve_cfg.session_ctx_cache and config.shared_backbone:
            raise ValueError(
                "session_ctx_cache is unsupported with shared_backbone: "
                "fnet is computed from the cnet trunk, so the context "
                "encoder cannot be skipped (models/raft_stereo.py)")
        # Model registry (round 21, serving/models.py): every served
        # model — the implicit constructor one under key None, plus any
        # registered "name@version" — keeps its per-model state in one
        # _EngineModel bundle.  The int8 quantization lock is shared
        # (host quantization runs at most once per bundle).
        self._qvars_lock = threading.Lock()
        self._models_lock = threading.Lock()
        self._pending_lock = threading.Lock()
        self._model_pending: Dict[Optional[str], int] = {}
        base_bundle = self._build_bundle(None, None, config, variables)
        self._models: Dict[Optional[str], _EngineModel] = {
            None: base_bundle}
        # Flat aliases of the implicit bundle — the pre-registry
        # attribute surface every existing call site (HTTP, CLIs,
        # tests) keeps reading.  Same objects, zero drift.
        self.config = base_bundle.config
        self.effective_config = base_bundle.effective_config
        self.model = base_bundle.model
        self._tier_models = base_bundle.tier_models
        self._worker_vars = base_bundle.worker_vars
        self._host_variables = variables
        # The model store + boot-time registrations (ServeConfig.models).
        self.model_store: Optional[ModelStore] = None
        store_dir = (serve_cfg.model_store_dir
                     or serve_cfg.executable_cache_dir)
        if store_dir and (serve_cfg.models
                          or serve_cfg.model_store_dir):
            self.model_store = ModelStore(store_dir)
        self.default_model: Optional[str] = None
        for spec in serve_cfg.models:
            reg = self.model_store.resolve(spec)   # deep-verified load
            self._models[reg.name] = self._build_bundle(
                reg.name, reg.version, reg.config, reg.variables)
            log.info("model %s registered at boot", reg.coord)
        if serve_cfg.default_model is not None:
            self.default_model = serve_cfg.default_model
        self._cache_lock = threading.Lock()
        self._compiled: "collections.OrderedDict[Tuple, object]" = (
            collections.OrderedDict())
        # Per-group dispatch-latency EWMA (seconds, device + fetch):
        # what the EDF bounded-slack derivation subtracts from the
        # nearest deadline so coalescing can delay a frame but never be
        # the reason it misses.  Updated after every dispatch
        # (_note_dispatch_latency); a group with no measurement yet
        # estimates 0 — the slack then bounds only on edf_max_slack_ms.
        self._latency_lock = threading.Lock()
        self._dispatch_latency_s: Dict[Tuple, float] = {}
        self.queue = BucketQueue(
            max_batch=serve_cfg.max_batch,
            batch_sizes=serve_cfg.batch_sizes,
            max_queue=serve_cfg.max_queue, metrics=self.metrics,
            edf=serve_cfg.edf_scheduler,
            edf_max_slack_s=serve_cfg.edf_max_slack_ms / 1e3,
            latency_fn=self._dispatch_latency_estimate)
        # ---- XL tier: mesh-sharded device groups (round 17) ------------
        # ``self.xl`` is an _XlTier (mesh spec + per-group meshes +
        # replicated variables) or None — None either because no xl_mesh
        # was configured or because THIS replica cannot supply the
        # devices (typed skip; the fleet contract for heterogeneous
        # replicas).  xl workers are extra entries at the END of the
        # unified worker table: indices [len(devices), len(devices) +
        # xl_workers) with their own breakers/threads, popping only
        # FAMILY_XL groups from the one shared queue.
        self.xl: Optional[_XlTier] = None
        self._xl_sizes: Tuple[int, ...] = ()
        if serve_cfg.xl_mesh is not None:
            self._init_xl(variables)
        # ---- Resilience layer (round 13) -------------------------------
        # Anomaly sink (telemetry/watchdog.AnomalySink | None): fires
        # worker_crash / circuit / brownout / poisoned events into the
        # run-event log + flight recorder.  The CLI attaches it after
        # construction (attach_anomaly_sink) because the event log is
        # wired after the engine exists; every fire site reads the
        # attribute at fire time.
        self.sink = None
        # Chaos injector: None unless configured AND enabled — the
        # dispatch path then carries exactly one attribute check.
        self.chaos: Optional[ChaosInjector] = None
        if serve_cfg.chaos is not None and serve_cfg.chaos.enabled:
            self.chaos = ChaosInjector(
                serve_cfg.chaos,
                observe=self.metrics.observe_injected_fault)
            log.warning("CHAOS ENABLED: %s — injected faults are ON for "
                        "this engine", serve_cfg.chaos)
        # Per-worker circuit breakers (solo devices AND xl device
        # groups); gauges start in the closed state so /metrics shows
        # every worker's circuit from boot.
        self.breakers = [
            CircuitBreaker(
                failure_threshold=serve_cfg.breaker_failures,
                cooldown_s=serve_cfg.breaker_cooldown_s,
                on_state=self._make_circuit_callback(i))
            for i in range(self._worker_count())]
        for i in range(self._worker_count()):
            self.metrics.circuit_gauge(i).set(CIRCUIT_CLOSED)
        # Brownout controller over the tier cost ladder (cheapest-first).
        self.brownout: Optional[BrownoutController] = None
        if serve_cfg.brownout:
            self.brownout = BrownoutController(
                self.metrics, serve_cfg.max_queue,
                ladder=cost_ladder(serve_cfg.parsed_tiers()),
                engage_fraction=serve_cfg.brownout_engage_fraction,
                engage_s=serve_cfg.brownout_engage_s,
                restore_fraction=serve_cfg.brownout_restore_fraction,
                restore_s=serve_cfg.brownout_restore_s,
                poll_s=serve_cfg.brownout_poll_s,
                gauge=self.metrics.brownout_level,
                sink=_SinkRef(self)).start()
            # Confidence-aware victim selection (round 24): requests at
            # tiers whose recent answers were already low-confidence are
            # spared from degradation (_admit_tier feeds the rolling
            # mean).  0.0 (default) disables the check.
            self.brownout.spare_below = serve_cfg.brownout_spare_below
        # ---- Quality observability (round 24) --------------------------
        # Per-request confidence telemetry + PSI drift watchdog
        # (telemetry/quality.py); None with confidence off — no tracker,
        # no series, the exposition stays byte-identical.  The drift
        # watchdog fires through _SinkRef, so a sink attached after
        # construction (the CLI order) is still reached.
        self.quality = None
        self._cascade_drafts = None
        self._cascade_escalations = None
        if serve_cfg.confidence:
            from raft_stereo_tpu.telemetry.quality import QualityTracker
            from raft_stereo_tpu.telemetry.slo import BurnRateTracker
            # The quality error budget: the fraction of requests allowed
            # below the confidence floor, burned on the same multi-window
            # machinery as the fleet's availability budget — one more
            # dimension label on the burn-rate gauge family.
            quality_slo = BurnRateTracker(
                availability=serve_cfg.quality_availability,
                registry=self.metrics.registry,
                gauge_name="serve_slo_burn_rate",
                dimension="quality")
            self.quality = QualityTracker(
                registry=self.metrics.registry,
                sink=_SinkRef(self),
                floor=serve_cfg.confidence_floor,
                drift_threshold=serve_cfg.quality_drift_threshold,
                drift_reference_size=serve_cfg.quality_drift_reference,
                drift_window=serve_cfg.quality_drift_window,
                slo=quality_slo)
        # Cascade tier resolution ("auto"): draft on the cheapest rung
        # of the cost ladder, escalate to the most expensive, unless the
        # config names either explicitly.
        self._cascade_draft: Optional[str] = None
        self._cascade_escalate: Optional[str] = None
        if serve_cfg.cascade:
            ladder = cost_ladder(serve_cfg.parsed_tiers())
            self._cascade_draft = serve_cfg.cascade_draft or ladder[0]
            self._cascade_escalate = (serve_cfg.cascade_escalate
                                      or ladder[-1])
            if self._cascade_draft == self._cascade_escalate:
                raise ValueError(
                    f"cascade draft and escalation tiers both resolve "
                    f"to {self._cascade_draft!r} — configure "
                    f"cascade_draft/cascade_escalate explicitly")
            self._cascade_drafts = self.metrics.registry.counter(
                "serve_cascade_draft_total",
                "Cascade (tier=auto) requests answered by the draft "
                "tier alone")
            self._cascade_escalations = self.metrics.registry.counter(
                "serve_cascade_escalated_total",
                "Cascade (tier=auto) requests escalated to the "
                "expensive tier on low draft confidence")
        # Persistent executable cache / shared artifact store
        # (serving/persist.py).
        self.disk_cache = None
        if serve_cfg.executable_cache_dir:
            from raft_stereo_tpu.serving.persist import ExecutableDiskCache
            self.disk_cache = ExecutableDiskCache(
                serve_cfg.executable_cache_dir,
                max_bytes=serve_cfg.executable_cache_max_bytes,
                read_only=serve_cfg.executable_cache_read_only,
                bytes_gauge=self.metrics.persist_cache_bytes)
        # Streaming-session store (serving/sessions.py): the per-stream
        # warm-start state behind submit_session / POST /v1/stream.  None
        # (default) keeps the engine stateless — no warm executable
        # families compile, prewarm, or join the readiness target.
        self.sessions: Optional[SessionStore] = None
        if serve_cfg.sessions:
            self.sessions = SessionStore(
                capacity=serve_cfg.session_capacity,
                ttl_s=serve_cfg.session_ttl_s,
                active_gauge=self.metrics.sessions_active,
                created_counter=self.metrics.sessions_created,
                expired_counter=self.metrics.sessions_expired,
                evicted_counter=self.metrics.sessions_evicted)
        # Session handoff (round 18): the artifact store's sessions/
        # namespace a draining engine publishes its live streams into,
        # and a receiving engine lazily adopts them from
        # (submit_session handoff_key=).  Needs BOTH the session store
        # and a shared artifact directory; absent either, drains keep
        # the r16 typed-loss behavior.
        self.handoff_store = None
        self._handoff_manifest: Optional[Dict[str, object]] = None
        self._handoff_fetched = threading.Event()
        self._handoff_lock = threading.Lock()
        self._handoff_blobs: Dict[str, Dict] = {}
        if serve_cfg.sessions and serve_cfg.executable_cache_dir:
            from raft_stereo_tpu.serving.persist import SessionHandoffStore
            self.handoff_store = SessionHandoffStore(
                serve_cfg.executable_cache_dir,
                ttl_s=max(serve_cfg.session_ttl_s, 60.0) * 4)
        # Retry bookkeeping: requests bounced by a crashed dispatch sit in
        # backoff timers between dequeue and requeue — drain() must wait
        # for them and close() must fail them, so they are accounted here.
        self._retry_lock = threading.Lock()
        self._pending_retries = 0
        self._retry_timers: set = set()   # (Timer, reqs) pairs
        # Readiness (the /readyz gate): the configured warm surface is
        # warmup_shapes x distinct executable families x batch sizes x
        # workers; ready once every entry has dispatched once (prewarm or
        # traffic).  No configured warmup -> ready at boot.
        self._warm_lock = threading.Lock()
        self._warmed: set = set()
        self._warm_target: set = set()
        for hw in serve_cfg.warmup_shapes:
            hp, wp, _ = self.policy.bucket_for(int(hw[0]), int(hw[1]))
            if self._xl_routes((hp, wp)):
                # This bucket's traffic runs on the xl mesh groups —
                # warming the solo ladder for it would pay megapixel
                # single-device compiles no request will ever dispatch.
                # (Named models never route xl, so the entry is
                # implicit-model only.)
                for widx in self._xl_worker_indices():
                    for n in self._xl_sizes:
                        self._warm_target.add(
                            (widx, (hp, wp), n, None, FAMILY_XL, None))
                continue
            for mname in self._registered_names():
                for widx in range(len(self.devices)):
                    for tier in self._distinct_cache_tiers(mname):
                        for n in self.queue.sizes:
                            for family in self._families():
                                self._warm_target.add(
                                    (widx, (hp, wp), n, tier, family,
                                     mname))
        self._closed = False
        self._shutting_down = False
        self._workers_lock = threading.Lock()
        self._workers = [
            threading.Thread(target=self._worker_loop, args=(i,),
                             daemon=True, name=f"stereo-worker-{i}")
            for i in range(self._worker_count())]
        for t in self._workers:
            t.start()
        if serve_cfg.prewarm_on_init:
            for hw in serve_cfg.warmup_shapes:
                self.prewarm(hw)

    def _make_circuit_callback(self, widx: int):
        """Breaker transition hook for one device: gauge + anomaly event.
        Opening the circuit is the page-worthy event (a device is
        quarantined); closing is the all-clear."""
        def on_state(old: int, new: int, failures: int) -> None:
            self.metrics.circuit_gauge(widx).set(new)
            log.warning("device %d circuit %s -> %s (%d consecutive "
                        "failures)", widx, circuit_state_name(old),
                        circuit_state_name(new), failures)
            sink = self.sink
            if sink is not None:
                sink.fire(f"circuit_{circuit_state_name(new)}",
                          device=widx,
                          previous=circuit_state_name(old),
                          consecutive_failures=failures)
        return on_state

    def attach_anomaly_sink(self, sink) -> None:
        """Wire an AnomalySink (telemetry/watchdog.py): resilience
        transitions emit anomaly run events + flight-recorder bundles
        through the same path the watchdogs use."""
        self.sink = sink

    # -------------------------------------------------------- model registry
    def _effective(self, cfg_in: RaftStereoConfig) -> RaftStereoConfig:
        """One model config's effective inference form: the solo runner's
        deep-iteration guard plus the calibrated int8 correlation scales
        swapped into quantized configs (quant/calibrate.py)."""
        eff = effective_inference_config(cfg_in, self.serve_cfg.iters)
        if (eff.quant != "off" and self._quant_corr_scales is not None
                and eff.quant_corr_scales is None):
            eff = dataclasses.replace(
                eff, quant_corr_scales=self._quant_corr_scales)
        return eff

    def _build_bundle(self, name: Optional[str], version: Optional[str],
                      config: RaftStereoConfig, variables) -> _EngineModel:
        """Build one model's engine-side state: effective config, the
        per-tier model objects (fixed-depth tiers share the bundle's
        base model — one program per DISTINCT effective config), and the
        per-worker resident fp32 trees.  Same construction for the
        implicit model and every registered one."""
        import jax

        eff = self._effective(config)
        model = RAFTStereo(eff)
        tier_models: Dict[Optional[str], RAFTStereo] = {None: model}
        for tname, tier in self.tiers.items():
            teff = self._effective(tier.apply(config))
            tier_models[tname] = (model if teff == eff
                                  else RAFTStereo(teff))
        worker_vars = [jax.device_put(variables, d)
                       for d in self.devices]
        return _EngineModel(name=name, version=version, config=config,
                            effective_config=eff, model=model,
                            tier_models=tier_models,
                            host_variables=variables,
                            worker_vars=worker_vars)

    def _registered_names(self, include_implicit: bool = True
                          ) -> List[Optional[str]]:
        """Model names this engine serves, implicit first — what the
        warm target and prewarm iterate."""
        with self._models_lock:
            names = sorted(n for n in self._models if n is not None)
        return ([None] + names) if include_implicit else names

    def resolve_model(self, model: Optional[str]) -> Optional[str]:
        """The model a request actually runs: the named one (validated
        against the registry), or the default-model pointer, or None
        (the implicit constructor model).  Raises the typed
        ``ModelUnknown`` (HTTP 404 ``model_unknown``) on an
        unregistered or retiring name."""
        if model is None:
            model = self.default_model
        if model is None:
            return None
        bundle = self._models.get(model)
        if bundle is None or bundle.retiring:
            with self._models_lock:
                known = [n for n, b in self._models.items()
                         if n is not None and not b.retiring]
            raise ModelUnknown(model, known)
        return model

    def _note_pending(self, model: Optional[str], delta: int) -> None:
        """Per-model in-flight admission count — ``retire_model``'s
        drain signal (a model with pending admissions must not have its
        pytree evicted under a dispatch that will still read it)."""
        with self._pending_lock:
            self._model_pending[model] = (
                self._model_pending.get(model, 0) + delta)

    def _model_pending_count(self, model: Optional[str]) -> int:
        with self._pending_lock:
            return self._model_pending.get(model, 0)

    def _extend_warm_target(self, name: str) -> None:
        """Grow the /readyz warm surface by one registered model's
        ladder: ``ready`` flips False until the new model's prewarm
        completes — a hot swap can never report ready ahead of a warm
        ladder (acceptance: model_smoke asserts this)."""
        with self._warm_lock:
            for hw in self.serve_cfg.warmup_shapes:
                hp, wp, _ = self.policy.bucket_for(int(hw[0]),
                                                   int(hw[1]))
                if self._xl_routes((hp, wp)):
                    continue    # named models never route xl
                for widx in range(len(self.devices)):
                    for tier in self._distinct_cache_tiers(name):
                        for n in self.queue.sizes:
                            for family in self._families():
                                self._warm_target.add(
                                    (widx, (hp, wp), n, tier, family,
                                     name))

    def _purge_model_cache(self, name: str,
                           drop_target: bool = False) -> None:
        """Drop one model's in-memory compiled executables and warm
        entries (same-name version replace / retirement).  Disk-cache
        entries stay — their content keys carry the version, so they
        can never serve the wrong weights."""
        with self._cache_lock:
            for k in [k for k in self._compiled if k[5] == name]:
                self._compiled.pop(k)
        with self._warm_lock:
            self._warmed = {e for e in self._warmed if e[5] != name}
            if drop_target:
                self._warm_target = {e for e in self._warm_target
                                     if e[5] != name}

    def register_model(self, spec: str, set_default: bool = False,
                       prewarm: bool = True) -> Dict[str, object]:
        """Hot-register a model version on this LIVE engine (``POST
        /admin/models``): deep-verified store load, bundle build
        (device placement; the turbo tier quantizes lazily at first
        dispatch), warm-target extension, prewarm of the declared
        ladder through the warm artifact-store path, and — only then,
        when asked — the atomic default-pointer flip.  Re-registering
        the SAME name@version is idempotent; a new version under a
        live name replaces it (its in-memory executables purge; the
        old pytree is released once in-flight dispatches drain)."""
        if self.model_store is None:
            store_dir = (self.serve_cfg.model_store_dir
                         or self.serve_cfg.executable_cache_dir)
            if not store_dir:
                raise RuntimeError(
                    "no model store: construct the engine with "
                    "ServeConfig.model_store_dir (or "
                    "executable_cache_dir) to register models")
            self.model_store = ModelStore(store_dir)
        reg = self.model_store.resolve(spec)   # deep SHA-256 verify
        with self._models_lock:
            existing = self._models.get(reg.name)
            fresh = not (existing is not None
                         and existing.version == reg.version
                         and not existing.retiring)
        if fresh:
            bundle = self._build_bundle(reg.name, reg.version,
                                        reg.config, reg.variables)
            if existing is not None:
                # Same-name version replace: the old version's
                # executables must never answer the new version's
                # requests (the in-memory cache keys by NAME).
                self._purge_model_cache(reg.name)
            with self._models_lock:
                self._models[reg.name] = bundle
            self._extend_warm_target(reg.name)
            log.info("model %s registered%s", reg.coord,
                     " (replacing a live version)" if existing else "")
            if prewarm:
                for hw in self.serve_cfg.warmup_shapes:
                    self.prewarm(hw, models=[reg.name])
        if set_default:
            self.set_default_model(reg.name)
        return {"model": reg.name, "version": reg.version,
                "registered": bool(fresh),
                "default": self.default_model,
                "ready": self.ready}

    def set_default_model(self, name: Optional[str]) -> Optional[str]:
        """Atomically flip the default-model pointer (what unnamed
        requests run); None restores the implicit constructor model.
        The flip is the LAST step of a rollout — ``register_model``
        prewarms before it, so the first post-flip request hits warm
        executables."""
        with self._models_lock:
            if name is not None:
                b = self._models.get(name)
                if b is None or b.retiring:
                    raise ModelUnknown(
                        name, [n for n, bb in self._models.items()
                               if n is not None and not bb.retiring])
            previous, self.default_model = self.default_model, name
        log.info("default model: %s -> %s", previous, name)
        return name

    def retire_model(self, name: str, timeout: float = 30.0
                     ) -> Dict[str, object]:
        """Retire a registered model from this live engine: latch it
        retiring (new requests get the typed 404), DRAIN its in-flight
        admissions, then evict the pytree and purge its executables.
        Refuses the current default (RuntimeError — flip the pointer
        first; HTTP 409) and raises ``TimeoutError`` (retiring latch
        released) if in-flight work does not drain in ``timeout``."""
        with self._models_lock:
            bundle = self._models.get(name) if name is not None else None
            if bundle is None:
                raise ModelUnknown(
                    name, [n for n in self._models if n is not None])
            if self.default_model == name:
                raise RuntimeError(
                    f"model {name!r} is the default — set_default_model "
                    f"to another version before retiring it")
            bundle.retiring = True
        deadline = time.monotonic() + max(0.0, timeout)
        while self._model_pending_count(name) > 0:
            if time.monotonic() > deadline:
                with self._models_lock:
                    bundle.retiring = False
                raise TimeoutError(
                    f"model {name!r}: {self._model_pending_count(name)} "
                    f"admission(s) still in flight after {timeout}s — "
                    f"retirement rolled back")
            time.sleep(0.005)
        with self._models_lock:
            self._models.pop(name, None)
        self._purge_model_cache(name, drop_target=True)
        with self._pending_lock:
            self._model_pending.pop(name, None)
        log.info("model %s retired (drained, pytree evicted)",
                 bundle.coord)
        return {"model": name, "version": bundle.version,
                "retired": True}

    def models_status(self) -> Dict[str, object]:
        """The registry's JSON line (/healthz, /admin/models GET):
        registered versions, the default pointer, per-model in-flight
        admissions."""
        with self._models_lock:
            registered = [
                {"name": b.name, "version": b.version,
                 "coord": b.coord, "retiring": b.retiring}
                for n, b in sorted(self._models.items(),
                                   key=lambda kv: kv[0] or "")
                if n is not None]
        with self._pending_lock:
            pending = {(k if k is not None else "(implicit)"): v
                       for k, v in self._model_pending.items() if v > 0}
        return {"default": self.default_model,
                "registered": registered, "pending": pending}

    # -------------------------------------------------------------- xl tier
    def _xl_model_config(self, spec: Dict[str, int]) -> RaftStereoConfig:
        """The model config xl bucket executables compile: the engine's
        effective config with the mesh sharding knobs swapped in.
        Raises typed ``ValueError`` at BOOT for architecture/mesh
        combinations the sharded executors do not support — a
        misdeclared xl tier must fail loudly at construction, not at the
        first megapixel request."""
        base = self.effective_config
        rows, corr = spec["rows"], spec["corr"]
        if corr > 1 and base.corr_backend == "alt":
            raise ValueError(
                "xl_mesh corr sharding shards the 'reg' correlation "
                "volume and is incompatible with corr_backend='alt' "
                "(which builds no volume) — use 'reg'/'reg_fused' or a "
                "rows-only mesh")
        if rows > 1:
            from raft_stereo_tpu.models.banded import banded_supported
            norms = (base.context_norm,) + (
                () if base.shared_backbone else (base.fnet_norm,))
            for norm in norms:
                if not banded_supported(norm, base.n_downsample):
                    raise ValueError(
                        f"xl_mesh rows sharding is unsupported for this "
                        f"architecture: norm {norm!r} with n_downsample="
                        f"{base.n_downsample} (parallel/rows_sharded.py "
                        f"supports the published n_downsample=2 trunks)")
        # Fixed-depth, full-precision, unbanded: the sharded executors
        # run their own paths and the early-exit / int8 / banded knobs
        # do not compose with them (config.py validation); rows_gru
        # (full-loop context parallelism) needs the volume unsharded,
        # so a combined rows x corr mesh shards encoders + volume and
        # leaves the GRU loop replicated (the MULTICHIP_r05 dryrun
        # topology).
        return dataclasses.replace(
            base, rows_shards=rows, corr_w2_shards=corr,
            rows_gru=(rows > 1 and corr == 1), banded_encoder=False,
            exit_threshold_px=0.0, exit_max_iters=None,
            quant="off", quant_corr_scales=None)

    def _init_xl(self, variables) -> None:
        """Build the xl tier: parse the mesh spec, carve device groups
        from the stable local-device order (after the solo workers),
        and replicate the variables onto each group's mesh.  A replica
        whose devices cannot supply the mesh logs the typed skip line
        and serves WITHOUT the tier (xl-routed requests fall through to
        tiling / solo dispatch) — fleet replicas are allowed to be
        heterogeneous."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from raft_stereo_tpu.parallel.distributed import device_groups
        from raft_stereo_tpu.parallel.mesh import (make_mesh,
                                                   mesh_spec_label,
                                                   parse_mesh_spec)

        serve_cfg = self.serve_cfg
        spec = parse_mesh_spec(serve_cfg.xl_mesh)
        size = spec["rows"] * spec["corr"]
        xl_cfg = self._xl_model_config(spec)   # raises typed on bad combos
        groups_devs = device_groups(size, serve_cfg.xl_workers,
                                    skip=len(self.devices))
        if not groups_devs:
            # Not enough devices past the solo workers: overlap with them
            # rather than refuse — dispatches contend on the shared
            # devices but stay correct (the CPU test backend and small
            # dev hosts hit this; production sizes data_parallel +
            # xl_workers*size <= local devices).
            groups_devs = device_groups(size, serve_cfg.xl_workers)
            if groups_devs:
                log.warning(
                    "xl_mesh=%s: not enough devices after the %d solo "
                    "worker(s) — xl group(s) share their devices "
                    "(dispatches contend; size data_parallel + "
                    "xl_workers*%d <= %d local devices to avoid this)",
                    serve_cfg.xl_mesh, len(self.devices), size,
                    len(jax.local_devices()))
        if not groups_devs:
            log.warning(
                "xl_mesh=%s skipped: this replica has %d local "
                "device(s) but the mesh needs %d x %d worker group(s) "
                "— serving WITHOUT the xl tier (big requests fall back "
                "to tiling / solo dispatch)", serve_cfg.xl_mesh,
                len(jax.local_devices()), size, serve_cfg.xl_workers)
            return
        label = mesh_spec_label(spec)
        model = (self.model if xl_cfg == self.effective_config
                 else RAFTStereo(xl_cfg))
        groups = []
        for devs in groups_devs:
            mesh = make_mesh(n_data=1, n_corr=spec["corr"],
                             n_rows=spec["rows"], devices=devs)
            repl = NamedSharding(mesh, P())
            groups.append(_XlGroup(
                devices=tuple(devs), mesh=mesh,
                variables=jax.device_put(self._host_variables, repl),
                sharding=repl))
        self.xl = _XlTier(spec=spec, label=label, size=size, model=model,
                          groups=groups)
        self._xl_sizes = tuple(sorted(set(
            int(s) for s in serve_cfg.xl_batch_sizes)))
        log.info("xl tier up: mesh %s (%s), %d group(s) of %d device(s), "
                 "routing buckets > %d px (and ?tier=xl)",
                 serve_cfg.xl_mesh, label, len(groups), size,
                 serve_cfg.xl_threshold_pixels)

    @property
    def xl_enabled(self) -> bool:
        return self.xl is not None

    def _worker_count(self) -> int:
        return len(self.devices) + (len(self.xl.groups)
                                    if self.xl is not None else 0)

    def _is_xl_worker(self, widx: int) -> bool:
        return widx >= len(self.devices)

    def _xl_group(self, widx: int) -> _XlGroup:
        return self.xl.groups[widx - len(self.devices)]

    def _xl_worker_indices(self) -> List[int]:
        if self.xl is None:
            return []
        return list(range(len(self.devices),
                          len(self.devices) + len(self.xl.groups)))

    def _xl_compatible(self, bucket: Tuple[int, int]
                       ) -> Tuple[bool, str]:
        """Whether this padded bucket satisfies the xl mesh's geometry
        (trunk row divisibility, rows_gru window constraints).  The /32
        pad guarantees most production shapes pass; the ones that don't
        fall through to tiling with the reason logged."""
        if self.xl is None:
            return False, "no xl mesh on this engine"
        cfg = self.xl.model.config
        rows = cfg.rows_shards
        h = int(bucket[0])
        if rows > 1:
            if h % (4 * rows):
                return False, (f"padded H={h} not divisible by 4*rows="
                               f"{4 * rows} (stride-2 trunk stages)")
            from raft_stereo_tpu.parallel.rows_sharded import DEFAULT_HALO
            if h // rows < DEFAULT_HALO:
                return False, (f"per-shard rows H/rows={h // rows} < "
                               f"trunk halo {DEFAULT_HALO}")
            if cfg.rows_gru:
                from raft_stereo_tpu.parallel.rows_gru import \
                    validate_rows_gru
                try:
                    validate_rows_gru(cfg, h // cfg.downsample_factor,
                                      rows)
                except ValueError as e:
                    return False, str(e)
        return True, ""

    def _xl_routes(self, bucket: Tuple[int, int]) -> bool:
        """Whether a stateless request at this bucket routes to the xl
        family automatically (the prewarm/readiness surface uses the
        same predicate, so the warm target matches real routing)."""
        px = bucket[0] * bucket[1]
        return (self.xl is not None
                and px > self.serve_cfg.xl_threshold_pixels
                and (self.serve_cfg.xl_max_pixels is None
                     or px <= self.serve_cfg.xl_max_pixels)
                and self._xl_compatible(bucket)[0])

    def xl_status(self) -> Optional[Dict[str, object]]:
        """One JSON-able line for /healthz: the tier's topology and
        routing threshold, or None when this engine serves without it."""
        if self.xl is None:
            return None
        return {"mesh": self.serve_cfg.xl_mesh, "label": self.xl.label,
                "groups": len(self.xl.groups),
                "devices_per_group": self.xl.size,
                "threshold_pixels": self.serve_cfg.xl_threshold_pixels,
                "batch_sizes": list(self._xl_sizes)}

    # ----------------------------------------------------------- back-compat
    @property
    def batcher(self) -> BucketQueue:
        """Round-6 name for the request queue (healthz / CLI used
        ``service.batcher.depth``)."""
        return self.queue

    def quality_status(self) -> Optional[Dict[str, object]]:
        """Online quality posture (``GET /quality``): rolling per-tier
        mean confidence, good/bad totals vs the floor, drift-watchdog
        state, the quality SLO burn, and — with the cascade on — the
        draft/escalation split.  None when confidence telemetry is off
        (the endpoint 404s, keeping the off wire surface unchanged)."""
        if self.quality is None:
            return None
        out = self.quality.status()
        if self._cascade_draft is not None:
            out["cascade"] = {
                "draft": self._cascade_draft,
                "escalate": self._cascade_escalate,
                "threshold": self.serve_cfg.cascade_threshold,
                "drafts": self._cascade_drafts.value,
                "escalated": self._cascade_escalations.value,
            }
        return out

    # ------------------------------------------------------------ front door
    def bucket_for(self, shape: Tuple[int, int, int]) -> Tuple[int, int]:
        """The padded (Hp, Wp) this image shape dispatches at."""
        return self.policy.bucket_for(shape[0], shape[1])[:2]

    def _dispatch_latency_estimate(self, group_key: Tuple,
                                   batch_size: int) -> Optional[float]:
        """The measured per-dispatch wall (device + fetch EWMA) of one
        queue group — the EDF scheduler's slack subtrahend.  None before
        the group's first dispatch."""
        with self._latency_lock:
            return self._dispatch_latency_s.get(group_key)

    def _note_dispatch_latency(self, group_key: Tuple,
                               seconds: float) -> None:
        with self._latency_lock:
            prev = self._dispatch_latency_s.get(group_key)
            self._dispatch_latency_s[group_key] = (
                seconds if prev is None else 0.7 * prev + 0.3 * seconds)

    def resolve_tier(self, tier: Optional[str]) -> Optional[str]:
        """The tier a request actually runs at: the named one (validated),
        or the default tier when tiers are configured, or None (the base
        fixed-depth path) when they are not."""
        if tier is None:
            return self.default_tier
        if tier not in self.tiers:
            raise ValueError(
                f"unknown tier {tier!r}: this engine serves "
                f"{sorted(self.tiers) or '(no tiers configured)'}")
        return tier

    def submit(self, left: np.ndarray, right: np.ndarray,
               deadline_ms: Optional[float] = None,
               tier: Optional[str] = None,
               degradable: bool = True,
               model: Optional[str] = None,
               trace_context=None) -> Future:
        """Admit one stereo pair; returns a Future of ``ServeResult``.

        ``tier`` selects a configured latency tier (``ServeConfig.tiers``)
        — requests of different tiers run different compiled programs and
        never share a dispatch; None runs the default tier (or the base
        fixed-depth path when no tiers are configured).  Raises
        ``Overloaded`` at the door when the queue is full or the engine is
        draining; the Future fails with ``DeadlineExceeded`` if the
        request's deadline passes before a device picks it up, or with
        ``RequestPoisoned`` if its dispatch crashes on every bounded
        retry.  Under active brownout (``ServeConfig.brownout``) an
        eligible request is rerouted down the tier ladder —
        ``degradable=False`` opts this request out (the HTTP layer maps
        the X-No-Degrade header here), and ``brownout_exempt_tiers``
        opts a whole tier out; a degraded result carries
        ``requested_tier`` / ``degraded``.

        Big-image routing (round 17): with an xl tier configured
        (``ServeConfig.xl_mesh``), a request whose padded bucket exceeds
        ``xl_threshold_pixels`` — or that names ``tier="xl"`` explicitly
        — dispatches ONE mesh-sharded executable on an xl device group
        (result ``tier`` reads "xl", ``mesh`` carries the topology
        label).  Past ``tile_threshold_pixels`` (or when the bucket does
        not fit the mesh geometry) the request is answered by
        halo-overlap tiling instead: equal-height row tiles ride the
        ordinary batcher and the stitched result carries ``tiles`` /
        ``seam_epe``.  Naming ``tier="xl"`` without an xl tier, or for
        a mesh-incompatible bucket, raises ``ValueError`` (HTTP 400).

        ``model`` (round 21) selects a REGISTERED model version
        (``?model=`` / X-Model); None runs the default-model pointer
        (the implicit constructor model unless a hot swap flipped it).
        Unknown/retiring names raise the typed ``ModelUnknown``
        (HTTP 404).  Requests of different models never share a
        dispatch (the queue groups by model) and named models never
        route to the xl mesh (its replicated weights are the implicit
        model's).

        ``tier="auto"`` (round 24) is the confidence-gated cascade
        pseudo-tier (requires ``ServeConfig.cascade``): the request runs
        on the cheap draft tier first and re-runs on the quality tier
        ONLY when the draft's mean confidence falls below
        ``cascade_threshold``.  The result's ``tier`` is whichever tier
        produced the answer, with ``escalated`` / ``draft_tier`` /
        ``draft_confidence`` provenance; beyond the tiling threshold
        the gate applies per halo tile.

        ``trace_context`` (round 23) is an upstream ``TraceContext``
        decoded from an inbound ``traceparent`` header: the request's
        ``serve.request`` span ADOPTS that trace id and parents to the
        caller's span (the fleet router's ``route.forward``), bypassing
        this engine's local sample rate — the upstream sampling decision
        already happened.  None (the default) keeps the local-sampling
        behavior byte-for-byte.
        """
        t_admit = time.perf_counter()
        model = self.resolve_model(model)
        left, right = np.asarray(left), np.asarray(right)
        if left.ndim != 3 or left.shape != right.shape:
            raise ValueError(
                f"need two same-shape (H, W, 3) images, got {left.shape} "
                f"vs {right.shape}")
        bucket = self.policy.bucket_for(left.shape[0], left.shape[1])[:2]
        if tier == "auto":
            # Confidence-gated cascade (round 24): draft cheap, escalate
            # only low-confidence answers.  A pseudo-tier like "xl" —
            # resolved here, never a queue coordinate of its own.
            if self._cascade_draft is None:
                raise ValueError(
                    "tier 'auto' requested but this engine has no "
                    "cascade (configure ServeConfig.cascade / --cascade "
                    "with confidence telemetry on)")
            return self._submit_cascade(left, right, deadline_ms,
                                        degradable, t_admit, model,
                                        trace_context=trace_context)
        want_xl = tier == "xl"
        if want_xl and self.xl is None:
            raise ValueError(
                "tier 'xl' requested but this engine has no xl tier "
                "(configure ServeConfig.xl_mesh / --xl_mesh, and enough "
                "devices for the mesh)")
        if want_xl and model is not None:
            raise ValueError(
                f"tier 'xl' serves only the implicit constructor model "
                f"(the mesh groups replicate its weights); model "
                f"{model!r} cannot ride it")
        if (model is None and self.xl is not None
                and (want_xl or self._xl_routes(bucket))):
            ok, reason = self._xl_compatible(bucket)
            if ok:
                # Fixed-depth full-precision program: no tier ladder, no
                # brownout rung below it — the request IS the expensive
                # kind brownout protects the rest of the fleet from.
                return self._enqueue(left, right, deadline_ms, None,
                                     None, t_admit,
                                     family=FAMILY_XL,
                                     trace_context=trace_context).future
            if want_xl:
                raise ValueError(
                    f"tier 'xl': bucket {bucket[0]}x{bucket[1]} does "
                    f"not fit mesh {self.serve_cfg.xl_mesh}: {reason}")
            log.info("bucket %sx%s exceeds xl_threshold_pixels but does "
                     "not fit mesh %s (%s) — falling through to "
                     "tiling/solo dispatch", bucket[0], bucket[1],
                     self.serve_cfg.xl_mesh, reason)
        tier, requested_tier = self._admit_tier(tier, degradable)
        tt = self.serve_cfg.tile_threshold_pixels
        if tt is not None and bucket[0] * bucket[1] > tt:
            return self._submit_tiled(left, right, deadline_ms, tier,
                                      requested_tier, t_admit, model,
                                      trace_context=trace_context)
        return self._enqueue(left, right, deadline_ms, tier,
                             requested_tier, t_admit,
                             model=model,
                             trace_context=trace_context).future

    def _admit_tier(self, tier: Optional[str], degradable: bool
                    ) -> Tuple[Optional[str], Optional[str]]:
        """Resolve the requested tier and apply brownout degradation:
        ``(effective_tier, requested_tier_if_degraded)``."""
        tier = self.resolve_tier(tier)
        requested_tier = None
        if (self.brownout is not None and degradable
                and tier not in self.serve_cfg.brownout_exempt_tiers):
            # Victim selection (round 24): the tier's recent rolling
            # mean confidence, when tracked, spares already-struggling
            # streams from degradation (resilience.degrade).  None
            # (confidence off) keeps the unconditional ladder.
            conf = (self.quality.mean_confidence(tier)
                    if self.quality is not None else None)
            effective = self.brownout.degrade(tier, confidence=conf)
            if effective != tier:
                requested_tier, tier = tier, effective
        return tier, requested_tier

    def _enqueue(self, left: np.ndarray, right: np.ndarray,
                 deadline_ms: Optional[float], tier: Optional[str],
                 requested_tier: Optional[str], t_admit: float,
                 family: Optional[str] = FAMILY_BASE,
                 session=None, session_id: Optional[str] = None,
                 flow_init: Optional[np.ndarray] = None,
                 thumb: Optional[np.ndarray] = None,
                 frame_index: Optional[int] = None,
                 scene_cut: bool = False,
                 frame_delta_v: Optional[float] = None,
                 ctx_init=None, hidden_init=None,
                 model: Optional[str] = None,
                 trace_context=None) -> Request:
        """Pad, build, trace, and queue one request — shared by the
        stateless ``submit`` (base family, no session fields) and the
        streaming ``submit_session``.  ``model`` is the RESOLVED
        registered-model name (None = implicit) — it joins the queue
        group key, so models never share a dispatch."""
        hp, wp, grid = self.policy.bucket_for(left.shape[0], left.shape[1])
        padder = InputPadder((1,) + left.shape, divis_by=grid)
        l, r, t, b = padder.pads
        spec = ((t, b), (l, r), (0, 0))
        payload = _Payload(left=np.pad(left, spec, mode="edge"),
                           right=np.pad(right, spec, mode="edge"),
                           padder=padder, flow_init=flow_init,
                           hidden_init=hidden_init,
                           session=session, thumb=thumb,
                           raw_shape=tuple(left.shape[:2]),
                           frame_index=frame_index, scene_cut=scene_cut,
                           frame_delta=frame_delta_v, ctx_init=ctx_init)
        now = time.monotonic()
        deadline_ms = (deadline_ms if deadline_ms is not None
                       else self.serve_cfg.default_deadline_ms)
        req = Request(bucket=(hp, wp), payload=payload,
                      future=Future(), t_enqueue=now, tier=tier,
                      requested_tier=requested_tier,
                      family=family, session_id=session_id,
                      model=model,
                      deadline=(None if deadline_ms is None
                                else now + deadline_ms / 1e3))
        # Per-model in-flight accounting (retire_model's drain signal):
        # incremented before the queue sees the request, decremented by
        # the future resolving — admission-to-resolution coverage, so a
        # retiring model's pytree is never evicted under a live
        # dispatch.  The Overloaded path below decrements explicitly
        # (a refused request's future never resolves).
        self._note_pending(model, +1)
        req.future.add_done_callback(
            lambda f, m=model: self._note_pending(m, -1))
        # Sampled request: root span + admission (validate/pad) span; the
        # queue span opens here and closes at worker pickup (_run_chunk)
        # or in the done-callback for requests dropped in the queue.  An
        # upstream trace context (the router's traceparent) ADOPTS the
        # caller's trace id — serve.request parents to the router's
        # route.forward span and the local sample rate is bypassed (the
        # sampling decision already happened one hop up).
        trace_attrs = dict(
            bucket=str(req.bucket), deadline_ms=deadline_ms,
            **({"tier": tier} if tier is not None else {}),
            **({"session": session_id} if session_id is not None else {}))
        if trace_context is not None:
            trace = self.tracer.adopt_trace(trace_context,
                                            "serve.request",
                                            **trace_attrs)
        else:
            trace = self.tracer.start_trace("serve.request",
                                            **trace_attrs)
        if trace is not None:
            req.trace = trace
            self.tracer.add_span("serve.admission", trace,
                                 t_admit, time.perf_counter(),
                                 bucket=str(req.bucket))
            req.queue_span = self.tracer.start_span("serve.queue", trace)
            req.future.add_done_callback(
                lambda f, r=req: self._finish_request_trace(r, f))
        try:
            self.queue.submit(req)     # raises Overloaded at the door
        except Overloaded:
            self._note_pending(model, -1)   # refused: future never resolves
            if trace is not None and trace.root is not None:
                trace.root.set_attr("status", "overloaded")
                self._finish_request_trace(req, None)
            raise
        if requested_tier is not None:
            self.metrics.degraded.inc()
            if trace is not None and trace.root is not None:
                trace.root.set_attr("degraded_from", requested_tier)
        return req

    def _finish_request_trace(self, req: Request, future) -> None:
        """Close the queue span (if no worker picked the request up) and
        the root span; idempotence guards the two close paths (worker
        pickup vs future resolution)."""
        qs = req.queue_span
        if qs is not None and qs.t_end is None:
            self.tracer.finish(qs)
        root = req.trace.root if req.trace is not None else None
        if root is not None and root.t_end is None:
            if future is not None:
                exc = future.exception()
                root.set_attr("status",
                              "ok" if exc is None else type(exc).__name__)
            self.tracer.finish(root)

    def infer(self, left: np.ndarray, right: np.ndarray,
              deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None,
              tier: Optional[str] = None,
              degradable: bool = True,
              model: Optional[str] = None,
              trace_context=None) -> ServeResult:
        """Blocking convenience: submit + wait (the in-process client)."""
        return self.submit(left, right, deadline_ms, tier=tier,
                           degradable=degradable, model=model,
                           trace_context=trace_context
                           ).result(timeout=timeout)

    # ------------------------------------------------------ tiled dispatch
    def _submit_tiled(self, left: np.ndarray, right: np.ndarray,
                      deadline_ms: Optional[float], tier: Optional[str],
                      requested_tier: Optional[str],
                      t_admit: float,
                      model: Optional[str] = None,
                      trace_context=None) -> Future:
        """Answer one beyond-threshold pair as N halo-overlap row tiles
        through the ORDINARY bucket path (serving/tiles.py): every tile
        is an equal-height `_enqueue` at the same bucket/tier/family, so
        the continuous batcher coalesces them into batch-N dispatches —
        no new scheduler.  The returned Future resolves once every tile
        did, with the center-crop-stitched disparity and the measured
        seam error.  A tile failing (deadline, poisoning, shutdown)
        fails the whole request with that tile's typed error.  An
        ``Overloaded`` mid-tiling propagates to the caller; tiles
        admitted before the bound hit still run and are discarded (their
        futures resolve into a dead aggregate) — admission stays a
        single bounded door, unreserved."""
        from raft_stereo_tpu.serving import tiles as tiles_mod

        specs = tiles_mod.plan_tiles(left.shape[0],
                                     self.serve_cfg.tile_rows,
                                     self.serve_cfg.tile_halo)
        if len(specs) < 2:
            # Shorter than one tile extent: nothing to split.
            return self._enqueue(left, right, deadline_ms, tier,
                                 requested_tier, t_admit,
                                 model=model,
                                 trace_context=trace_context).future
        # Every tile adopts the same upstream context: N serve.request
        # subtrees under one trace id, all parented to the caller's span
        # — the tiled answer reads as one fan-out in the timeline.
        reqs = [self._enqueue(
                    np.ascontiguousarray(left[s.src0:s.src1]),
                    np.ascontiguousarray(right[s.src0:s.src1]),
                    deadline_ms, tier, requested_tier, t_admit,
                    model=model, trace_context=trace_context)
                for s in specs]
        agg: Future = Future()
        state = {"remaining": len(reqs), "done": False}
        lock = threading.Lock()

        def on_done(future):
            # One-shot resolution decided INSIDE the lock: the first
            # failing tile owns the aggregate; later tiles (including
            # other failures) are no-ops.
            action = None
            with lock:
                if state["done"]:
                    return
                if future.exception() is not None:
                    state["done"], action = True, "fail"
                else:
                    state["remaining"] -= 1
                    if state["remaining"] == 0:
                        state["done"], action = True, "finish"
            if action == "fail":
                agg.set_exception(future.exception())
            elif action == "finish":
                try:
                    self._finish_tiled(agg, reqs, specs, tier,
                                       requested_tier, t_admit, model)
                except BaseException as e:  # noqa: BLE001 — typed to caller
                    agg.set_exception(e)

        for req in reqs:
            req.future.add_done_callback(on_done)
        return agg

    def _finish_tiled(self, agg: Future, reqs: List[Request],
                      specs, tier: Optional[str],
                      requested_tier: Optional[str],
                      t_admit: float,
                      model: Optional[str] = None) -> None:
        """All tiles answered: stitch, measure the seam, resolve the
        aggregate.  Latency legs report the worst tile (the tiles ran
        concurrently); ``total_s`` is admission -> stitched."""
        from raft_stereo_tpu.serving import tiles as tiles_mod

        results = [r.future.result() for r in reqs]
        flow = tiles_mod.stitch([res.flow for res in results], specs)
        seam = tiles_mod.seam_epe([res.flow for res in results], specs)
        self.metrics.tiled_requests.inc()
        if seam is not None:
            self.metrics.tile_seam_epe.observe(seam)
        iters = [res.iters_used for res in results
                 if res.iters_used is not None]
        conf_map, conf_mean = self._stitch_confidence(results, specs)
        agg.set_result(ServeResult(
            flow=np.ascontiguousarray(flow),
            queue_wait_s=max(res.queue_wait_s for res in results),
            device_s=max(res.device_s for res in results),
            fetch_s=max(res.fetch_s for res in results),
            total_s=time.perf_counter() - t_admit,
            batch_size=max(res.batch_size for res in results),
            iters_used=max(iters) if iters else None,
            tier=tier, requested_tier=requested_tier,
            attempts=max(res.attempts for res in results),
            tiles=len(reqs), seam_epe=seam,
            model=results[0].model,
            model_version=results[0].model_version,
            confidence=conf_map, confidence_mean=conf_mean,
            trace_id=results[0].trace_id))

    @staticmethod
    def _stitch_confidence(results: List["ServeResult"], specs
                           ) -> Tuple[Optional[np.ndarray],
                                      Optional[float]]:
        """Stitch per-tile confidence maps with the same halo-crop
        geometry as the disparity (confidence and disparity are both
        (H, W) row fields); (None, None) when confidence is off."""
        from raft_stereo_tpu.serving import tiles as tiles_mod

        if any(res.confidence is None for res in results):
            return None, None
        conf = np.ascontiguousarray(tiles_mod.stitch(
            [res.confidence for res in results], specs))
        return conf, float(conf.mean())

    # ------------------------------------------- confidence-gated cascade
    def _submit_cascade(self, left: np.ndarray, right: np.ndarray,
                        deadline_ms: Optional[float], degradable: bool,
                        t_admit: float, model: Optional[str] = None,
                        trace_context=None) -> Future:
        """The ``auto`` pseudo-tier: answer on the cheap draft tier
        first and escalate to the quality tier ONLY when the draft's own
        confidence map says the answer is doubtful.  Well-textured
        frames pay draft cost; the hard ones pay draft + quality — mean
        fleet cost tracks the EASY fraction of traffic instead of the
        worst case.  Beyond the tiling threshold the gate is per tile:
        only the doubtful rows of a large frame re-run at quality.

        The draft runs at the ADMITTED draft tier (brownout may degrade
        it further); escalation re-admits at escalation time so a
        brownout that deepened mid-request still applies."""
        tt = self.serve_cfg.tile_threshold_pixels
        bucket = self.policy.bucket_for(left.shape[0], left.shape[1])[:2]
        if tt is not None and bucket[0] * bucket[1] > tt:
            return self._submit_cascade_tiled(
                left, right, deadline_ms, degradable, t_admit, model,
                trace_context=trace_context)
        return self._cascade_one(left, right, deadline_ms, degradable,
                                 t_admit, model,
                                 trace_context=trace_context)

    def _cascade_one(self, left: np.ndarray, right: np.ndarray,
                     deadline_ms: Optional[float], degradable: bool,
                     t_admit: float, model: Optional[str] = None,
                     trace_context=None) -> Future:
        """One draft -> (maybe) escalate chain for a single pair; the
        returned Future resolves with whichever answer survived, carrying
        full provenance (``draft_tier``, ``draft_confidence``,
        ``escalated``)."""
        draft = self._cascade_draft
        threshold = self.serve_cfg.cascade_threshold
        agg: Future = Future()
        draft_tier, draft_requested = self._admit_tier(draft, degradable)
        dreq = self._enqueue(left, right, deadline_ms, draft_tier,
                             draft_requested, t_admit, model=model,
                             trace_context=trace_context)

        def on_draft(future):
            exc = future.exception()
            if exc is not None:
                agg.set_exception(exc)
                return
            res = future.result()
            conf = res.confidence_mean
            if conf is None or conf >= threshold:
                # Confident (or confidence unavailable — fail open to
                # the draft rather than double every request's cost).
                res.draft_tier = draft_tier
                res.draft_confidence = conf
                res.total_s = time.perf_counter() - t_admit
                if self._cascade_drafts is not None:
                    self._cascade_drafts.inc()
                agg.set_result(res)
                return
            if self._cascade_escalations is not None:
                self._cascade_escalations.inc()
            try:
                esc_tier, esc_requested = self._admit_tier(
                    self._cascade_escalate, degradable)
                ereq = self._enqueue(left, right, deadline_ms, esc_tier,
                                     esc_requested, t_admit, model=model,
                                     trace_context=trace_context)
            except BaseException as e:  # noqa: BLE001 — typed to caller
                agg.set_exception(e)
                return

            def on_escalated(f2):
                exc2 = f2.exception()
                if exc2 is not None:
                    agg.set_exception(exc2)
                    return
                res2 = f2.result()
                res2.escalated = True
                res2.draft_tier = draft_tier
                res2.draft_confidence = conf
                res2.total_s = time.perf_counter() - t_admit
                agg.set_result(res2)

            ereq.future.add_done_callback(on_escalated)

        dreq.future.add_done_callback(on_draft)
        return agg

    def _submit_cascade_tiled(self, left: np.ndarray, right: np.ndarray,
                              deadline_ms: Optional[float],
                              degradable: bool, t_admit: float,
                              model: Optional[str] = None,
                              trace_context=None) -> Future:
        """Per-tile cascade for beyond-threshold pairs: every halo tile
        runs its own draft -> escalate chain (``_cascade_one``), so only
        the low-confidence ROWS of a large frame pay quality-tier cost.
        Stitching and seam measurement mirror ``_finish_tiled``."""
        from raft_stereo_tpu.serving import tiles as tiles_mod

        specs = tiles_mod.plan_tiles(left.shape[0],
                                     self.serve_cfg.tile_rows,
                                     self.serve_cfg.tile_halo)
        if len(specs) < 2:
            return self._cascade_one(left, right, deadline_ms,
                                     degradable, t_admit, model,
                                     trace_context=trace_context)
        futs = [self._cascade_one(
                    np.ascontiguousarray(left[s.src0:s.src1]),
                    np.ascontiguousarray(right[s.src0:s.src1]),
                    deadline_ms, degradable, t_admit, model,
                    trace_context=trace_context)
                for s in specs]
        agg: Future = Future()
        state = {"remaining": len(futs), "done": False}
        lock = threading.Lock()

        def on_done(future):
            action = None
            with lock:
                if state["done"]:
                    return
                if future.exception() is not None:
                    state["done"], action = True, "fail"
                else:
                    state["remaining"] -= 1
                    if state["remaining"] == 0:
                        state["done"], action = True, "finish"
            if action == "fail":
                agg.set_exception(future.exception())
            elif action == "finish":
                try:
                    self._finish_cascade_tiled(agg, futs, specs, t_admit)
                except BaseException as e:  # noqa: BLE001 — typed to caller
                    agg.set_exception(e)

        for fut in futs:
            fut.add_done_callback(on_done)
        return agg

    def _finish_cascade_tiled(self, agg: Future, futs: List[Future],
                              specs, t_admit: float) -> None:
        """All per-tile cascades answered: stitch (disparity AND
        confidence), report the ESCALATED tier when any tile escalated
        (the cost actually paid), keep per-tile draft provenance in the
        aggregate's ``draft_confidence`` (worst tile — the gate that
        mattered)."""
        from raft_stereo_tpu.serving import tiles as tiles_mod

        results = [f.result() for f in futs]
        flow = tiles_mod.stitch([res.flow for res in results], specs)
        seam = tiles_mod.seam_epe([res.flow for res in results], specs)
        self.metrics.tiled_requests.inc()
        if seam is not None:
            self.metrics.tile_seam_epe.observe(seam)
        iters = [res.iters_used for res in results
                 if res.iters_used is not None]
        conf_map, conf_mean = self._stitch_confidence(results, specs)
        escalated = any(res.escalated for res in results)
        final = next((res for res in results if res.escalated),
                     results[0])
        draft_confs = [res.draft_confidence for res in results
                       if res.draft_confidence is not None]
        agg.set_result(ServeResult(
            flow=np.ascontiguousarray(flow),
            queue_wait_s=max(res.queue_wait_s for res in results),
            device_s=max(res.device_s for res in results),
            fetch_s=max(res.fetch_s for res in results),
            total_s=time.perf_counter() - t_admit,
            batch_size=max(res.batch_size for res in results),
            iters_used=max(iters) if iters else None,
            tier=final.tier, requested_tier=final.requested_tier,
            attempts=max(res.attempts for res in results),
            tiles=len(futs), seam_epe=seam,
            model=results[0].model,
            model_version=results[0].model_version,
            confidence=conf_map, confidence_mean=conf_mean,
            escalated=escalated,
            draft_tier=results[0].draft_tier,
            draft_confidence=min(draft_confs) if draft_confs else None,
            trace_id=results[0].trace_id))

    # ---------------------------------------------------- streaming sessions
    def submit_session(self, session_id: str, left: np.ndarray,
                       right: np.ndarray,
                       deadline_ms: Optional[float] = None,
                       tier: Optional[str] = None,
                       degradable: bool = True,
                       handoff_key: Optional[str] = None,
                       model: Optional[str] = None,
                       trace_context=None) -> Future:
        """Admit one frame of a streaming session (the engine behind
        ``POST /v1/stream/<session>``).  Returns a Future of
        ``ServeResult`` whose session fields say what happened:
        ``warm`` (the GRU was seeded from the previous frame's
        disparity), ``scene_cut`` (the inter-frame delta check failed and
        the frame cold-started), ``frame_index``, ``frame_delta``.

        First frame of a new id creates the session and cold-starts;
        every subsequent frame warm-starts unless the resolution changed,
        the previous frame failed, or the scene-cut gate fired.  Raises
        the typed ``SessionExpired`` (HTTP 410) on a TTL-expired /
        LRU-evicted / closed id and ``SessionsDisabled`` when the engine
        has no session store.

        **Ordering:** the session's ordering lock is held from here until
        the frame's future resolves, so a session never has two frames
        in flight and a dispatch cycle can never reorder its frames —
        the call blocks while the previous frame of the SAME session is
        still pending (distinct sessions proceed concurrently and batch
        together freely).  Every admitted frame terminates (success or
        typed error; round-13 guarantee), so the lock cannot be held
        forever.

        **Model pinning (round 21):** a session PINS the model its
        first frame resolved (the explicit ``model`` or the
        then-current default) — later frames run that model even if a
        hot swap flips the default mid-stream, so no session ever
        receives frames from two different versions.  A later frame
        naming a DIFFERENT model than the pin raises ``ValueError``
        (HTTP 400); a frame whose pinned model was retired raises the
        typed ``ModelUnknown`` (404 — open a fresh session)."""
        if self.sessions is None:
            raise SessionsDisabled(
                "this engine runs without a session store — construct it "
                "with ServeConfig(sessions=True) to stream")
        t_admit = time.perf_counter()
        tier, requested_tier = self._admit_tier(tier, degradable)
        left, right = np.asarray(left), np.asarray(right)
        if left.ndim != 3 or left.shape != right.shape:
            raise ValueError(
                f"need two same-shape (H, W, 3) images, got {left.shape} "
                f"vs {right.shape}")
        sess, created = self.sessions.get_or_create(session_id)
        # One frame per session in the pipeline: block until the previous
        # frame's future resolved (its done-callback releases the lock).
        sess.order_lock.acquire()
        try:
            if created and handoff_key is not None:
                # Lazy handoff adoption (round 18): the router tagged
                # this id's first frame here with the draining replica's
                # published blob — import THAT session's state so this
                # frame warm-starts exactly where the old replica left
                # off.  Any failure (missing blob, corrupt entry,
                # unregistered pinned model) just leaves ``created``
                # true: the frame cold-starts, which is the pre-handoff
                # baseline.
                created = not self._adopt_handoff(sess, session_id,
                                                  handoff_key)
            if created:
                # Pin the model at session birth: the explicit name or
                # the CURRENT default — frames of this stream run it
                # for the session's whole life, hot swaps
                # notwithstanding.
                sess.model = self.resolve_model(model)
            else:
                pinned = sess.model
                if model is not None and model != pinned:
                    raise ValueError(
                        f"session {session_id!r} is pinned to model "
                        f"{pinned or '(implicit)'} — a mid-stream "
                        f"switch to {model!r} would mix versions; open "
                        f"a new session")
                if pinned is not None:
                    # Retired mid-stream -> typed 404 on the next frame.
                    self.resolve_model(pinned)
            req_model = sess.model
            thumb = frame_thumbnail(left)
            hp, wp, _grid = self.policy.bucket_for(left.shape[0],
                                                   left.shape[1])
            hidden_on = self.serve_cfg.session_hidden
            warm = (not created and sess.flow_low is not None
                    and sess.bucket == (hp, wp)
                    and sess.raw_shape == tuple(left.shape[:2])
                    # warm-h programs consume BOTH state halves: a
                    # session missing its hidden tree (dropped at
                    # export, invalidated by a crash) cold-starts
                    # rather than feeding the warm-h executable a
                    # fabricated trajectory.
                    and (not hidden_on or sess.hidden is not None))
            scene_cut = False
            delta = None
            if warm:
                delta = frame_delta(thumb, sess.thumb)
                if delta is not None:
                    self.metrics.frame_delta.observe(delta)
                    if (self.serve_cfg.scene_cut_threshold > 0
                            and delta > self.serve_cfg.scene_cut_threshold):
                        # The previous disparity field belongs to a scene
                        # this frame is not in: a warm start would anchor
                        # the GRU to garbage, so fall back to cold (the
                        # session survives — state re-seeds from this
                        # frame's result).
                        warm, scene_cut = False, True
                        sess.scene_cuts += 1
                        self.metrics.scene_cuts.inc()
            # Family routing with the ctx cache on: cold frames SAVE the
            # context bundle (state_ctx); a warm frame whose measured
            # delta proves the scene static REUSES it (warm_ctx — the
            # context encoder never runs); a warm frame past the gate
            # runs plain warm AND the bundle is dropped at completion
            # (the scene moved; a stale context is a silent accuracy
            # leak, so it re-establishes at the next cold frame).
            ctx_on = self.serve_cfg.session_ctx_cache
            ctx_init = None
            if warm:
                family = FAMILY_WARM_H if hidden_on else FAMILY_WARM
                if (ctx_on and sess.ctx is not None and delta is not None
                        and delta <= self.serve_cfg.ctx_cache_threshold):
                    family = (FAMILY_WARM_CTX_H if hidden_on
                              else FAMILY_WARM_CTX)
                    ctx_init = sess.ctx
            elif ctx_on:
                family = (FAMILY_STATE_CTX_H if hidden_on
                          else FAMILY_STATE_CTX)
            else:
                family = FAMILY_STATE_H if hidden_on else FAMILY_STATE
            req = self._enqueue(
                left, right, deadline_ms, tier, requested_tier, t_admit,
                family=family,
                session=sess, session_id=session_id,
                flow_init=sess.flow_low if warm else None,
                hidden_init=(sess.hidden if warm and hidden_on
                             else None),
                ctx_init=ctx_init,
                thumb=thumb, frame_index=sess.frame_index,
                scene_cut=scene_cut, frame_delta_v=delta,
                model=req_model, trace_context=trace_context)
        except BaseException:
            sess.order_lock.release()
            raise
        req.future.add_done_callback(
            lambda f, r=req: self._finish_session_frame(r, f))
        return req.future

    def infer_session(self, session_id: str, left: np.ndarray,
                      right: np.ndarray,
                      deadline_ms: Optional[float] = None,
                      timeout: Optional[float] = None,
                      tier: Optional[str] = None,
                      degradable: bool = True,
                      handoff_key: Optional[str] = None,
                      model: Optional[str] = None,
                      trace_context=None) -> ServeResult:
        """Blocking convenience: submit_session + wait."""
        return self.submit_session(
            session_id, left, right, deadline_ms, tier=tier,
            degradable=degradable, handoff_key=handoff_key,
            model=model,
            trace_context=trace_context).result(timeout=timeout)

    # ------------------------------------------------------ session handoff
    def exec_config_fingerprint(self) -> str:
        """SHA-256 identity of the compiled surface a handed-off session
        would re-enter here: the effective model config (architecture,
        precision, quant — array geometry and dtypes of every state
        tree) plus the serving knobs that pick the session executable
        families (``session_hidden`` / ``session_ctx_cache``), the GRU
        depth cap, and the fetch dtype.  Stamped onto every published
        handoff blob; an importer whose fingerprint differs refuses the
        blob TYPED (``serve_handoff_import_skipped_total{reason=
        "config_mismatch"}``) instead of silently installing state its
        programs cannot consume — deliberately coarse: any drift costs
        one cold start per stream, which is the cheap failure."""
        import hashlib

        payload = {
            "model": self.effective_config.to_json(),
            "session_hidden": self.serve_cfg.session_hidden,
            "session_ctx_cache": self.serve_cfg.session_ctx_cache,
            "iters": self.serve_cfg.iters,
            "fetch_dtype": self.serve_cfg.fetch_dtype,
        }
        if self.default_model is not None:
            # The default-model coordinate joins the fingerprint ONLY
            # when a registered model holds the pointer (the implicit
            # default keeps the pre-registry fingerprint byte-stable):
            # a handoff exported under one default version is refused
            # typed-cold by an importer whose default moved — never a
            # wrong-weights warm frame.
            bundle = self._models[self.default_model]
            payload["default_model"] = bundle.coord
        import json as json_mod
        return hashlib.sha256(
            json_mod.dumps(payload, sort_keys=True).encode()).hexdigest()

    def _handoff_records(self, key: str) -> Dict:
        """Parsed ``{sid: (meta, arrays)}`` of one published handoff
        blob, fetched and decoded at most once per key (N inherited
        sessions share one artifact read).  A blob stamped with a
        DIFFERENT exec-config fingerprint than this engine's is refused
        wholesale — every session it carries counts into
        ``serve_handoff_import_skipped_total{reason="config_mismatch"}``
        and cold-starts (the r18 follow-up: mismatch is typed, never a
        silent wrong-geometry import)."""
        with self._handoff_lock:
            cached = self._handoff_blobs.get(key)
        if cached is not None:
            return cached
        records: Dict = {}
        if self.handoff_store is not None:
            blob = self.handoff_store.fetch(key)
            if blob is not None:
                from raft_stereo_tpu.serving.sessions import (
                    handoff_fingerprint, handoff_session_ids)
                stamped = handoff_fingerprint(blob)
                mine = self.exec_config_fingerprint()
                if stamped is not None and stamped != mine:
                    n = len(handoff_session_ids(blob))
                    self.metrics.observe_handoff_skip("config_mismatch",
                                                      n)
                    log.warning(
                        "handoff artifact %s was exported under exec-"
                        "config %.12s but this engine compiles %.12s; "
                        "refusing %d session(s) — they cold-start "
                        "(config_mismatch)", key[:12], stamped, mine, n)
                else:
                    records, skipped = parse_handoff_blob(blob)
                    if skipped:
                        self.metrics.observe_handoff_skip("corrupt",
                                                          skipped)
            else:
                log.warning("handoff artifact %s not in the store; its "
                            "sessions cold-start", key)
        with self._handoff_lock:
            self._handoff_blobs[key] = records
            # A replica inherits from at most a handful of concurrent
            # drains; keep the parse cache from growing across weeks of
            # rolling restarts.
            while len(self._handoff_blobs) > 8:
                self._handoff_blobs.pop(next(iter(self._handoff_blobs)))
        return records

    def _adopt_handoff(self, sess, sid: str, key: str) -> bool:
        """Install the handed-off state for ``sid`` from blob ``key``
        into the freshly created session; True when adopted (the frame
        may warm-start).  A session pinned to a model THIS engine does
        not serve is refused typed (it cold-starts on whatever this
        engine's default is — never a wrong-weights warm frame)."""
        rec = self._handoff_records(key).get(sid)
        if rec is None:
            return False
        meta, arrays = rec
        pinned = meta.get("model") if isinstance(meta, dict) else None
        if pinned is not None:
            bundle = self._models.get(pinned)
            if bundle is None or bundle.retiring:
                self.metrics.observe_handoff_skip("model_unknown", 1)
                log.warning(
                    "session %s was pinned to model %r which this "
                    "engine does not serve — refusing its handed-off "
                    "state (cold start)", sid, pinned)
                return False
        self.sessions.adopt(sess, meta, arrays)
        sess.model = pinned
        self.metrics.sessions_adopted.inc()
        log.info("session %s adopted from handoff %s at frame %s "
                 "(imported warm-start state)", sid, key[:12],
                 sess.frame_index)
        return True

    def publish_handoff(self) -> Optional[Dict[str, object]]:
        """Serialize every live session into the artifact store's
        ``sessions/`` namespace and remember the manifest ``GET
        /admin/handoff`` serves (cli/serve.py calls this at SIGTERM,
        after ``begin_shutdown``).  Returns the manifest — with
        ``artifact=None`` when there was nothing to export (an empty
        manifest is still an ANSWER: the router learns definitively
        that no sessions need remapping).  None only when this engine
        cannot hand off at all (no session store, or no shared artifact
        directory) — the router then falls back to the r16 typed-loss
        path when the process exits."""
        if self.sessions is None or self.handoff_store is None:
            return None
        blob = self.sessions.export(
            config_fingerprint=self.exec_config_fingerprint())
        sids = handoff_session_ids(blob)
        key = None
        if sids:
            key = self.handoff_store.publish(blob)
            if key is None:
                log.warning("session handoff publish failed; %d "
                            "session(s) will fail typed on exit instead",
                            len(sids))
                sids = []
            else:
                self.metrics.sessions_exported.inc(len(sids))
        manifest = {"artifact": key, "sessions": sids,
                    "count": len(sids), "published_unix": time.time(),
                    "config_fingerprint": self.exec_config_fingerprint()}
        self._handoff_manifest = manifest
        log.info("session handoff published: %d session(s) -> %s",
                 len(sids), key and key[:12])
        return manifest

    @property
    def handoff_manifest(self) -> Optional[Dict[str, object]]:
        """The drain handoff manifest (None until ``publish_handoff``
        ran) — what ``GET /admin/handoff`` serves."""
        return self._handoff_manifest

    def note_handoff_fetched(self) -> None:
        """The HTTP layer records that a router fetched the manifest —
        the CLI's post-drain linger can stop waiting."""
        self._handoff_fetched.set()

    def wait_handoff_fetched(self, timeout: float) -> bool:
        return self._handoff_fetched.wait(timeout)

    def close_session(self, session_id: str) -> Dict[str, object]:
        """End one session deliberately (``DELETE /v1/stream/<id>``);
        returns its lifetime stats.  Raises ``SessionsDisabled`` /
        ``SessionExpired`` / ``KeyError`` like the store."""
        if self.sessions is None:
            raise SessionsDisabled("this engine runs without a session "
                                   "store")
        return self.sessions.close(session_id)

    def _finish_session_frame(self, req: Request, future) -> None:
        """Completion hook of one session frame: fold the result's state
        back into the session (under the ordering lock, so the next
        frame — possibly already blocked in ``submit_session`` — reads a
        consistent snapshot), then release the lock.  A failed frame
        releases without touching state: the session's previous state
        stays the warm-start source, and the scene-cut delta check
        guards against it having gone stale."""
        sess = req.payload.session
        try:
            if future.exception() is None:
                res = future.result()
                flow_low = res.flow_low
                reseed = False
                if (self.serve_cfg.session_reseed_on_cap and res.warm
                        and res.iters_used is not None
                        and res.iters_used >= self.serve_cfg.iters
                        and early_exit_enabled(
                            self._models[req.model].tier_models[
                                self._cache_tier(req.tier, req.model)
                            ].config)):
                    # Keyframe guard (ServeConfig.session_reseed_on_cap):
                    # the gate never fired, so this warm output is not a
                    # trusted init — drop the state and let the next
                    # frame cold-start.
                    flow_low = None
                    reseed = True
                    self.metrics.session_reseeds.inc()
                if self.serve_cfg.session_ctx_cache:
                    if res.ctx is not None:
                        # Cold state_ctx frame: (re-)establish the bundle.
                        sess.ctx = res.ctx
                    elif reseed or (res.warm and not res.ctx_cached):
                        # Invalidated: the keyframe guard fired, or a
                        # warm frame ran past the static-scene gate —
                        # either way the cached context no longer
                        # describes the scene; it re-establishes at the
                        # next cold frame.
                        sess.ctx = None
                    if res.ctx_cached:
                        sess.ctx_hits += 1
                        self.metrics.ctx_cache_hits.inc()
                sess.note_result(
                    flow_low=flow_low, thumb=req.payload.thumb,
                    bucket=req.bucket, raw_shape=req.payload.raw_shape,
                    warm=res.warm, iters_used=res.iters_used,
                    # The hidden tree rides (and drops) with the flow
                    # state: the keyframe guard's flow_low=None above
                    # zeroes both halves inside note_result.
                    hidden=res.hidden,
                    confidence=res.confidence_mean)
                self.metrics.observe_session_frame(
                    "warm" if res.warm else "cold")
        finally:
            # The dispatch counts as session activity: a first-frame
            # compile longer than the TTL must not expire the stream.
            self.sessions.touch(req.session_id)
            sess.order_lock.release()

    # ------------------------------------------------------------ readiness
    @property
    def ready(self) -> bool:
        """The /readyz gate: every configured (worker, bucket, batch,
        tier-family) warm entry has dispatched at least once.  True at
        boot when no ``warmup_shapes`` are configured — an engine with no
        declared warm surface is ready by definition (it just pays
        first-request compiles, as before).  False the moment a graceful
        shutdown begins (``begin_shutdown``): the fleet router reads
        this as "stop routing here" while queued work still drains.
        Chaos slow-start (``ChaosConfig.slow_start_s``) also holds the
        gate closed — the replica a failover test brings up slowly."""
        if self._shutting_down or self._closed:
            return False
        if self.chaos is not None and self.chaos.ready_blocked():
            return False
        with self._warm_lock:
            return self._warm_target <= self._warmed

    def warm_status(self) -> Dict[str, object]:
        """Readiness detail for /readyz: progress through the configured
        bucket x batch x tier ladder, plus the disk-cache counters that
        say whether warmness came from disk or from XLA."""
        with self._warm_lock:
            done = len(self._warm_target & self._warmed)
            total = len(self._warm_target)
            ready = self._warm_target <= self._warmed
        out: Dict[str, object] = {"ready": ready and self.ready,
                                  "warm_done": done,
                                  "warm_target": total,
                                  "draining": self._shutting_down}
        out["compiles_cold"] = self.metrics.compiles_cold.value
        out["compiles_warm"] = self.metrics.compiles_warm.value
        if self.disk_cache is not None:
            out["executable_cache"] = self.disk_cache.stats()
        # The registry joins the readiness detail ONLY when named
        # models exist — a single-model engine's payload stays
        # byte-identical to the pre-registry build.
        if len(self._models) > 1 or self.default_model is not None:
            out["models"] = self.models_status()
        return out

    def _note_warm(self, widx: int, bucket: Tuple[int, int], batch: int,
                   cache_tier: Optional[str],
                   family: Optional[str] = FAMILY_BASE,
                   model: Optional[str] = None) -> None:
        with self._warm_lock:
            self._warmed.add((widx, tuple(bucket), batch, cache_tier,
                              family, model))

    def _families(self) -> Tuple[Optional[str], ...]:
        """The executable families this engine serves: the base program
        always; the session state/warm variants only when the session
        store exists (so a stateless engine's compile surface, prewarm
        cost, and readiness target are exactly the round-13 ones); the
        ctx-cache variants replace state/warm when the per-session
        context cache is on (cold frames must SAVE the bundle for warm
        frames to reuse, so plain "state" never runs there); with
        ``session_hidden`` every session family swaps for its ``_h``
        variant (all session programs must carry the hidden tree —
        otherwise one un-carried frame would silently break the warm-h
        chain)."""
        if self.sessions is None:
            return (FAMILY_BASE,)
        hidden = self.serve_cfg.session_hidden
        if self.serve_cfg.session_ctx_cache:
            if hidden:
                return (FAMILY_BASE, FAMILY_STATE_CTX_H, FAMILY_WARM_H,
                        FAMILY_WARM_CTX_H)
            return (FAMILY_BASE, FAMILY_STATE_CTX, FAMILY_WARM,
                    FAMILY_WARM_CTX)
        if hidden:
            return (FAMILY_BASE, FAMILY_STATE_H, FAMILY_WARM_H)
        return (FAMILY_BASE, FAMILY_STATE, FAMILY_WARM)

    # ------------------------------------------------------- tier variables
    def _vars_for(self, widx: int, cache_tier: Optional[str],
                  model: Optional[str] = None):
        """The variable tree a tier's executables consume on one worker:
        the bundle's resident fp32 tree for full-precision tiers, the
        bundle's per-worker int8 tree for quant tiers (built lazily,
        host-quantized once per bundle — disk checkpoints stay fp32).
        Two models with identical shapes NEVER share a variables slot:
        each bundle owns its own device placements."""
        if self._is_xl_worker(widx):
            # xl workers consume the tree replicated over their group's
            # mesh (one host->devices placement per group at boot);
            # tiers never apply there — xl is fixed-depth fp, implicit
            # model only.
            return self._xl_group(widx).variables
        bundle = self._models[model]
        if bundle.tier_models[cache_tier].config.quant == "off":
            return bundle.worker_vars[widx]
        import jax

        with self._qvars_lock:
            dev = bundle.qvars.get(widx)
            if dev is None:
                if bundle.qvars_host is None:
                    from raft_stereo_tpu.quant import quantize_variables
                    # One int8 tree serves every quant tier of the
                    # bundle: the calibrated activation scales ride the
                    # packs as an extra member that the weights-only
                    # "int8" mode's in-program dequant simply ignores,
                    # while "int8_mxu" executables read them as their
                    # static input-quantization constants.
                    bundle.qvars_host = quantize_variables(
                        bundle.host_variables,
                        act_scales=self._quant_act_scales)
                dev = jax.device_put(bundle.qvars_host,
                                     self.devices[widx])
                bundle.qvars[widx] = dev
        return dev

    def _ctx_avals(self, cfg, bucket: Tuple[int, int], batch: int):
        """Abstract shapes of one context bundle at ``bucket`` — what the
        AOT persistent-cache path lowers the ctx families with and what
        prewarm feeds as zeros (models/raft_stereo.py: per-level initial
        hidden states + (cz, cr, cq) biases at 1/2^(downsample+l))."""
        import jax
        import jax.numpy as jnp

        dt = jnp.bfloat16 if cfg.mixed_precision else jnp.float32
        f = cfg.downsample_factor
        nets, ctxs = [], []
        for l in range(cfg.n_gru_layers):
            h = bucket[0] // (f * 2 ** l)
            w = bucket[1] // (f * 2 ** l)
            c = cfg.hidden_dims[l]
            nets.append(jax.ShapeDtypeStruct((batch, h, w, c), dt))
            ctxs.append(tuple(jax.ShapeDtypeStruct((batch, h, w, c), dt)
                              for _ in range(3)))
        return (tuple(nets), tuple(ctxs))

    def _hidden_avals(self, cfg, bucket: Tuple[int, int], batch: int):
        """Abstract shapes of one hidden-state tree at ``bucket`` — the
        per-level evolved GRU states the warm-h families consume
        (identical geometry to the ctx bundle's net half)."""
        return self._ctx_avals(cfg, bucket, batch)[0]

    # --------------------------------------------------------- compile cache
    def _cache_tier(self, tier: Optional[str],
                    model: Optional[str] = None) -> Optional[str]:
        """The executable-cache key a tier compiles under: None when the
        tier's model IS the bundle's base model (fixed-depth tiers share
        the base executables — one program, one cost record, bitwise
        parity)."""
        bundle = self._models[model]
        if tier is None or bundle.tier_models.get(tier) is bundle.model:
            return None
        return tier

    def _distinct_cache_tiers(self, model: Optional[str] = None
                              ) -> List[Optional[str]]:
        """The DISTINCT executable families the configured tiers compile
        to ("quality" and the base path normalize to one cache key) —
        what prewarm and the readiness target iterate, per model."""
        tiers = tuple(self.tiers) if self.tiers else (None,)
        return sorted({self._cache_tier(t, model) for t in tiers},
                      key=lambda t: (t is not None, t or ""))

    def _cost_key(self, bucket: Tuple[int, int], batch: int,
                  tier: Optional[str] = None,
                  family: Optional[str] = FAMILY_BASE,
                  model: Optional[str] = None) -> str:
        """Stable label of one compile point in the cost registry — what
        GET /debug/compiles lists and the MFU path looks up.  The quant
        mode joins the key exactly like the family tag (the r14
        warm/state split): an int8 tier's executable must never share a
        cost record with the full-precision program of the same
        (bucket, batch).  A registered model's coordinate joins LAST
        (",model=name@version") — the implicit model's keys stay
        byte-identical to the pre-registry build."""
        if family == FAMILY_XL:
            # The mesh label IS the family coordinate for xl (the
            # ISSUE's ",mesh=rows4" contract): an xl executable must
            # never share a cost record with the solo program of the
            # same (bucket, batch).
            label = self.xl.label if self.xl is not None else "none"
            return (f"serving.forward({bucket[0]}x{bucket[1]},b{batch}"
                    f",mesh={label})")
        bundle = self._models[model]
        cache_tier = self._cache_tier(tier, model)
        tail = "" if cache_tier is None else f",tier={tier}"
        qmode = bundle.tier_models[cache_tier].config.quant
        if qmode != "off":
            tail += f",quant={qmode}"
        if self.serve_cfg.confidence:
            # The confidence variant returns two extra outputs — a
            # different program, so a different cost record.  Off keeps
            # every key byte-identical to the round-23 build.
            tail += ",conf"
        if family is not None:
            tail += f",{family}"
        if bundle.name is not None:
            tail += f",model={bundle.coord}"
        return f"serving.forward({bucket[0]}x{bucket[1]},b{batch}{tail})"

    def compiled_cost(self, bucket: Tuple[int, int], batch: int = 1,
                      tier: Optional[str] = None,
                      family: Optional[str] = FAMILY_BASE,
                      model: Optional[str] = None):
        """The cost record for a compiled (bucket, batch) executable, or
        None (no registry / not compiled yet / analysis degraded)."""
        if self.costs is None:
            return None
        return self.costs.get(self._cost_key(bucket, batch, tier, family,
                                             model))

    def _forward_for(self, bucket: Tuple[int, int], batch: int = 1,
                     worker: int = 0, tier: Optional[str] = None,
                     family: Optional[str] = FAMILY_BASE,
                     model: Optional[str] = None):
        """The compiled batch-``batch`` executable for ``bucket`` on
        ``worker``'s device — the engine-owned cache the round-6 design
        spread across per-worker InferenceRunners.  Bounded per worker at
        ``max_cached_shapes`` (bucket, batch, tier, family, model)
        entries, oldest evicted."""
        tier = self._cache_tier(tier, model)
        bundle = self._models[model]
        key = (worker, tuple(bucket), batch, tier, family, model)
        with self._cache_lock:
            if key in self._compiled:
                self._compiled[key] = self._compiled.pop(key)  # LRU refresh
                return self._compiled[key]
        # Build + (with cost telemetry) AOT-instrument outside the lock —
        # distinct keys may compile concurrently on different workers.
        if family == FAMILY_XL:
            # The mesh-sharded program over this worker's device group
            # (eval/runner.make_forward_mesh); base arity, fixed depth.
            fwd = make_forward_mesh(
                self.xl.model, self.serve_cfg.iters,
                self._xl_group(worker).mesh,
                self._fetch_jax_dtype(),
                donate_images=self.serve_cfg.donate_buffers)
        else:
            fwd = make_forward(
                bundle.tier_models[tier], self.serve_cfg.iters,
                self._fetch_jax_dtype(),
                donate_images=self.serve_cfg.donate_buffers,
                warm_start=(family in _WARM_FAMILIES),
                return_state=(family is not FAMILY_BASE
                              and family != FAMILY_XL),
                ctx=("save" if family in _CTX_SAVE_FAMILIES
                     else "reuse" if family in _CTX_REUSE_FAMILIES
                     else None),
                hidden_init=(family in _H_IN_FAMILIES),
                return_hidden=(family in _H_OUT_FAMILIES),
                return_confidence=self.serve_cfg.confidence)
        if self.disk_cache is not None:
            fwd = self._load_or_compile(fwd, bucket, batch, worker, tier,
                                        family, model)
        else:
            # No persistent cache: the executable is built by XLA (at
            # first dispatch on the plain-jit path, inside instrument on
            # the cost path) — a cold compile either way.
            self.metrics.compiles_cold.inc()
            if self.costs is not None:
                fwd = self.costs.instrument(
                    fwd, key=self._cost_key(bucket, batch, tier, family,
                                            model),
                    site="serving", model=bundle.coord)
        with self._cache_lock:
            mine = [k for k in self._compiled if k[0] == worker]
            while len(mine) >= self.serve_cfg.max_cached_shapes:
                evicted = mine.pop(0)
                self._compiled.pop(evicted)
                log.info(
                    "engine compile cache full (max_cached_shapes=%d): "
                    "evicting oldest executable for bucket %s batch %d "
                    "tier %s family %s model %s on worker %d — its next "
                    "use re-pays XLA compile time",
                    self.serve_cfg.max_cached_shapes, evicted[1],
                    evicted[2], evicted[3], evicted[4], evicted[5],
                    evicted[0])
                if self.costs is not None:
                    self.costs.note_runner_eviction(
                        self._cost_key(*evicted[1:]), len(mine))
            self._compiled[key] = fwd
            if self.costs is not None:
                self.costs.note_runner_cache_size(len(self._compiled))
        return fwd

    def _disk_key(self, bucket: Tuple[int, int], batch: int,
                  worker: int, cache_tier: Optional[str],
                  family: Optional[str] = FAMILY_BASE,
                  model: Optional[str] = None) -> str:
        """The persistent-cache content key of one compile point: every
        coordinate that selects a distinct program, plus the device the
        serialized executable is bound to (persist.py mixes in the
        jax/backend fingerprint).  ``family`` / ``flow_init`` encode the
        streaming-program arity — a warm executable takes an extra
        traced input and returns the low-res state, so it must NEVER
        share a disk entry with the sessionless program of the same
        (config, bucket, batch, tier)."""
        from raft_stereo_tpu.serving.persist import executable_cache_key

        if family == FAMILY_XL:
            # The xl coordinates: the sharded config JSON (rows_shards /
            # corr_w2_shards / rows_gru live inside it), the explicit
            # mesh label (belt and braces, like quant below), and the
            # WHOLE device group — a serialized sharded executable is
            # bound to its device assignment, so groups never share an
            # entry.
            group = self._xl_group(worker)
            return executable_cache_key(
                config=self.xl.model.config.to_json(),
                bucket=tuple(bucket), batch=int(batch),
                tier=None, iters=self.serve_cfg.iters,
                fetch_dtype=self.serve_cfg.fetch_dtype,
                donate=self.serve_cfg.donate_buffers,
                family=FAMILY_XL, flow_init=False,
                mesh=self.xl.label, device=group.label)
        bundle = self._models[model]
        # Registered models join the key ONLY as extra kwargs (the
        # content hash is over sorted kwargs JSON), so the implicit
        # model's keys — no model kwargs at all — stay byte-identical
        # to the pre-registry build (the bitwise single-model pin).
        extra = {}
        if bundle.name is not None:
            extra = {"model": bundle.name,
                     "model_version": bundle.version}
        if self.serve_cfg.confidence:
            # Confidence variants return two extra outputs — a distinct
            # program, so a distinct disk entry.  Joins as an extra
            # kwarg ONLY when on, so confidence-off keys stay
            # byte-identical to the round-23 build (the bitwise pin).
            extra["confidence"] = True
        return executable_cache_key(
            config=bundle.tier_models[cache_tier].config.to_json(),
            bucket=tuple(bucket), batch=int(batch),
            tier=cache_tier, iters=self.serve_cfg.iters,
            fetch_dtype=self.serve_cfg.fetch_dtype,
            donate=self.serve_cfg.donate_buffers,
            family=family, flow_init=(family in _WARM_FAMILIES),
            # The hidden-tree arity (round 19): warm-h programs take an
            # extra traced input tree and every _h program returns one —
            # the family string above already separates them, but the
            # explicit coordinate keeps the key self-describing.
            hidden=(family in _H_IN_FAMILIES),
            # Belt and braces for the int8 tier: the quant mode is
            # already inside the config JSON above, but it also keys
            # explicitly — a quantized and a base executable consume
            # DIFFERENT input trees (int8 packs vs fp32 kernels) and
            # must never collide on one disk entry (tests/test_quant.py).
            quant=bundle.tier_models[cache_tier].config.quant,
            device=str(getattr(self.devices[worker], "id", worker)),
            **extra)

    def _load_or_compile(self, fwd, bucket: Tuple[int, int], batch: int,
                         worker: int, cache_tier: Optional[str],
                         family: Optional[str] = FAMILY_BASE,
                         model: Optional[str] = None):
        """The persistent-cache build path: deserialize the executable
        from disk (warm — no XLA compile paid) or AOT-compile it now and
        store it for the next boot (cold).  Either way the cost registry
        (when attached) gets its record, so /debug/compiles stays the
        complete executable inventory.  Falls back to the plain callable
        when the AOT machinery is unavailable — the cache can never take
        the dispatch path down."""
        import jax

        bundle = self._models[model]
        disk_key = self._disk_key(bucket, batch, worker, cache_tier,
                                  family, model)
        t0 = time.perf_counter()
        exe = self.disk_cache.load(disk_key)
        if exe is not None:
            self.metrics.compiles_warm.inc()
            log.info("bucket %s batch %d tier %s family %s model %s "
                     "worker %d: executable restored from persistent "
                     "cache in %.3fs",
                     bucket, batch, cache_tier, family, bundle.coord,
                     worker, time.perf_counter() - t0)
            if self.costs is not None:
                self.costs.record(
                    self._cost_key(bucket, batch, cache_tier, family,
                                   model),
                    "serving", time.perf_counter() - t0, compiled=exe,
                    model=bundle.coord)
            return exe
        aval = jax.ShapeDtypeStruct((batch, bucket[0], bucket[1], 3),
                                    np.uint8)
        avals = [aval, aval]
        tier_cfg = (self.xl.model.config if family == FAMILY_XL
                    else bundle.tier_models[cache_tier].config)
        if family in _WARM_FAMILIES:
            f = tier_cfg.downsample_factor
            avals.append(jax.ShapeDtypeStruct(
                (batch, bucket[0] // f, bucket[1] // f), np.float32))
        if family in _H_IN_FAMILIES:
            avals.append(self._hidden_avals(tier_cfg, bucket, batch))
        if family in _CTX_REUSE_FAMILIES:
            avals.append(self._ctx_avals(tier_cfg, bucket, batch))
        try:
            compiled = fwd.lower(self._vars_for(worker, cache_tier,
                                                model),
                                 *avals).compile()
        except Exception:
            log.warning("AOT compile for the persistent cache failed; "
                        "falling back to plain jit dispatch (this "
                        "executable will not be cached)", exc_info=True)
            self.metrics.compiles_cold.inc()
            if self.costs is not None:
                return self.costs.instrument(
                    fwd, key=self._cost_key(bucket, batch, cache_tier,
                                            family, model),
                    site="serving", model=bundle.coord)
            return fwd
        compile_s = time.perf_counter() - t0
        self.metrics.compiles_cold.inc()
        if self.costs is not None:
            self.costs.record(
                self._cost_key(bucket, batch, cache_tier, family, model),
                "serving", compile_s, compiled=compiled,
                model=bundle.coord)
        self.disk_cache.store(
            disk_key, compiled,
            meta={"bucket": list(bucket), "batch": int(batch),
                  "tier": cache_tier, "family": family,
                  "iters": self.serve_cfg.iters,
                  "quant": tier_cfg.quant,
                  "model": bundle.coord,
                  "mesh": (self.xl.label if family == FAMILY_XL
                           else None),
                  "fetch_dtype": self.serve_cfg.fetch_dtype,
                  "compile_s": round(compile_s, 3)})
        return compiled

    def _fetch_jax_dtype(self):
        import jax.numpy as jnp

        fetch = self.serve_cfg.fetch_dtype
        if fetch not in (None, "fp16", "bf16"):
            raise ValueError(f"fetch_dtype={fetch!r}: use 'fp16', 'bf16', "
                             f"or None (full fp32 fetch)")
        return {None: None, "fp16": jnp.float16,
                "bf16": jnp.bfloat16}[fetch]

    def prewarm(self, raw_hw: Tuple[int, int],
                batch_sizes: Optional[Sequence[int]] = None,
                tiers: Optional[Sequence[Optional[str]]] = None,
                models: Optional[Sequence[Optional[str]]] = None) -> None:
        """Compile + warm the whole bucket ladder for one raw shape on
        every worker: each configured batch size dispatches once with
        zero images, so the first real requests at this shape hit warm
        executables (and, with cost telemetry, the registry holds every
        ladder rung's cost record at boot).  With latency tiers
        configured, every tier's executable family is warmed (fixed-depth
        tiers share the base executables, so the ladder compiles once per
        DISTINCT program, not once per tier name).  ``models`` limits
        the pass to specific registered models (None = every served
        model, implicit first) — the hot-swap path warms just the new
        arrival."""
        import jax

        h, w = int(raw_hw[0]), int(raw_hw[1])
        hp, wp, _ = self.policy.bucket_for(h, w)
        if self._xl_routes((hp, wp)):
            # This bucket's traffic dispatches on the xl mesh groups —
            # warm THAT surface (and only it; the solo ladder at this
            # size would compile programs no request runs).  Implicit
            # model only: named models never route xl.
            if models is None or None in models:
                self._prewarm_xl((hp, wp), batch_sizes)
            return
        sizes = tuple(batch_sizes) if batch_sizes else self.queue.sizes
        model_names = (list(models) if models is not None
                       else self._registered_names())
        for mname in model_names:
            if tiers is None:
                cache_tiers = self._distinct_cache_tiers(mname)
            else:
                # Distinct executable families only: "quality" and the
                # base path normalize to the same cache key.
                cache_tiers = sorted(
                    {self._cache_tier(t, mname) for t in tiers},
                    key=lambda t: (t is not None, t or ""))
            bundle = self._models[mname]
            for widx, dev in enumerate(self.devices):
                for tier in cache_tiers:
                    for n in sizes:
                        for family in self._families():
                            fwd = self._forward_for(
                                (hp, wp), n, worker=widx,
                                tier=tier, family=family, model=mname)
                            zeros = np.zeros((n, hp, wp, 3), np.uint8)
                            args = [self._vars_for(widx, tier, mname),
                                    jax.device_put(zeros, dev),
                                    jax.device_put(zeros.copy(), dev)]
                            tier_cfg = bundle.tier_models[tier].config
                            if family in _WARM_FAMILIES:
                                f = tier_cfg.downsample_factor
                                args.append(jax.device_put(
                                    np.zeros((n, hp // f, wp // f),
                                             np.float32), dev))
                            if family in _H_IN_FAMILIES:
                                import jax.tree_util as jtu
                                args.append(jtu.tree_map(
                                    lambda s: jax.device_put(
                                        np.zeros(s.shape, s.dtype), dev),
                                    self._hidden_avals(tier_cfg, (hp, wp),
                                                       n)))
                            if family in _CTX_REUSE_FAMILIES:
                                import jax.tree_util as jtu
                                ctx_zeros = jtu.tree_map(
                                    lambda s: jax.device_put(
                                        np.zeros(s.shape, s.dtype), dev),
                                    self._ctx_avals(tier_cfg, (hp, wp), n))
                                args.append(ctx_zeros)
                            out = fwd(*args)
                            jax.block_until_ready(out)
                            self._note_warm(widx, (hp, wp), n, tier,
                                            family, mname)
        log.info("prewarmed bucket %dx%d batch sizes %s (%d model(s) x "
                 "tier families x %d program variant(s)) on %d "
                 "worker(s)",
                 hp, wp, sizes, len(model_names),
                 len(self._families()), len(self.devices))

    def _prewarm_xl(self, bucket: Tuple[int, int],
                    batch_sizes: Optional[Sequence[int]] = None) -> None:
        """Compile + warm the xl bucket ladder on every xl device group:
        each batch size dispatches once with zero images through the
        mesh-sharded program, the warm entries open /readyz, and (with
        cost telemetry) the per-device HBM gauge goes live."""
        import jax

        sizes = tuple(batch_sizes) if batch_sizes else self._xl_sizes
        for widx in self._xl_worker_indices():
            group = self._xl_group(widx)
            for n in sizes:
                fwd = self._forward_for(bucket, n, worker=widx,
                                        tier=None, family=FAMILY_XL)
                zeros = np.zeros((n, bucket[0], bucket[1], 3), np.uint8)
                out = fwd(group.variables,
                          jax.device_put(zeros, group.sharding),
                          jax.device_put(zeros.copy(), group.sharding))
                jax.block_until_ready(out)
                self._note_warm(widx, bucket, n, None, FAMILY_XL)
                self._note_xl_hbm(bucket, n)
        log.info("prewarmed XL bucket %dx%d batch sizes %s (mesh %s) on "
                 "%d device group(s)", bucket[0], bucket[1], sizes,
                 self.xl.label, len(self.xl.groups))

    def _note_xl_hbm(self, bucket: Tuple[int, int], batch: int) -> None:
        """Surface the xl executable's per-device HBM (CompileRecord
        memory_analysis) as serve_xl_hbm_bytes{mesh=,bucket=} — the
        sharding win as a live gauge.  No-op without cost telemetry or
        when the backend's analysis degraded."""
        rec = self.compiled_cost(bucket, batch=batch, family=FAMILY_XL)
        if rec is not None and rec.hbm_bytes:
            self.metrics.xl_hbm_gauge(
                self.xl.label, f"{bucket[0]}x{bucket[1]}"
            ).set(rec.hbm_bytes)

    # --------------------------------------------------------------- workers
    def _worker_loop(self, widx: int) -> None:
        """One device worker under supervision.  The circuit breaker
        gates the pop (an open circuit = this device takes no work); a
        dispatch crash hands the batch to the recovery path and then
        RESTARTS the worker thread — a crashed dispatch must never kill
        the server, and a fresh thread is the cheapest guarantee that no
        corrupted per-thread state survives the crash."""
        breaker = self.breakers[widx]
        # Worker-class pop filter: xl device-group workers take ONLY the
        # mesh-sharded xl groups (their own batch ladder); solo workers
        # take everything else.  One queue, one admission bound, one
        # drain — the filter is the whole scheduler change.
        want, sizes = None, None
        if self.xl is not None:
            if self._is_xl_worker(widx):
                want = lambda key: key[2] == FAMILY_XL  # noqa: E731
                sizes = self._xl_sizes
            else:
                want = lambda key: key[2] != FAMILY_XL  # noqa: E731
        while True:
            delay = breaker.until_allowed()
            if delay > 0:
                if self._closed:
                    return
                time.sleep(min(delay, 0.05))
                continue
            batch = self.queue.pop(want=want, sizes=sizes)
            if batch is None:       # queue closed: worker shutdown
                return
            try:
                self._run_batch(widx, batch)
                breaker.record_success()
            except BaseException as e:  # noqa: BLE001 — recover, restart
                self._on_dispatch_failure(widx, batch, e)
                self.metrics.inflight.dec(len(batch))
                self._restart_worker(widx)
                return              # this thread exits; successor took over
            self.metrics.inflight.dec(len(batch))

    # ---------------------------------------------------- supervised recovery
    def _on_dispatch_failure(self, widx: int, batch: List[Request],
                             exc: BaseException) -> None:
        """The recovery path for one crashed dispatch: record the breaker
        failure, requeue the batch's unresolved requests with backoff, and
        poison the ones that exhausted their attempts.  Chunks of the
        batch that already completed (futures done) are untouched."""
        pending = [r for r in batch if not r.future.done()]
        log.exception("dispatch of %d request(s) crashed on worker %d "
                      "(%d unresolved)", len(batch), widx, len(pending))
        self.breakers[widx].record_failure()
        sink = self.sink
        if sink is not None:
            sink.fire("worker_crash", device=widx, batch_size=len(batch),
                      unresolved=len(pending),
                      error=f"{type(exc).__name__}: {exc}")
        retry: List[Request] = []
        now_pc = time.perf_counter()
        for r in pending:
            r.attempts += 1
            if getattr(r.payload, "session", None) is not None:
                self._invalidate_crashed_session_frame(r)
            if r.attempts >= self.serve_cfg.max_dispatch_attempts:
                self.metrics.poisoned.inc()
                self.metrics.failed.inc()
                if r.trace is not None and r.trace.root is not None:
                    r.trace.root.set_attr("attempts", r.attempts)
                r.future.set_exception(RequestPoisoned(
                    f"dispatch crashed on all {r.attempts} attempts "
                    f"(last: {type(exc).__name__}: {exc})",
                    attempts=r.attempts, last_error=exc))
            else:
                retry.append(r)
        if not retry:
            return
        self.metrics.retries.inc(len(retry))
        attempt = max(r.attempts for r in retry)
        backoff_s = (self.serve_cfg.retry_backoff_ms / 1e3
                     * 2 ** (attempt - 1))
        for r in retry:
            if r.trace is not None:
                self.tracer.add_span(
                    "serve.retry", r.trace, now_pc, time.perf_counter(),
                    attempt=r.attempts, device=widx,
                    backoff_ms=round(backoff_s * 1e3, 3),
                    error=type(exc).__name__)
        self._schedule_requeue(retry, backoff_s)

    def _invalidate_crashed_session_frame(self, req: Request) -> None:
        """A crashed dispatch carried this SESSION frame (r13 requeue x
        r14 submit_session cross): the flow this frame was supposed to
        produce never existed, so (a) a requeued WARM frame must not
        re-run the warm program against state the crash voided — a
        crash *caused by* that state (NaN init, poisoned buffer) would
        deterministically burn every retry attempt — and (b) the
        session's stored state must not seed any LATER frame across the
        gap.  Demote the requeued frame to the cold family (it
        cold-starts and, on success, re-seeds the chain exactly like a
        scene cut) and drop the session's warm-start state.  Mutating
        the session here is safe: its ordering lock is held by THIS
        frame from submit to resolution, so no other frame of the
        session can observe a torn state.  The ordering lock itself is
        released by the frame's future resolving (retry success or
        typed poisoning) — never leaked.  Regression:
        tests/test_sessions.py."""
        sess = req.payload.session
        if req.family in _WARM_FAMILIES:
            ctx_on = self.serve_cfg.session_ctx_cache
            if self.serve_cfg.session_hidden:
                req.family = (FAMILY_STATE_CTX_H if ctx_on
                              else FAMILY_STATE_H)
            else:
                req.family = FAMILY_STATE_CTX if ctx_on else FAMILY_STATE
            req.payload.flow_init = None
            req.payload.hidden_init = None
            req.payload.ctx_init = None
            log.warning("session %s frame %s: crashed warm dispatch "
                        "demoted to a cold start for its retry",
                        req.session_id, req.payload.frame_index)
        sess.flow_low = None
        sess.hidden = None
        sess.ctx = None

    def _schedule_requeue(self, reqs: List[Request],
                          delay_s: float) -> None:
        """Requeue ``reqs`` after ``delay_s`` on a backoff timer.  The
        pending-retry count keeps ``drain`` honest (requests in backoff
        are neither queued nor inflight) and ``close`` fails the timers'
        requests instead of stranding them."""
        with self._retry_lock:
            self._pending_retries += len(reqs)

        entry = None

        def _requeue():
            try:
                self.queue.requeue(reqs)   # closed queue -> typed failure
            finally:
                with self._retry_lock:
                    self._pending_retries -= len(reqs)
                    self._retry_timers.discard(entry)

        timer = threading.Timer(max(0.0, delay_s), _requeue)
        timer.daemon = True
        entry = (timer, tuple(reqs))
        with self._retry_lock:
            self._retry_timers.add(entry)
        timer.start()

    def _pending_retry_count(self) -> int:
        with self._retry_lock:
            return self._pending_retries

    def _restart_worker(self, widx: int) -> None:
        """Supervisor: replace a crashed worker thread with a fresh one
        on the same device (unless the engine is closing)."""
        with self._workers_lock:
            if self._closed:
                return
            t = threading.Thread(target=self._worker_loop, args=(widx,),
                                 daemon=True, name=f"stereo-worker-{widx}")
            # Start inside the lock so close() can never snapshot (and
            # try to join) a thread that was not started yet.
            self._workers[widx] = t
            t.start()
        self.metrics.worker_restarts.inc()
        log.warning("worker %d restarted after dispatch crash "
                    "(restart #%d)", widx,
                    self.metrics.worker_restarts.value)

    def _run_batch(self, widx: int, batch: List[Request]) -> None:
        """One popped batch.  The scheduler pops exact bucket sizes, but
        deadline triage can shrink a batch below the size it picked —
        decompose so every device dispatch still runs a compiled
        batch-size bucket."""
        sizes = (self._xl_sizes if batch[0].family == FAMILY_XL
                 else self.queue.sizes)
        i = 0
        for k in decompose_batch(len(batch), sizes):
            self._run_chunk(widx, batch[i:i + k])
            i += k

    def _run_chunk(self, widx: int, batch: List[Request]) -> None:
        import jax

        t_pickup = time.monotonic()
        waits = [t_pickup - r.t_enqueue for r in batch]
        bucket = batch[0].bucket
        # The queue groups by (bucket, tier, family, model): every
        # member of this chunk shares all four coordinates.
        tier = batch[0].tier
        family = batch[0].family
        model = batch[0].model
        bundle = self._models[model]
        cache_tier = self._cache_tier(tier, model)
        n = len(batch)
        xl = family == FAMILY_XL
        if xl:
            group = self._xl_group(widx)
            device = group.sharding   # replicated upload over the mesh
            device_label = f"xl:{group.label}"
        else:
            device = self.devices[widx]
            device_label = str(device)

        # Sampled requests: the queue leg ends at worker pickup; the
        # dispatch/fetch spans below share the chunk's time window but land
        # in each request's own trace (a trace stays self-contained).
        sampled = [r for r in batch if r.trace is not None]
        p_pickup = time.perf_counter() if sampled else 0.0
        for r in sampled:
            if r.queue_span is not None and r.queue_span.t_end is None:
                r.queue_span.set_attr("batch_size", n)
                self.tracer.finish(r.queue_span)

        # Fault injection (serving/chaos.py): one attribute check when
        # chaos is off — the no-chaos dispatch path is the round-12
        # program, bitwise-unchanged (tests/test_resilience.py).  The
        # injected exceptions propagate into the worker loop's recovery
        # path exactly like organic faults.
        if self.chaos is not None:
            self.chaos.on_compile(widx)
            self.chaos.on_dispatch(widx)

        with profiling.annotate("serve.device"):
            # ONE batch-n dispatch through the (bucket, n) executable.
            # n == 1 is the identical program the solo InferenceRunner
            # compiles (make_forward), so that bucket stays bitwise-equal
            # to solo inference; n > 1 amortizes the fixed per-dispatch
            # work across a real batch axis with zero filler frames.
            fwd = self._forward_for(bucket, n, worker=widx, tier=tier,
                                    family=family, model=model)
            adaptive = False if xl else early_exit_enabled(
                bundle.tier_models[cache_tier].config)
            p1 = np.stack([r.payload.left for r in batch])
            p2 = np.stack([r.payload.right for r in batch])
            args = [self._vars_for(widx, cache_tier, model),
                    jax.device_put(p1, device),
                    jax.device_put(p2, device)]
            if family in _WARM_FAMILIES:
                # Warm session frames: the batch's previous-frame states
                # stack into the program's flow_init input.
                fi = np.stack([r.payload.flow_init for r in batch]
                              ).astype(np.float32)
                args.append(jax.device_put(fi, device))
            if family in _H_IN_FAMILIES:
                # Hidden warm start: the batch members' per-level hidden
                # trees stack leaf-wise (frames of DIFFERENT sessions
                # batch together; each leaf is per-image along axis 0).
                import jax.tree_util as jtu
                hidden_stacked = jtu.tree_map(
                    lambda *xs: np.stack(xs),
                    *[r.payload.hidden_init for r in batch])
                args.append(jax.device_put(hidden_stacked, device))
            if family in _CTX_REUSE_FAMILIES:
                # Context reuse: the batch members' cached bundles stack
                # leaf-wise (frames of DIFFERENT static-scene sessions
                # batch together; each leaf is per-image along axis 0).
                import jax.tree_util as jtu
                ctx_stacked = jtu.tree_map(
                    lambda *xs: np.stack(xs),
                    *[r.payload.ctx_init for r in batch])
                args.append(jax.device_put(ctx_stacked, device))
            out = fwd(*args)
            # Advisory device clock: honest on a local backend; behind an
            # async tunnel readiness reports at dispatch (profiling.py) and
            # only the fetch below is a real stop clock.
            jax.block_until_ready(out)
        t_ready = time.monotonic()
        p_ready = time.perf_counter() if sampled else 0.0

        with profiling.annotate("serve.fetch"):
            flow_low_padded = None
            ctx_out = None
            hidden_out = None
            if family in _CTX_SAVE_FAMILIES:
                # The ctx-saving cold program appends the context bundle
                # LAST (eval/runner.make_forward): peel it off, fetch it
                # to host leaves (numpy; bf16 leaves ride as ml_dtypes).
                import jax.tree_util as jtu
                out, ctx_dev = out[:-1], out[-1]
                ctx_out = jtu.tree_map(lambda x: np.asarray(x), ctx_dev)
            if family in _H_OUT_FAMILIES:
                # The hidden tree rides just before the ctx bundle
                # (return order: flow_up, flow_low[, iters][, conf]
                # [, hidden][, ctx]) — now the LAST remaining element.
                import jax.tree_util as jtu
                out, hidden_dev = out[:-1], out[-1]
                hidden_out = jtu.tree_map(lambda x: np.asarray(x),
                                          hidden_dev)
            conf_padded = None
            confidence_on = self.serve_cfg.confidence and not xl
            if confidence_on:
                # The confidence element — the model's (conf_low,
                # conf_up) pair — rides just before hidden/ctx, so after
                # those peels it is the last remaining element.  Only
                # the full-res map is served.
                out, conf_dev = out[:-1], out[-1]
                conf_padded = np.asarray(conf_dev[1])   # (n, Hp, Wp)
                if family is FAMILY_BASE and not adaptive:
                    # The base fixed-depth program returns a bare array
                    # without confidence; restore that arity for the
                    # shared unpack below.
                    out = out[0]
            if family is FAMILY_BASE or xl:
                if adaptive:
                    flows, iters_used_dev = out
                    iters_used = int(iters_used_dev)  # extra scalar fetch
                else:
                    flows, iters_used = out, self.serve_cfg.iters
            else:
                # Session families also return the padded low-res state
                # (and, adaptive, the trip count): (flow_up, flow_low[,
                # iters_used]) — eval/runner.make_forward.
                if adaptive:
                    flows, flow_low, iters_used_dev = out
                    iters_used = int(iters_used_dev)
                else:
                    (flows, flow_low), iters_used = out, self.serve_cfg.iters
                flow_low_padded = np.asarray(flow_low)  # (n, Hp/f, Wp/f)
            flows_padded = np.asarray(flows)      # (n, Hp, Wp)
        t_fetched = time.monotonic()
        p_fetched = time.perf_counter() if sampled else 0.0
        for r in sampled:
            self.tracer.add_span(
                "serve.dispatch", r.trace, p_pickup, p_ready,
                bucket=str(bucket), batch_size=n, device=device_label,
                iters_used=iters_used, attempt=r.attempts + 1,
                **({"tier": tier} if tier is not None else {}))
            self.tracer.add_span("serve.fetch", r.trace, p_ready, p_fetched,
                                 batch_size=n)

        device_s = t_ready - t_pickup
        fetch_s = t_fetched - t_ready
        # Per-group dispatch-latency EWMA: the EDF scheduler's bounded
        # slack subtracts this from the nearest deadline.
        self._note_dispatch_latency(batch[0].group_key,
                                    device_s + fetch_s)
        self.metrics.observe_dispatch(n)
        if xl:
            self.metrics.xl_dispatches.inc()
            self._note_xl_hbm(bucket, n)
        # Trip-count telemetry: every dispatch lands in the per-tier
        # infer_gru_iters_used histogram (fixed-depth paths report the
        # configured depth, so tier histograms are directly comparable)
        # and early-exit dispatches accumulate the iterations they saved.
        self.metrics.observe_iters_used(
            "xl" if xl else (tier or "default"), iters_used,
            self.serve_cfg.iters, n_requests=n)
        self.metrics.device_time.observe(device_s)
        self.metrics.fetch_time.observe(fetch_s)
        # Padding-waste accounting + the policy feedback loop: every
        # dispatched pixel beyond the requests' real image pixels is pure
        # waste at fixed GRU depth.  With the engine's exact-occupancy
        # batch axis the only waste left is spatial padding — which is
        # exactly what BucketPolicy.note adapts on.
        real_px = sum(r.payload.padder.ht * r.payload.padder.wd
                      for r in batch)
        dispatched_px = n * bucket[0] * bucket[1]
        self.metrics.observe_padding(bucket, real_px, dispatched_px)
        self.policy.note(bucket, real_px, dispatched_px)
        # MFU numerator: the batch-n executable's model flops, once per
        # dispatch.  NOTE XLA's cost_analysis counts a loop body ONCE
        # regardless of trip count (scan and while alike —
        # tools/cost_report.py records both undercounts), so this
        # numerator never overstates under early exit; scale phase flops
        # by the observed iters_used for honest per-phase MFU
        # (cost_report --observed_iters).
        if self._mfu is not None:
            rec = self.compiled_cost(bucket, batch=n, tier=tier,
                                     family=family, model=model)
            if rec is not None and rec.flops:
                self.metrics.dispatched_flops.inc(rec.flops)
                self._mfu.note(rec.flops)
        self.metrics.note_batch_done()
        if model is not None:
            # Per-model request accounting (named models only: the
            # implicit model's /metrics stay byte-identical to pre-
            # registry builds).
            self.metrics.observe_model_request(bundle.name, bundle.version,
                                               n_requests=n)
        self._note_warm(widx, bucket, n, cache_tier, family, model)
        for i, (r, fp, wait) in enumerate(zip(batch, flows_padded, waits)):
            exemplar = r.trace.trace_id if r.trace is not None else None
            p_respond = time.perf_counter() if exemplar is not None else 0.0
            flow = r.payload.padder.unpad(fp[None])[0]
            if flow.dtype != np.float32:             # half-precision fetch
                flow = flow.astype(np.float32)
            total = t_fetched - r.t_enqueue
            self.metrics.queue_wait.observe(wait, exemplar=exemplar)
            self.metrics.total_latency.observe(total, exemplar=exemplar)
            self.metrics.completed.inc()
            ctx_i = None
            if ctx_out is not None:
                # Per-member slice of the batch's returned bundle: the
                # session stores a batch-axis-free copy it can stack
                # into any later dispatch.
                import jax.tree_util as jtu
                ctx_i = jtu.tree_map(lambda leaf, j=i: leaf[j], ctx_out)
            hidden_i = None
            if hidden_out is not None:
                import jax.tree_util as jtu
                hidden_i = jtu.tree_map(lambda leaf, j=i: leaf[j],
                                        hidden_out)
            conf_i = None
            conf_mean = None
            if conf_padded is not None:
                conf_i = r.payload.padder.unpad(
                    conf_padded[i][None])[0]
                if conf_i.dtype != np.float32:
                    conf_i = conf_i.astype(np.float32)
                conf_i = np.ascontiguousarray(conf_i)
                conf_mean = float(conf_i.mean())
                if self.quality is not None:
                    self.quality.observe(tier or "default",
                                         bundle.coord, conf_mean,
                                         exemplar=exemplar)
            r.future.set_result(ServeResult(
                flow=np.ascontiguousarray(flow), queue_wait_s=wait,
                device_s=device_s, fetch_s=fetch_s, total_s=total,
                batch_size=n, iters_used=iters_used,
                tier="xl" if xl else tier,
                mesh=self.xl.label if xl else None,
                requested_tier=r.requested_tier, attempts=r.attempts + 1,
                session_id=r.session_id,
                frame_index=r.payload.frame_index,
                warm=(family in _WARM_FAMILIES),
                scene_cut=r.payload.scene_cut,
                frame_delta=r.payload.frame_delta,
                flow_low=(np.ascontiguousarray(flow_low_padded[i])
                          if flow_low_padded is not None else None),
                ctx_cached=(family in _CTX_REUSE_FAMILIES),
                ctx=ctx_i,
                hidden=hidden_i,
                warm_hidden=(family in _H_IN_FAMILIES),
                model=bundle.name,
                model_version=bundle.version,
                confidence=conf_i, confidence_mean=conf_mean,
                trace_id=exemplar))
            if exemplar is not None:
                self.tracer.add_span("serve.respond", r.trace, p_respond,
                                     time.perf_counter())

    # ---------------------------------------------------------- fleet hooks
    def set_brownout_floor(self, level: int) -> int:
        """Fleet-wide degradation floor (``POST /admin/brownout``, pushed
        by the fleet router): the engine degrades at least this many
        rungs regardless of its local pressure signals, so the whole
        fleet steps down in lockstep instead of each replica flapping on
        its own queue.  Returns the effective level.  Raises
        ``RuntimeError`` when this engine runs without a brownout
        controller (``ServeConfig.brownout=False``)."""
        if self.brownout is None:
            raise RuntimeError(
                "this engine runs without a brownout controller "
                "(ServeConfig.brownout=False) — no ladder to degrade on")
        return self.brownout.set_floor(level)

    def begin_shutdown(self) -> None:
        """Phase one of graceful SIGTERM (cli/serve.py): flip ``ready``
        to False — /readyz answers 503 and the fleet router pulls this
        replica out of rotation within one health poll — and stop
        admitting (new submits shed with the typed draining
        ``Overloaded``), while queued + in-flight + backoff work keeps
        flowing and the HTTP server stays up to answer it.  ``drain()``
        then waits that work out and ``close()``s."""
        self._shutting_down = True
        self.queue.stop_admitting()

    # -------------------------------------------------------------- shutdown
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful SIGTERM story: refuse new work (``Overloaded``), let
        the workers finish the queue, in-flight batches, AND any crashed
        requests sitting in retry backoff, then stop them.  Returns False
        if ``timeout`` elapsed first (workers are still stopped; any
        stranded requests fail rather than hang)."""
        self.queue.stop_admitting()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        ok = True
        while (self.queue.depth > 0 or self.metrics.inflight.value > 0
               or self._pending_retry_count() > 0):
            if deadline is not None and time.monotonic() > deadline:
                ok = False
                break
            time.sleep(0.002)
        self.close()
        return ok

    def close(self) -> None:
        """Hard stop: closes the queue (queued requests fail with
        ``Overloaded``; blocked worker pops return None), cancels retry
        backoff timers (their requests fail the same typed way instead of
        hanging), stops the brownout controller, and joins the worker
        threads.  ``drain`` first for the graceful version."""
        if self._closed:
            return
        self._closed = True
        if self.brownout is not None:
            self.brownout.stop()
        self.queue.close()
        # Retry timers: cancel, then run each timer's requeue through the
        # now-closed queue so its requests get the typed shutdown failure
        # (requeue dedups, so racing an already-fired timer is safe).
        with self._retry_lock:
            entries = list(self._retry_timers)
        for timer, reqs in entries:
            timer.cancel()
            self.queue.requeue(list(reqs))
        with self._workers_lock:
            workers = list(self._workers)
        for t in workers:
            t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# The engine IS the service: the round-6 class name stays importable for
# every existing call site (serving/http.py, cli/serve.py, tests).
StereoService = ServingEngine
