"""Streaming stereo sessions: the temporal state behind warm-start video
serving.

RAFT-Stereo inherits RAFT's warm start (Teed & Deng, ECCV 2020;
arXiv 2109.07547 §3): the GRU refinement loop accepts an initial
disparity field (``flow_init``, models/raft_stereo.py), and initializing
frame t+1 from frame t's converged low-res disparity lets the
convergence-gated loop (round 12) stall after a fraction of the
iterations a cold zero-init needs.  The engine was stateless, so that
win was unreachable: this module holds the per-stream state — one
``StereoSession`` per client stream mapping session id → the previous
frame's padded low-res x-flow, a grayscale thumbnail for the scene-cut
check, and bookkeeping — under a thread-safe TTL + LRU store.

Design points:

* **TTL expiry + LRU capacity eviction.**  A session that stops sending
  frames is garbage after ``ttl_s`` (a stale disparity field is a bad
  init anyway — the scene moved on), and the store holds at most
  ``capacity`` live sessions, evicting the least-recently-used beyond
  that.  Both removals leave a bounded **tombstone** so the next frame
  on a dead id fails with the typed ``SessionExpired`` (the HTTP layer's
  410) instead of silently cold-restarting mid-stream — the client must
  acknowledge the break and open a fresh session.  Tombstones age out
  after ``ttl_s``, so an id becomes reusable once the break is old news.
* **Per-session frame ordering.**  Warm start is a frame-to-frame chain:
  frame t+1's init IS frame t's output, so two frames of one session
  must never be in flight at once (the second would read stale state,
  and a batcher could reorder them within a dispatch cycle).  Each
  session carries an ordering lock the engine holds from submit until
  the frame's future resolves — one frame per session in the pipeline,
  strict submission order, while *different* sessions batch together
  freely.
* **Scene-cut fallback.**  Warm start helps only while frames are
  temporally coherent.  ``frame_delta`` — the mean |Δintensity| between
  consecutive frames' mean-pooled grayscale thumbnails — is compared
  against the engine's threshold; a cut falls back to a cold start (and
  the session keeps streaming: state re-seeds from the cold frame).
* **Handoff serialization** (round 18).  ``export()``/``import_()``
  round-trip the whole store through a VERSIONED, per-entry-CHECKSUMMED
  blob so a gracefully draining replica can hand its live streams to a
  survivor through the shared artifact store instead of 410ing them
  (serving/engine.py ``publish_handoff``, fleet/router.py drain remap).
  The format is deliberately paranoid: a self-describing header, one
  SHA-256 per session over its metadata AND its array payload, and
  pickle-free numpy encoding — a corrupt, truncated, or
  version-mismatched entry degrades that ONE session to a cold start
  (skipped, counted), never crashes the importer, and never installs a
  torn disparity field as a warm init.

The store never touches JAX: like the batcher, every policy here is
testable in milliseconds (tests/test_sessions.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import logging
import struct
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

# Pooling factor of the scene-cut thumbnails: coarse enough that the
# per-frame host cost is trivial (~Kb), fine enough that a real scene
# change moves the mean intensity delta far past camera noise.
THUMB_POOL = 16


class SessionsDisabled(RuntimeError):
    """Streaming was requested but the engine runs without a session
    store (``ServeConfig.sessions=False``).  The HTTP layer maps this to
    a typed 400."""


class SessionExpired(KeyError):
    """The typed dead-session failure (HTTP 410): the id was live once
    but its session expired (TTL), was evicted (LRU capacity), or was
    closed — the client must open a fresh session.  ``reason`` is one of
    ``"expired"`` / ``"evicted"`` / ``"closed"``."""

    def __init__(self, session_id: str, reason: str):
        super().__init__(f"session {session_id!r} {reason}; open a new "
                         f"session to keep streaming")
        self.session_id = session_id
        self.reason = reason


def frame_thumbnail(image: np.ndarray, pool: int = THUMB_POOL) -> np.ndarray:
    """Mean-pooled grayscale thumbnail of one (H, W, 3) frame — the
    cheap host-side signature the scene-cut delta compares.  Pure NumPy,
    microseconds at video shapes."""
    gray = np.asarray(image, dtype=np.float32).mean(axis=-1)
    h, w = gray.shape
    hp, wp = h - h % pool, w - w % pool
    if hp >= pool and wp >= pool:
        gray = gray[:hp, :wp].reshape(hp // pool, pool,
                                      wp // pool, pool).mean(axis=(1, 3))
    return gray


def frame_delta(thumb_a: Optional[np.ndarray],
                thumb_b: Optional[np.ndarray]) -> Optional[float]:
    """Mean |Δintensity| (0..255) between two frame thumbnails; None when
    either side is missing or the shapes disagree (a resolution change is
    its own cold-start reason, not a measurable delta)."""
    if thumb_a is None or thumb_b is None or thumb_a.shape != thumb_b.shape:
        return None
    return float(np.mean(np.abs(thumb_a - thumb_b)))


# -------------------------------------------------------------- handoff
# Blob layout: MAGIC + u16 version + u32 manifest length + manifest JSON
# + concatenated array payload.  The manifest lists one entry per
# session: its metadata, the [offset, offset+length) payload slice its
# arrays occupy, and a SHA-256 over (canonical metadata JSON + slice).
# Arrays are packed as plain ``np.save`` segments (allow_pickle=False on
# the way back in) under a tiny recursive tree spec, so the ctx bundle's
# nested tuples survive without pickle.
#
# Version 2 (round 19): entries additionally pack the GRU hidden-state
# tree (``StereoSession.hidden``, the warm-h chain's second state half)
# and the manifest carries the EXPORTING engine's exec-config
# fingerprint so an importer with a different compiled surface (other
# model config / iters / h-family knobs) degrades TYPED instead of
# silently installing state its programs cannot consume.  Version-1
# blobs (no hidden, no fingerprint) are rejected by the version check —
# their sessions cold-start, the documented degrade.
HANDOFF_MAGIC = b"RSTPU-SESS"
HANDOFF_VERSION = 2

# Array trees one session entry packs (in spec order).
_RECORD_ARRAYS = ("flow_low", "thumb", "ctx", "hidden")

# StereoSession counters that ride the handoff verbatim.
_RECORD_COUNTERS = ("frame_index", "warm_frames", "cold_frames",
                    "scene_cuts", "ctx_hits", "iters_used_sum",
                    "iters_used_frames")


def _pack_tree(obj, out: io.BytesIO):
    """Spec node for one array tree: ndarray leaves become np.save
    segments appended to ``out`` (offsets relative to the session's
    payload slice); tuples/lists recurse; None passes through.  Raises
    ``TypeError`` on anything else — the caller decides whether that
    drops the leaf's whole tree (ctx) or the session."""
    if obj is None:
        return {"k": "none"}
    if isinstance(obj, np.ndarray):
        start = out.tell()
        np.save(out, obj, allow_pickle=False)
        return {"k": "nd", "o": start, "n": out.tell() - start}
    if isinstance(obj, (tuple, list)):
        return {"k": "tuple" if isinstance(obj, tuple) else "list",
                "items": [_pack_tree(x, out) for x in obj]}
    raise TypeError(f"unserializable handoff leaf: {type(obj).__name__}")


def _unpack_tree(spec, payload: bytes):
    kind = spec["k"]
    if kind == "none":
        return None
    if kind == "nd":
        seg = payload[spec["o"]:spec["o"] + spec["n"]]
        return np.load(io.BytesIO(seg), allow_pickle=False)
    if kind in ("tuple", "list"):
        items = [_unpack_tree(s, payload) for s in spec["items"]]
        return tuple(items) if kind == "tuple" else items
    raise ValueError(f"unknown handoff tree node {kind!r}")


def _entry_digest(meta: Dict[str, object], payload: bytes) -> str:
    h = hashlib.sha256()
    h.update(json.dumps(meta, sort_keys=True, default=str).encode())
    h.update(payload)
    return h.hexdigest()


def export_sessions_blob(records: Iterable[Tuple[Dict[str, object],
                                                 Dict[str, object]]],
                         config_fingerprint: Optional[str] = None
                         ) -> bytes:
    """Serialize ``(meta, arrays)`` session records (see
    ``StereoSession.to_record``) into one handoff blob.
    ``config_fingerprint`` (engine.exec_config_fingerprint) stamps the
    manifest so an importer with a DIFFERENT compiled surface (model
    config / iters / h-family knobs) can refuse the whole blob typed
    instead of installing state its programs cannot consume."""
    entries: List[Dict[str, object]] = []
    body = io.BytesIO()
    for meta, arrays in records:
        seg = io.BytesIO()
        spec: Dict[str, object] = {}
        for name in ("flow_low", "thumb"):
            spec[name] = _pack_tree(arrays.get(name), seg)
        for name in ("ctx", "hidden"):
            mark = seg.tell()
            try:
                spec[name] = _pack_tree(arrays.get(name), seg)
            except (TypeError, ValueError, OSError):
                # These trees can carry backend-exotic leaves (bf16 via
                # ml_dtypes) np.save may refuse.  Warmth only needs the
                # flow: drop the tree — the ctx bundle re-establishes at
                # the next cold ctx frame on the importer, and a missing
                # hidden tree demotes that session's first inherited
                # frame to a cold start (the r14 baseline, never a torn
                # state).
                seg.seek(mark)
                seg.truncate()
                spec[name] = {"k": "none"}
        payload = seg.getvalue()
        entries.append({"id": meta["session_id"], "meta": meta,
                        "spec": spec, "offset": body.tell(),
                        "length": len(payload),
                        "sha256": _entry_digest(meta, payload)})
        body.write(payload)
    manifest = json.dumps({"version": HANDOFF_VERSION,
                           "config_fingerprint": config_fingerprint,
                           "sessions": entries}).encode()
    return (HANDOFF_MAGIC + struct.pack("<HI", HANDOFF_VERSION,
                                        len(manifest))
            + manifest + body.getvalue())


def handoff_session_ids(blob: bytes) -> List[str]:
    """The session ids a handoff blob claims to carry (header-only read;
    [] on anything unparseable)."""
    manifest = _handoff_manifest(blob)
    if manifest is None:
        return []
    return [str(e.get("id")) for e in manifest.get("sessions", ())]


def handoff_fingerprint(blob: bytes) -> Optional[str]:
    """The exporting engine's exec-config fingerprint a handoff blob
    was stamped with (header-only read; None on anything unparseable or
    an unstamped blob)."""
    manifest = _handoff_manifest(blob)
    if manifest is None:
        return None
    fp = manifest.get("config_fingerprint")
    return str(fp) if fp is not None else None


def _handoff_manifest(blob: bytes) -> Optional[Dict[str, object]]:
    try:
        if not blob.startswith(HANDOFF_MAGIC):
            return None
        off = len(HANDOFF_MAGIC)
        version, mlen = struct.unpack_from("<HI", blob, off)
        if version != HANDOFF_VERSION:
            log.warning("handoff blob version %d != %d; ignoring "
                        "(sessions cold-start)", version, HANDOFF_VERSION)
            return None
        start = off + struct.calcsize("<HI")
        return json.loads(blob[start:start + mlen])
    except (struct.error, ValueError, UnicodeDecodeError):
        log.warning("unparseable handoff blob header; ignoring "
                    "(sessions cold-start)", exc_info=True)
        return None


def parse_handoff_blob(blob: bytes
                       ) -> Tuple[Dict[str, Tuple[Dict[str, object],
                                                  Dict[str, object]]],
                                  int]:
    """Decode a handoff blob into ``{sid: (meta, arrays)}`` plus the
    count of entries SKIPPED (checksum mismatch, truncation, undecodable
    arrays).  Never raises: total garbage returns ``({}, 0)`` — the
    affected sessions simply cold-start, which is the r14 baseline, not
    a failure."""
    manifest = _handoff_manifest(blob)
    if manifest is None:
        return {}, 0
    # The header's manifest length field is authoritative
    # (re-serializing the parsed manifest need not be byte-identical).
    _, mlen = struct.unpack_from("<HI", blob, len(HANDOFF_MAGIC))
    body_start = len(HANDOFF_MAGIC) + struct.calcsize("<HI") + mlen
    body = blob[body_start:]
    out: Dict[str, Tuple[Dict[str, object], Dict[str, object]]] = {}
    skipped = 0
    for entry in manifest.get("sessions", ()):
        try:
            payload = body[entry["offset"]:entry["offset"]
                           + entry["length"]]
            if len(payload) != entry["length"]:
                raise ValueError("truncated payload slice")
            meta = entry["meta"]
            if _entry_digest(meta, payload) != entry["sha256"]:
                raise ValueError("checksum mismatch")
            arrays = {name: _unpack_tree(
                          entry["spec"].get(name, {"k": "none"}), payload)
                      for name in _RECORD_ARRAYS}
            out[str(entry["id"])] = (meta, arrays)
        except Exception:   # noqa: BLE001 — per-entry degradation
            skipped += 1
            log.warning("handoff entry %r corrupt; that session will "
                        "cold-start", entry.get("id"), exc_info=True)
    return out, skipped


@dataclasses.dataclass
class StereoSession:
    """One client stream's temporal state.  ``flow_low`` is the previous
    frame's PADDED low-res x-flow (= -disparity, shape
    (Hp/f, Wp/f) float32) — exactly the tensor the model's ``flow_init``
    consumes; ``None`` until the first frame completes.  Mutated only
    under the store lock or while the session's ordering lock is held."""

    session_id: str
    created_mono: float
    last_used_mono: float
    bucket: Optional[Tuple[int, int]] = None   # padded (Hp, Wp) of state
    raw_shape: Optional[Tuple[int, int]] = None
    flow_low: Optional[np.ndarray] = None
    thumb: Optional[np.ndarray] = None
    # Cached CONTEXT bundle (engine session_ctx_cache): the per-level
    # initial GRU hidden states + context biases a cold state_ctx frame
    # computed, reused by warm_ctx frames while the inter-frame delta
    # proves the scene static; None until a cold frame saves one (and
    # again after any invalidation — scene cut, keyframe guard, a warm
    # frame past the static-scene gate).
    ctx: Optional[object] = None
    ctx_hits: int = 0             # frames served with the cached context
    # Final per-level GRU hidden states of the previous frame (tuple of
    # batch-axis-free host arrays) — the warm-h chain's second state
    # half (round 19, ``ServeConfig.session_hidden``).  Carried and
    # invalidated in LOCKSTEP with ``flow_low``: scene cuts, the
    # keyframe guard, and crash demotion drop both, so a warm-h frame
    # never mixes a fresh disparity with a stale trajectory.
    hidden: Optional[object] = None
    # Registered-model PIN (round 21 multi-model serving): the model
    # name this stream's first frame resolved to, or None for the
    # implicit model.  Every later frame dispatches against the pinned
    # model — a stream never mixes weights mid-flight — and the pin
    # rides the handoff meta so an importer that doesn't serve it
    # degrades typed-cold instead of warm-starting on other weights.
    model: Optional[str] = None
    frame_index: int = 0          # frames COMPLETED (the next frame's index)
    warm_frames: int = 0
    cold_frames: int = 0
    scene_cuts: int = 0
    iters_used_sum: int = 0
    iters_used_frames: int = 0
    # Per-frame mean confidence accumulation (round 24 quality
    # observability; fed only when the engine serves with
    # ``ServeConfig.confidence``): the close stats report the stream's
    # lifetime mean and its last frame — the per-stream "was this stream
    # healthy" answer.  Advisory telemetry: deliberately NOT in the
    # handoff record (an imported stream restarts its quality history).
    confidence_sum: float = 0.0
    confidence_frames: int = 0
    confidence_last: Optional[float] = None
    # Frame-ordering lock (see module docstring): held from submit until
    # the frame's future resolves, so one session never has two frames
    # in flight and a dispatch cycle can never reorder them.
    order_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    def note_result(self, flow_low: Optional[np.ndarray],
                    thumb: Optional[np.ndarray],
                    bucket: Tuple[int, int], raw_shape: Tuple[int, int],
                    warm: bool, iters_used: Optional[int],
                    hidden: Optional[object] = None,
                    confidence: Optional[float] = None) -> None:
        """Fold one completed frame into the state (called by the engine
        while ``order_lock`` is held, so no torn reads are possible).
        ``flow_low=None`` drops the warm-start state — the engine's
        keyframe guard passes None when the frame never converged, so
        the next frame cold-starts.  ``hidden`` rides (and drops) with
        it: a dropped flow with a kept trajectory would be exactly the
        torn state the lockstep rule forbids."""
        self.flow_low = flow_low
        self.hidden = hidden if flow_low is not None else None
        self.thumb = thumb
        self.bucket = tuple(bucket)
        self.raw_shape = tuple(raw_shape)
        self.frame_index += 1
        if warm:
            self.warm_frames += 1
        else:
            self.cold_frames += 1
        if iters_used is not None:
            self.iters_used_sum += int(iters_used)
            self.iters_used_frames += 1
        if confidence is not None:
            self.confidence_sum += float(confidence)
            self.confidence_frames += 1
            self.confidence_last = float(confidence)

    def to_record(self) -> Tuple[Dict[str, object], Dict[str, object]]:
        """``(meta, arrays)`` snapshot for the handoff blob.  The caller
        must hold ``order_lock`` (the exporter does), so the fields are
        a consistent post-frame state, never a torn mid-dispatch one."""
        meta: Dict[str, object] = {"session_id": self.session_id,
                                   "bucket": (list(self.bucket)
                                              if self.bucket else None),
                                   "raw_shape": (list(self.raw_shape)
                                                 if self.raw_shape
                                                 else None)}
        for name in _RECORD_COUNTERS:
            meta[name] = int(getattr(self, name))
        if self.model is not None:
            # Only when pinned: implicit-model records stay byte-
            # identical to pre-registry blobs (same digest, same meta).
            meta["model"] = self.model
        return meta, {"flow_low": self.flow_low, "thumb": self.thumb,
                      "ctx": self.ctx, "hidden": self.hidden}

    def apply_record(self, meta: Dict[str, object],
                     arrays: Dict[str, object]) -> None:
        """Install a handed-off state into this (fresh) session: the
        next frame then warm-starts exactly as if the previous frame had
        completed locally.  Caller holds ``order_lock``."""
        self.bucket = (tuple(meta["bucket"]) if meta.get("bucket")
                       else None)
        self.raw_shape = (tuple(meta["raw_shape"])
                          if meta.get("raw_shape") else None)
        for name in _RECORD_COUNTERS:
            setattr(self, name, int(meta.get(name, 0)))
        self.model = meta.get("model") or None
        self.flow_low = arrays.get("flow_low")
        self.thumb = arrays.get("thumb")
        self.ctx = arrays.get("ctx")
        self.hidden = arrays.get("hidden")

    def iters_used_mean(self) -> Optional[float]:
        """Per-session mean GRU trip count — the number the close stats
        and the streaming bench report per stream."""
        if not self.iters_used_frames:
            return None
        return self.iters_used_sum / self.iters_used_frames

    def confidence_mean(self) -> Optional[float]:
        """Lifetime mean per-frame confidence; None unless the engine
        served this stream with confidence telemetry on."""
        if not self.confidence_frames:
            return None
        return self.confidence_sum / self.confidence_frames

    def stats(self) -> Dict[str, object]:
        out = {
            "session_id": self.session_id,
            **({"model": self.model} if self.model is not None else {}),
            "frames": self.frame_index,
            "warm_frames": self.warm_frames,
            "cold_frames": self.cold_frames,
            "scene_cuts": self.scene_cuts,
            "ctx_cache_hits": self.ctx_hits,
            "iters_used_mean": (round(self.iters_used_mean(), 3)
                                if self.iters_used_mean() is not None
                                else None),
        }
        if self.confidence_frames:
            # Only when fed: confidence-off close stats stay
            # byte-identical to the round-23 payload.
            out["confidence_mean"] = round(self.confidence_mean(), 4)
            out["confidence_last"] = round(self.confidence_last, 4)
        return out


class SessionStore:
    """Thread-safe session table: id → ``StereoSession`` with TTL expiry,
    LRU capacity eviction, and tombstoned removal (``SessionExpired``).

    ``clock`` is injectable (tests pin expiry deterministically).  The
    optional ``active_gauge`` / ``expired_counter`` / ``evicted_counter``
    instruments keep ``serve_sessions_*`` live without the store
    importing the metrics module."""

    def __init__(self, capacity: int = 256, ttl_s: float = 30.0,
                 clock=time.monotonic, active_gauge=None,
                 created_counter=None, expired_counter=None,
                 evicted_counter=None):
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s={ttl_s} must be > 0")
        self.capacity = capacity
        self.ttl_s = ttl_s
        self._clock = clock
        self._lock = threading.Lock()
        self._sessions: "OrderedDict[str, StereoSession]" = OrderedDict()
        # id -> (reason, tombstone_mono); bounded at 4x capacity and aged
        # out after ttl_s, so dead ids 410 for one TTL window and then
        # become creatable again.
        self._tombstones: "OrderedDict[str, Tuple[str, float]]" = (
            OrderedDict())
        self._active_gauge = active_gauge
        self._created = created_counter
        self._expired = expired_counter
        self._evicted = evicted_counter

    # ----------------------------------------------------------- internals
    def _note_active(self) -> None:
        if self._active_gauge is not None:
            self._active_gauge.set(len(self._sessions))

    def _bury(self, sid: str, reason: str, now: float) -> None:
        self._tombstones[sid] = (reason, now)
        self._tombstones.move_to_end(sid)
        while len(self._tombstones) > 4 * self.capacity:
            self._tombstones.popitem(last=False)
        if reason == "expired" and self._expired is not None:
            self._expired.inc()
        if reason == "evicted" and self._evicted is not None:
            self._evicted.inc()

    def _sweep_locked(self, now: float) -> None:
        """Expire TTL-stale sessions and aged-out tombstones.  Sessions
        iterate in last-used order (every touch moves to the back), so
        the scan stops at the first live one.  A session whose ordering
        lock is held has a frame IN FLIGHT (a first-frame compile can
        outlast a short TTL) — it is skipped, and the frame's completion
        callback touches it back to freshness."""
        expired = []
        for sid, sess in self._sessions.items():
            if now - sess.last_used_mono <= self.ttl_s:
                break
            if sess.order_lock.locked():
                continue
            expired.append(sid)
        for sid in expired:
            del self._sessions[sid]
            self._bury(sid, "expired", now)
        while self._tombstones:
            sid, (_reason, t) = next(iter(self._tombstones.items()))
            if now - t <= self.ttl_s:
                break
            del self._tombstones[sid]
        self._note_active()

    def _check_tombstone_locked(self, sid: str) -> None:
        entry = self._tombstones.get(sid)
        if entry is not None:
            raise SessionExpired(sid, entry[0])

    # -------------------------------------------------------------- surface
    def get_or_create(self, sid: str) -> Tuple[StereoSession, bool]:
        """The session for ``sid``, creating it on first use.  Returns
        ``(session, created)``.  Raises ``SessionExpired`` when the id is
        tombstoned (expired / evicted / closed within the last TTL
        window) — the 410 contract: a broken stream must be re-opened
        explicitly, never silently restarted."""
        now = self._clock()
        with self._lock:
            self._sweep_locked(now)
            sess = self._sessions.get(sid)
            if sess is not None:
                sess.last_used_mono = now
                self._sessions.move_to_end(sid)
                return sess, False
            self._check_tombstone_locked(sid)
            while len(self._sessions) >= self.capacity:
                evicted_id, _ = self._sessions.popitem(last=False)
                self._bury(evicted_id, "evicted", now)
            sess = StereoSession(session_id=sid, created_mono=now,
                                 last_used_mono=now)
            self._sessions[sid] = sess
            if self._created is not None:
                self._created.inc()
            self._note_active()
            return sess, True

    def get(self, sid: str) -> StereoSession:
        """The live session for ``sid``; ``SessionExpired`` on a
        tombstone, plain ``KeyError`` on an id this store never saw."""
        now = self._clock()
        with self._lock:
            self._sweep_locked(now)
            sess = self._sessions.get(sid)
            if sess is None:
                self._check_tombstone_locked(sid)
                raise KeyError(sid)
            sess.last_used_mono = now
            self._sessions.move_to_end(sid)
            return sess

    def touch(self, sid: str) -> None:
        """Refresh ``sid``'s last-used stamp (no-op on unknown ids) —
        the frame-completion callback calls this so a long dispatch
        counts as activity, not idleness."""
        now = self._clock()
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is not None:
                sess.last_used_mono = now
                self._sessions.move_to_end(sid)

    def close(self, sid: str) -> Dict[str, object]:
        """End one session deliberately: removes it and returns its
        lifetime stats (the DELETE response body).  The id tombstones as
        ``"closed"`` for one TTL window so a straggler frame racing the
        close gets the typed 410, not a silent new session."""
        now = self._clock()
        with self._lock:
            self._sweep_locked(now)
            sess = self._sessions.pop(sid, None)
            if sess is None:
                self._check_tombstone_locked(sid)
                raise KeyError(sid)
            self._bury(sid, "closed", now)
            self._note_active()
        return sess.stats()

    # -------------------------------------------------------------- handoff
    def export(self, config_fingerprint: Optional[str] = None) -> bytes:
        """Serialize every live session into one versioned, checksummed
        handoff blob (the graceful-drain path; engine.publish_handoff).
        Acquires each session's ordering lock, so a frame still in
        flight completes — and folds its state in — before that session
        is captured; with admission already stopped (begin_shutdown)
        every lock wait is bounded by one frame's latency.
        ``config_fingerprint`` stamps the blob with the exporter's
        exec-config identity (round-19 mismatch-typed import)."""
        with self._lock:
            self._sweep_locked(self._clock())
            sessions = list(self._sessions.values())
        records = []
        for sess in sessions:
            with sess.order_lock:
                records.append(sess.to_record())
        return export_sessions_blob(records,
                                    config_fingerprint=config_fingerprint)

    def import_(self, blob: bytes, overwrite: bool = False,
                expect_fingerprint: Optional[str] = None
                ) -> Tuple[int, int]:
        """Bulk-install a handoff blob's sessions; returns ``(imported,
        skipped)``.  Corrupt entries, tombstoned ids, and (without
        ``overwrite``) ids already live here are skipped — an import can
        only ever ADD warmth, never clobber a stream this store is
        actively serving or resurrect one it deliberately killed.
        With ``expect_fingerprint`` set, a blob stamped with a DIFFERENT
        exporter fingerprint is refused wholesale — every session counts
        skipped (the typed config-mismatch degrade; the engine's lazy
        adoption path applies the same check with its own metric)."""
        if expect_fingerprint is not None:
            stamped = handoff_fingerprint(blob)
            if stamped is not None and stamped != expect_fingerprint:
                n = len(handoff_session_ids(blob))
                log.warning(
                    "handoff blob exec-config fingerprint %.12s != this "
                    "store's %.12s; refusing %d session(s) — they "
                    "cold-start (config_mismatch)", stamped,
                    expect_fingerprint, n)
                return 0, n
        records, skipped = parse_handoff_blob(blob)
        now = self._clock()
        imported = 0
        with self._lock:
            self._sweep_locked(now)
            for sid, (meta, arrays) in records.items():
                if sid in self._tombstones:
                    skipped += 1
                    continue
                if sid in self._sessions and not overwrite:
                    skipped += 1
                    continue
                sess = StereoSession(session_id=sid, created_mono=now,
                                     last_used_mono=now)
                sess.apply_record(meta, arrays)
                while len(self._sessions) >= self.capacity \
                        and sid not in self._sessions:
                    evicted_id, _ = self._sessions.popitem(last=False)
                    self._bury(evicted_id, "evicted", now)
                self._sessions[sid] = sess
                self._sessions.move_to_end(sid)
                imported += 1
            self._note_active()
        return imported, skipped

    def adopt(self, sess: StereoSession, meta: Dict[str, object],
              arrays: Dict[str, object]) -> None:
        """Install one handed-off record into an already-created session
        (the LAZY import path: the engine creates the session at the
        frame's arrival and adopts state before deciding warm vs cold).
        Caller holds the session's ordering lock."""
        sess.apply_record(meta, arrays)

    def sweep(self) -> None:
        """Eagerly expire TTL-stale sessions (every access sweeps too —
        this is for idle-time housekeeping / tests)."""
        with self._lock:
            self._sweep_locked(self._clock())

    @property
    def active_count(self) -> int:
        with self._lock:
            self._sweep_locked(self._clock())
            return len(self._sessions)

    def __len__(self) -> int:
        return self.active_count
