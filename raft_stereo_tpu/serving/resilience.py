"""Failure containment policies for the serving engine: per-device
circuit breakers and brownout degradation.

Both are deliberately dumb, testable state machines over signals the
engine already measures — no new probes, no model awareness:

* ``CircuitBreaker`` — one per device worker.  K consecutive dispatch
  failures open the circuit (the worker stops taking work: a device that
  fails every dispatch must not keep eating the queue through the retry
  path); after a cooldown the breaker goes half-open and admits ONE
  probe batch; a probe success closes it, a probe failure reopens it
  with the cooldown restarted.  Modeled on the classic pattern (Nygard,
  *Release It!*), with the half-open probe giving a flapping device a
  bounded, automatic way back in.
* ``BrownoutController`` — the load-shedding step BEFORE shedding.  The
  round-12 tier ladder (interactive/balanced/quality) prices the same
  request at three GRU depths, so sustained overload has a cheaper
  answer than a 503: degrade eligible requests one rung down the ladder
  and keep answering.  Engage/restore use the same signals as the
  ServingWatchdog's alarms (queue saturation, deadline-miss rate) with
  hysteresis — engaging needs sustained pressure, restoring needs a
  longer sustained calm at a LOWER watermark, so the controller cannot
  flap at the boundary.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

# serve_circuit_state gauge values (docs/architecture.md §Resilience).
CIRCUIT_CLOSED, CIRCUIT_OPEN, CIRCUIT_HALF_OPEN = 0, 1, 2
_STATE_NAMES = {CIRCUIT_CLOSED: "closed", CIRCUIT_OPEN: "open",
                CIRCUIT_HALF_OPEN: "half_open"}


def circuit_state_name(state: int) -> str:
    return _STATE_NAMES.get(state, str(state))


class CircuitBreaker:
    """Per-device dispatch gate: closed -> (K consecutive failures) ->
    open -> (cooldown) -> half-open -> one probe -> closed | open.

    ``on_state(old, new, consecutive_failures)`` fires on every
    transition (the engine wires the ``serve_circuit_state`` gauge and
    the anomaly events there).  Thread-safe; the worker loop calls
    ``until_allowed`` before popping and ``record_success`` /
    ``record_failure`` after each dispatch.
    """

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_state: Optional[Callable[[int, int, int], None]] = None):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold={failure_threshold} must be >= 1")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s={cooldown_s} must be > 0")
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._on_state = on_state
        self._lock = threading.Lock()
        self._state = CIRCUIT_CLOSED
        self._failures = 0          # consecutive
        self._opened_at: Optional[float] = None
        self._probe_out = False     # half-open: one probe in flight

    # ------------------------------------------------------------- state
    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def _transition(self, new: int) -> None:
        """Caller holds the lock."""
        old, self._state = self._state, new
        if old != new and self._on_state is not None:
            # Fire outside the lock would be nicer, but the callbacks are
            # a gauge.set + an event emit — reentry into the breaker is
            # the only real hazard and none of the wired callbacks do it.
            self._on_state(old, new, self._failures)

    def until_allowed(self) -> float:
        """0.0 when the worker may take a batch now, else seconds until
        the next transition is due.  In half-open, only the single probe
        dispatch is admitted; a second caller waits for its verdict."""
        with self._lock:
            if self._state == CIRCUIT_CLOSED:
                return 0.0
            now = self._clock()
            if self._state == CIRCUIT_OPEN:
                remaining = self._opened_at + self.cooldown_s - now
                if remaining > 0:
                    return remaining
                self._transition(CIRCUIT_HALF_OPEN)
                self._probe_out = False
            # half-open: admit exactly one probe at a time
            if self._probe_out:
                return self.cooldown_s / 4
            self._probe_out = True
            return 0.0

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_out = False
            if self._state != CIRCUIT_CLOSED:
                self._transition(CIRCUIT_CLOSED)

    def record_failure(self) -> bool:
        """Returns True when this failure OPENED the circuit."""
        with self._lock:
            self._failures += 1
            self._probe_out = False
            if self._state == CIRCUIT_HALF_OPEN:
                # failed probe: straight back to open, cooldown restarts
                self._opened_at = self._clock()
                self._transition(CIRCUIT_OPEN)
                return True
            if (self._state == CIRCUIT_CLOSED
                    and self._failures >= self.failure_threshold):
                self._opened_at = self._clock()
                self._transition(CIRCUIT_OPEN)
                return True
        return False


class BrownoutController:
    """Tier-ladder degradation under sustained overload, with hysteresis.

    ``ladder`` orders tier names cheapest-first (the engine derives it
    from the configured tiers by early-exit threshold: highest threshold
    = earliest exit = cheapest; fixed-depth tiers are the most
    expensive).  ``level`` is how many rungs every eligible request is
    pushed down: 0 = off, 1 = quality->balanced / balanced->interactive,
    up to ``len(ladder) - 1`` where everything runs the cheapest tier.

    Engage: queue depth >= ``engage_fraction`` of ``max_queue`` on every
    poll for ``engage_s``, OR deadline-miss rate over the poll window
    >= ``miss_rate`` (with ``min_events`` admissions).  Each sustained
    engage window raises the level one rung.  Restore: depth below
    ``restore_fraction`` AND no miss-rate signal for ``restore_s`` —
    longer than ``engage_s`` and at a lower watermark, so a queue
    hovering at the threshold cannot flap the level.
    """

    def __init__(self, metrics, max_queue: int, ladder: Sequence[str],
                 engage_fraction: float = 0.75, engage_s: float = 0.5,
                 restore_fraction: float = 0.25, restore_s: float = 2.0,
                 miss_rate: float = 0.5, min_events: int = 8,
                 poll_s: float = 0.1,
                 clock: Callable[[], float] = time.monotonic,
                 gauge=None, sink=None):
        if not 0 < restore_fraction <= engage_fraction <= 1:
            raise ValueError(
                f"need 0 < restore_fraction ({restore_fraction}) <= "
                f"engage_fraction ({engage_fraction}) <= 1")
        self.metrics = metrics
        self.max_queue = max(1, max_queue)
        self.ladder: Tuple[str, ...] = tuple(ladder)
        self.engage_fraction = engage_fraction
        self.engage_s = engage_s
        self.restore_fraction = restore_fraction
        self.restore_s = restore_s
        self.miss_rate = miss_rate
        self.min_events = min_events
        self.poll_s = poll_s
        self._clock = clock
        self._gauge = gauge
        self._sink = sink
        self._lock = threading.Lock()
        self._level = 0
        self._floor = 0     # fleet-wide minimum (set_floor; round 16)
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._prev_admitted = 0
        self._prev_missed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- degrade
    @property
    def level(self) -> int:
        """The EFFECTIVE degradation level: the local pressure state
        machine's rung, or the fleet-wide floor if that is higher (the
        router pushes the floor so every replica steps down together;
        local pressure can still degrade further on top)."""
        with self._lock:
            return max(self._level, self._floor)

    @property
    def floor(self) -> int:
        with self._lock:
            return self._floor

    def set_floor(self, level: int) -> int:
        """Set the fleet-wide minimum level (``POST /admin/brownout`` ->
        engine.set_brownout_floor).  Clamped to the ladder; returns the
        effective level.  The local controller keeps polling its own
        signals — the floor only prevents it from RESTORING below the
        fleet's verdict."""
        level = max(0, min(int(level), self.max_level))
        with self._lock:
            old_eff = max(self._level, self._floor)
            self._floor = level
            eff = max(self._level, self._floor)
            if self._gauge is not None:
                self._gauge.set(eff)
        if eff != old_eff:
            log.warning("brownout floor set to %d (effective level "
                        "%d -> %d, fleet-pushed)", level, old_eff, eff)
            if self._sink is not None:
                self._sink.fire("brownout_engaged" if eff > old_eff
                                else "brownout_restored",
                                level=eff, previous_level=old_eff,
                                reason="fleet_floor", floor=level,
                                ladder=list(self.ladder))
        return eff

    @property
    def max_level(self) -> int:
        return max(0, len(self.ladder) - 1)

    # Mean request confidence BELOW which a request is spared from
    # degradation (round 24 quality observability): brownout exists to
    # shed compute from requests that can afford it, and a
    # low-confidence stream is exactly the one that cannot — pushing it
    # down the ladder converts a latency problem into a quality
    # incident.  The engine feeds the requester's recent rolling mean
    # confidence (telemetry/quality.QualityTracker) when available.
    spare_below: float = 0.0

    def degrade(self, tier: Optional[str],
                confidence: Optional[float] = None) -> Optional[str]:
        """The tier a request actually runs at the current level: its
        requested tier pushed ``level`` rungs toward the cheap end of the
        ladder.  Tiers off the ladder (and None) pass through.

        ``confidence`` is the principled victim-selection signal: when
        given and below ``spare_below``, the request passes through
        undegraded — recent answers at its tier were already
        low-confidence, so it NEEDS the expensive program.  None (the
        default, and always when confidence telemetry is off) keeps the
        round-13 ladder behavior byte-for-byte."""
        lvl = self.level
        if lvl == 0 or tier is None or tier not in self.ladder:
            return tier
        if confidence is not None and confidence < self.spare_below:
            return tier
        idx = self.ladder.index(tier)
        return self.ladder[max(0, idx - lvl)]

    # ------------------------------------------------------------- poll
    def _set_level(self, new: int, reason: str, **detail) -> None:
        """Caller holds the lock."""
        old, self._level = self._level, new
        if self._gauge is not None:
            self._gauge.set(max(new, self._floor))
        log.warning("brownout level %d -> %d (%s)", old, new, reason)
        if self._sink is not None:
            self._sink.fire("brownout_engaged" if new > old
                            else "brownout_restored",
                            level=new, previous_level=old, reason=reason,
                            ladder=list(self.ladder), **detail)

    def check(self) -> int:
        """One poll; returns the (possibly changed) level.  Public for
        tests — the poll thread calls exactly this."""
        now = self._clock()
        depth = self.metrics.queue_depth.value
        admitted = self.metrics.admitted.value
        missed = self.metrics.deadline_missed.value
        d_adm = admitted - self._prev_admitted
        d_miss = missed - self._prev_missed
        self._prev_admitted, self._prev_missed = admitted, missed
        missing = (d_adm >= self.min_events
                   and d_miss / d_adm >= self.miss_rate)
        saturated = depth >= self.engage_fraction * self.max_queue
        calm = (depth <= self.restore_fraction * self.max_queue
                and not missing)

        with self._lock:
            if saturated or missing:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                elif (now - self._pressure_since >= self.engage_s
                        and self._level < self.max_level):
                    self._set_level(
                        self._level + 1,
                        "deadline_miss_rate" if missing
                        else "queue_saturation",
                        queue_depth=int(depth), max_queue=self.max_queue,
                        missed=int(d_miss), admitted=int(d_adm))
                    self._pressure_since = now  # next rung needs its own
                    #                             sustained window
            elif calm:
                self._pressure_since = None
                if self._level > 0:
                    if self._calm_since is None:
                        self._calm_since = now
                    elif now - self._calm_since >= self.restore_s:
                        self._set_level(self._level - 1, "load_restored",
                                        queue_depth=int(depth))
                        self._calm_since = now
                else:
                    self._calm_since = None
            else:
                # between the watermarks: hold level, reset both timers —
                # this band is the hysteresis.
                self._pressure_since = None
                self._calm_since = None
            return self._level

    def start(self) -> "BrownoutController":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="brownout-controller")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check()
            except Exception:  # pragma: no cover - controller must not die
                log.exception("brownout poll failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def cost_ladder(tiers) -> List[str]:
    """Tier names cheapest-first for the brownout ladder: higher
    early-exit threshold = earlier exit = cheaper; fixed-depth tiers
    (threshold <= 0) are the most expensive; at equal exit knobs an
    int8 tier is cheaper than the full-precision one (it moves a
    fraction of the bytes per iteration — the round-15 "turbo" tier
    sits below "interactive" as the ladder's bottom rung).  Ties keep
    configuration order.  ``tiers`` is a sequence of
    ``config.RequestTier``."""
    order = sorted(
        enumerate(tiers),
        key=lambda it: (it[1].exit_threshold_px <= 0,
                        -it[1].exit_threshold_px
                        if it[1].exit_threshold_px > 0 else 0,
                        getattr(it[1], "quant", "off") == "off",
                        it[0]))
    return [t.name for _, t in order]
