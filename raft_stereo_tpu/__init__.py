"""raft_stereo_tpu — a TPU-native (JAX/XLA/Pallas) stereo-depth framework.

Re-designs the capabilities of RAFT-Stereo (reference: /root/reference, arXiv
2109.07547) TPU-first: NHWC layouts, flax modules, `lax.scan` over GRU
refinement iterations, XLA/Pallas correlation backends, and SPMD data
parallelism over a `jax.sharding.Mesh`.
"""

from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig

__version__ = "0.1.0"

__all__ = ["RaftStereoConfig", "TrainConfig", "__version__"]
