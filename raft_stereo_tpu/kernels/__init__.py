"""Pallas TPU kernel family — public API.

Three kernels share one contract (docs/architecture.md §Kernels): an
``*_available()`` capability gate, a VMEM working-set fit check that sizes
(or vetoes) the launch, the package-wide interpret override so the tier-1
CPU suite runs the kernel code path through the HLO interpreter, and a
transparent fallback to the pure-XLA path when unavailable.

The ``*_q`` variants are the round-15 int8 entries: same kernels reading
int8 volumes/features with the in-kernel fp32 upcast acting as the
in-register dequant (callers apply the scales — docs/architecture.md
§Quantization).  Forward-only by design; the fp custom-VJP entries stay
the training path.

Callers import from HERE; the submodules' underscored helpers are
implementation detail.
"""

from raft_stereo_tpu.kernels.corr_alt import (alt_fused_available,
                                              alt_fused_fits,
                                              alt_lookup_fused,
                                              alt_lookup_fused_q)
from raft_stereo_tpu.kernels.corr_lookup import (fused_lookup_available,
                                                 interpret_enabled,
                                                 lookup_pyramid_fused,
                                                 lookup_pyramid_fused_q)
from raft_stereo_tpu.kernels.gru_fused import (gru_fused_available,
                                               gru_fused_row_block,
                                               gru_fused_should_use,
                                               gru_gates_fused)

__all__ = [
    "alt_fused_available",
    "alt_fused_fits",
    "alt_lookup_fused",
    "alt_lookup_fused_q",
    "fused_lookup_available",
    "gru_fused_available",
    "gru_fused_row_block",
    "gru_fused_should_use",
    "gru_gates_fused",
    "interpret_enabled",
    "lookup_pyramid_fused",
    "lookup_pyramid_fused_q",
]
