"""Pallas TPU kernel: fused correlation-pyramid window lookup.

TPU-native replacement for the reference's CUDA extension
(reference: sampler/sampler_kernel.cu — one thread per output pixel streaming
2r+2 taps along the disparity axis; hand-written scatter backward).

Placeholder in this milestone: the XLA lookup in models/corr.py is the live
path; the fused kernel lands with the performance phase (SURVEY.md §7 step 9).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp


def fused_lookup_available() -> bool:
    return False


def lookup_pyramid_fused(pyramid: List[jnp.ndarray], coords: jnp.ndarray,
                         radius: int) -> jnp.ndarray:
    raise NotImplementedError("Pallas fused lookup lands in the perf phase")
