"""Pallas TPU kernel: fused correlation-pyramid window lookup.

TPU-native replacement for the reference's CUDA extension (reference:
sampler/sampler.cpp + sampler/sampler_kernel.cu): sample a (2r+1)-tap window
of the 1-D correlation volume at fractional disparity positions, with linear
interpolation and zero padding, in the volume's own dtype (bf16-safe — the
whole point of the reference's fp16 CUDA path, sampler_kernel.cu:126).

Design: gathers are hostile to the TPU vector unit, so the kernel never
gathers.  For tap k the interpolation weight of volume bin x at center c is
the hat function  max(0, 1 - |x - (c + k - r)|)  — nonzero for at most the
two bins the reference's CUDA kernel reads (sampler_kernel.cu:46-59).  Each
(rows × W1-block) tile computes, per tap, an elementwise weight field over
the whole W2 axis and a multiply-reduce — pure VPU work on contiguous lanes,
O(K·W2) per pixel instead of a 2-bin gather, which wins on TPU because it
vectorizes and the volume tile is already in VMEM.

Backward mirrors the reference's hand-written scatter kernel
(sampler_kernel.cu:64-105) but needs no atomics: dV[x] = Σ_k g_k·hat_k(x) is
again an elementwise multiply-accumulate.  Like the reference's
``CorrSampler.backward`` (core/corr.py:24-29), no coordinate gradient is
produced — RAFT-Stereo detaches coords before every lookup
(core/raft_stereo.py:109).
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_BLK = 8       # (batch·H) rows per tile
W1_BLK = 128      # output pixels per tile (lane-aligned)

_interpret_override: Optional[bool] = None


def fused_lookup_available() -> bool:
    if _interpret_override:  # interpret mode works on any backend
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def interpret_enabled() -> bool:
    """True when kernels run via the HLO interpreter (CPU tests)."""
    return bool(_interpret_override)


_interpret = interpret_enabled  # internal alias


# -------------------------------------------------- shared hat-sample math
# The hat-function formulation (module docstring) shared by this kernel and
# the fused no-volume kernel (kernels/corr_alt.py) — one implementation so
# boundary/interpolation semantics can never diverge between them.
def hat_sample(v, centers, radius: int):
    """Σ_x v[..., x] · hat_k(x) for each tap k: (R, W1B, W2) tile +
    (R, W1B) centers → per-tap sampler yielding (R, W1B) slices."""
    w2 = v.shape[-1]
    xs = jax.lax.broadcasted_iota(jnp.int32, (1, 1, w2), 2).astype(jnp.float32)
    for k in range(2 * radius + 1):
        pos = centers + (k - radius)                  # (R, W1B)
        w = jnp.maximum(0.0, 1.0 - jnp.abs(xs - pos[..., None]))
        yield k, jnp.sum(v * w, axis=-1)


def hat_scatter(g, centers, w2: int, radius: int):
    """Transpose of :func:`hat_sample`: (R, W1B, K) cotangent + centers
    → (R, W1B, W2) volume cotangent."""
    xs = jax.lax.broadcasted_iota(jnp.int32, (1, 1, w2), 2).astype(jnp.float32)
    acc = jnp.zeros(centers.shape + (w2,), jnp.float32)
    for k in range(2 * radius + 1):
        pos = centers + (k - radius)
        w = jnp.maximum(0.0, 1.0 - jnp.abs(xs - pos[..., None]))
        acc = acc + g[:, :, k][..., None] * w
    return acc


# ------------------------------------------------------------------ kernels
def _fwd_kernel(vol_ref, coords_ref, out_ref, *, radius: int, scale: float):
    """One (ROW_BLK, W1_BLK) tile: volume (R, W1B, W2) + centers (R, W1B)
    → window samples (R, W1B, K)."""
    vol = vol_ref[:].astype(jnp.float32)              # (R, W1B, W2)
    centers = coords_ref[:].astype(jnp.float32) * scale   # (R, W1B)
    for k, sample in hat_sample(vol, centers, radius):
        out_ref[:, :, k] = sample.astype(out_ref.dtype)


def _bwd_kernel(coords_ref, g_ref, dvol_ref, *, radius: int, scale: float):
    """Tile transpose of the forward: g (R, W1B, K) → dV (R, W1B, W2)."""
    centers = coords_ref[:].astype(jnp.float32) * scale
    g = g_ref[:].astype(jnp.float32)
    dvol = hat_scatter(g, centers, dvol_ref.shape[-1], radius)
    dvol_ref[:] = dvol.astype(dvol_ref.dtype)


# ------------------------------------------------------------------- launch
def _launch_fwd(vol: jnp.ndarray, coords: jnp.ndarray, radius: int,
                scale: float) -> jnp.ndarray:
    rows, w1, w2 = vol.shape
    k = 2 * radius + 1
    grid = (pl.cdiv(rows, ROW_BLK), pl.cdiv(w1, W1_BLK))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, radius=radius, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_BLK, W1_BLK, w2), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROW_BLK, W1_BLK), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROW_BLK, W1_BLK, k), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, w1, k), vol.dtype),
        interpret=_interpret(),
    )(vol, coords)


def _launch_bwd(coords: jnp.ndarray, g: jnp.ndarray, w2: int, radius: int,
                scale: float, dtype) -> jnp.ndarray:
    rows, w1 = coords.shape
    k = 2 * radius + 1
    grid = (pl.cdiv(rows, ROW_BLK), pl.cdiv(w1, W1_BLK))
    return pl.pallas_call(
        functools.partial(_bwd_kernel, radius=radius, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_BLK, W1_BLK), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROW_BLK, W1_BLK, k), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROW_BLK, W1_BLK, w2), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, w1, w2), dtype),
        interpret=_interpret(),
    )(coords, g)


# ----------------------------------------------------------- level sampling
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _sample_level(vol, coords, radius: int, scale: float):
    """(B,H,W1,W2) volume + (B,H,W1) centers → (B,H,W1,2r+1) window."""
    b, h, w1, w2 = vol.shape
    out = _launch_fwd(vol.reshape(b * h, w1, w2),
                      coords.reshape(b * h, w1), radius, scale)
    return out.reshape(b, h, w1, -1)


def _sample_level_fwd(vol, coords, radius, scale):
    # vol rides along only for its STATIC shape/dtype; its values are unused
    # in the backward, so XLA dead-code-eliminates the residual.
    return _sample_level(vol, coords, radius, scale), (vol, coords)


def _sample_level_bwd(radius, scale, residuals, g):
    vol, coords = residuals
    b, h, w1, w2 = vol.shape
    dvol = _launch_bwd(coords.reshape(b * h, w1),
                       g.reshape(b * h, w1, -1), w2, radius, scale,
                       vol.dtype)
    # No coords grad: RAFT detaches coords before every lookup, and the
    # reference kernel's backward also only produces volume gradients.
    return dvol.reshape(vol.shape), jnp.zeros_like(coords)


_sample_level.defvjp(_sample_level_fwd, _sample_level_bwd)


def lookup_pyramid_fused(pyramid: List[jnp.ndarray], coords: jnp.ndarray,
                         radius: int) -> jnp.ndarray:
    """Fused window lookup at every pyramid level, concat level-major —
    drop-in replacement for ``lookup_pyramid_xla`` (models/corr.py)."""
    outs = [_sample_level(vol, coords, radius, 1.0 / (2 ** i))
            for i, vol in enumerate(pyramid)]
    return jnp.concatenate(outs, axis=-1)
