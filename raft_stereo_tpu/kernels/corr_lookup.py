"""Pallas TPU kernel: fused correlation-pyramid window lookup.

TPU-native replacement for the reference's CUDA extension (reference:
sampler/sampler.cpp + sampler/sampler_kernel.cu): sample a (2r+1)-tap window
of the 1-D correlation volume at fractional disparity positions, with linear
interpolation and zero padding, in the volume's own dtype (bf16-safe — the
whole point of the reference's fp16 CUDA path, sampler_kernel.cu:126).

Design: gathers are hostile to the TPU vector unit, so the kernel never
gathers.  For tap k the interpolation weight of volume bin x at center c is
the hat function  max(0, 1 - |x - (c + k - r)|)  — nonzero for at most the
two bins the reference's CUDA kernel reads (sampler_kernel.cu:46-59).  Each
(rows × W1-block) tile computes, per tap, an elementwise weight field over
the whole W2 axis and a multiply-reduce — pure VPU work on contiguous lanes,
O(K·W2) per pixel instead of a 2-bin gather, which wins on TPU because it
vectorizes and the volume tile is already in VMEM.

Backward mirrors the reference's hand-written scatter kernel
(sampler_kernel.cu:64-105) but needs no atomics: dV[x] = Σ_k g_k·hat_k(x) is
again an elementwise multiply-accumulate.  Like the reference's
``CorrSampler.backward`` (core/corr.py:24-29), no coordinate gradient is
produced — RAFT-Stereo detaches coords before every lookup
(core/raft_stereo.py:109).
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_BLK = 8       # (batch·H) rows per tile
W1_BLK = 128      # output pixels per tile (lane-aligned)

# Single per-program VMEM budget shared by ALL correlation kernels in this
# package (this module and kernels/corr_alt.py).  Mosaic FAILS TO COMPILE
# (no fallback) when a program's live set exceeds VMEM, so every launch
# either gates on a working-set estimate or shrinks its row block with
# ``row_blk_for`` until it fits.
VMEM_BUDGET = 8 * 2 ** 20


def row_blk_for(per_row_bytes: int) -> int:
    """Largest power-of-two row block (≤ ROW_BLK) whose per-program working
    set fits ``VMEM_BUDGET``; callers pass bytes-per-row-of-ROW_BLK=1."""
    rb = ROW_BLK
    while rb > 1 and rb * per_row_bytes > VMEM_BUDGET:
        rb //= 2
    return rb


def _lookup_row_bytes(w2: int, radius: int, itemsize: int) -> int:
    """Per-row working set of the single-level lookup kernels: volume tile
    (input + fp32 upcast), hat field, product/scatter intermediate, out."""
    fp32 = 4
    k = 2 * radius + 1
    return W1_BLK * (w2 * (itemsize + fp32)
                     + (w2 + 2 * radius) * fp32
                     + w2 * fp32
                     + k * fp32)

_interpret_override: Optional[bool] = None


def fused_lookup_available() -> bool:
    if _interpret_override:  # interpret mode works on any backend
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def interpret_enabled() -> bool:
    """True when kernels run via the HLO interpreter (CPU tests)."""
    return bool(_interpret_override)


_interpret = interpret_enabled  # internal alias


# ------------------------------------------------- fp8 q-entry capability
# float8_e4m3 correlation entries: same itemsize as int8 (the VMEM-fit
# estimators below are already itemsize-parameterized, so every budget
# holds unchanged), but a FLOAT grid — denser near zero where the
# post-softargmax correlation mass lives.  Availability is a separate
# capability from the fused kernels themselves: the dtype must exist in
# this jax build AND the backend must execute it (interpret mode counts
# — CPU parity tests run the same kernel body through the interpreter).
# The grid is OCP E4M3 (``float8_e4m3fn``: finite-only, max 448 — the
# variant TPU/GPU fp8 units implement), not the IEEE ``float8_e4m3``
# whose 240 finite max would overflow the 448-referenced scales.
FP8_CORR_DTYPE = getattr(jnp, "float8_e4m3fn", None)


def fp8_corr_available() -> bool:
    """Whether fp8 correlation q-entries can run here: gate BEFORE
    building an fp8 pyramid (models/corr.corr_q_dtype falls back to
    int8 when this is False — same transparent-fallback contract as
    fused_lookup_available)."""
    if FP8_CORR_DTYPE is None:  # pragma: no cover - all jax>=0.4.31
        return False
    if _interpret_override:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _q_dtypes_supported():
    out = [jnp.dtype(jnp.int8)]
    if FP8_CORR_DTYPE is not None:
        out.append(jnp.dtype(FP8_CORR_DTYPE))
    return tuple(out)


def check_q_dtype(pyramid, q_dtype):
    """Validate one q-entry call's dtype coordinate: every level must
    carry ``q_dtype`` (None = infer from level 0), and the dtype must be
    a supported quantized grid.  Returns the resolved ``jnp.dtype``."""
    q_dtype = jnp.dtype(q_dtype if q_dtype is not None
                        else pyramid[0].dtype)
    if q_dtype not in _q_dtypes_supported():
        raise ValueError(
            f"q_dtype={q_dtype} not a supported quantized grid "
            f"{tuple(str(d) for d in _q_dtypes_supported())}")
    bad = [str(v.dtype) for v in pyramid if jnp.dtype(v.dtype) != q_dtype]
    if bad:
        raise ValueError(
            f"q-entry levels must all be {q_dtype}; got {bad}")
    if (FP8_CORR_DTYPE is not None
            and q_dtype == jnp.dtype(FP8_CORR_DTYPE)
            and not fp8_corr_available()):
        raise ValueError(
            "fp8 correlation entries are unavailable on this backend "
            "(fp8_corr_available() is False) — quantize int8 instead")
    return q_dtype


# -------------------------------------------------- shared hat-sample math
# The hat-function formulation (module docstring) shared by this kernel and
# the fused no-volume kernel (kernels/corr_alt.py) — one implementation so
# boundary/interpolation semantics can never diverge between them.
def _hat_field(centers, w2: int, radius: int):
    """Shared per-tap weights: tap k's weight at bin x is
    ``max(0, 1-|x - centers - (k-radius)|)`` = F[x + 2·radius - k] where
    F[j] = max(0, 1-|j - radius - centers|) over j ∈ [0, w2+2·radius).
    Computing F ONCE and slicing per tap replaces ~6 vector passes per tap
    (iota, sub, abs, sub, max, mul) with 2 (mul, add) — the training-trace
    finding that the VPU weight construction, not DMA or launch overhead,
    dominates the lookup (docs/TRAIN_PROFILE.md)."""
    ext = w2 + 2 * radius
    xs = jax.lax.broadcasted_iota(jnp.int32, (1, 1, ext), 2).astype(jnp.float32)
    return jnp.maximum(0.0, 1.0 - jnp.abs(xs - radius - centers[..., None]))


def hat_sample(v, centers, radius: int):
    """Σ_x v[..., x] · hat_k(x) for each tap k: (R, W1B, W2) tile +
    (R, W1B) centers → per-tap sampler yielding (R, W1B) slices."""
    w2 = v.shape[-1]
    f = _hat_field(centers, w2, radius)
    for k in range(2 * radius + 1):
        off = 2 * radius - k
        yield k, jnp.sum(v * f[:, :, off:off + w2], axis=-1)


def hat_scatter(g, centers, w2: int, radius: int):
    """Transpose of :func:`hat_sample`: (R, W1B, K) cotangent + centers
    → (R, W1B, W2) volume cotangent."""
    f = _hat_field(centers, w2, radius)
    acc = jnp.zeros(centers.shape + (w2,), jnp.float32)
    for k in range(2 * radius + 1):
        off = 2 * radius - k
        acc = acc + g[:, :, k][..., None] * f[:, :, off:off + w2]
    return acc


# ------------------------------------------------------------------ kernels
def _fwd_kernel(vol_ref, coords_ref, out_ref, *, radius: int, scale: float):
    """One (row-block, W1_BLK) tile: volume (R, W1B, W2) + centers
    (R, W1B, 1) → window samples (R, W1B, K)."""
    vol = vol_ref[:].astype(jnp.float32)              # (R, W1B, W2)
    centers = coords_ref[:, :, 0].astype(jnp.float32) * scale   # (R, W1B)
    for k, sample in hat_sample(vol, centers, radius):
        out_ref[:, :, k] = sample.astype(out_ref.dtype)


def _bwd_kernel(coords_ref, g_ref, dvol_ref, *, radius: int, scale: float):
    """Tile transpose of the forward: g (R, W1B, K) → dV (R, W1B, W2)."""
    centers = coords_ref[:, :, 0].astype(jnp.float32) * scale
    g = g_ref[:].astype(jnp.float32)
    dvol = hat_scatter(g, centers, dvol_ref.shape[-1], radius)
    dvol_ref[:] = dvol.astype(dvol_ref.dtype)


# ------------------------------------------------------------------- launch
# coords blocks carry a trailing singleton so the (8, 128)-divisibility rule
# on the last two block dims keeps holding when the row block shrinks below
# 8 for VMEM (large W2).
def _launch_fwd(vol: jnp.ndarray, coords: jnp.ndarray, radius: int,
                scale: float, out_dtype=None) -> jnp.ndarray:
    # ``out_dtype`` (default: the volume's own dtype) exists for the
    # int8 pyramid path: an int8 volume samples to fp values (the
    # in-kernel fp32 upcast IS the in-register dequant modulo the
    # per-level scale the caller applies), so the output must not
    # round-trip through int8.
    rows, w1, w2 = vol.shape
    k = 2 * radius + 1
    rb = row_blk_for(_lookup_row_bytes(w2, radius, vol.dtype.itemsize))
    grid = (pl.cdiv(rows, rb), pl.cdiv(w1, W1_BLK))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, radius=radius, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, W1_BLK, w2), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, W1_BLK, 1), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rb, W1_BLK, k), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, w1, k),
                                       out_dtype or vol.dtype),
        interpret=_interpret(),
    )(vol, coords[..., None])


def _launch_bwd(coords: jnp.ndarray, g: jnp.ndarray, w2: int, radius: int,
                scale: float, dtype) -> jnp.ndarray:
    rows, w1 = coords.shape
    k = 2 * radius + 1
    rb = row_blk_for(_lookup_row_bytes(w2, radius, jnp.dtype(dtype).itemsize))
    grid = (pl.cdiv(rows, rb), pl.cdiv(w1, W1_BLK))
    return pl.pallas_call(
        functools.partial(_bwd_kernel, radius=radius, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, W1_BLK, 1), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, W1_BLK, k), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rb, W1_BLK, w2), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, w1, w2), dtype),
        interpret=_interpret(),
    )(coords[..., None], g)


# ----------------------------------------------------------- level sampling
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _sample_level(vol, coords, radius: int, scale: float):
    """(B,H,W1,W2) volume + (B,H,W1) centers → (B,H,W1,2r+1) window."""
    b, h, w1, w2 = vol.shape
    out = _launch_fwd(vol.reshape(b * h, w1, w2),
                      coords.reshape(b * h, w1), radius, scale)
    return out.reshape(b, h, w1, -1)


def _sample_level_fwd(vol, coords, radius, scale):
    # vol rides along only for its STATIC shape/dtype; its values are unused
    # in the backward, so XLA dead-code-eliminates the residual.
    return _sample_level(vol, coords, radius, scale), (vol, coords)


def _sample_level_bwd(radius, scale, residuals, g):
    vol, coords = residuals
    b, h, w1, w2 = vol.shape
    dvol = _launch_bwd(coords.reshape(b * h, w1),
                       g.reshape(b * h, w1, -1), w2, radius, scale,
                       vol.dtype)
    # No coords grad: RAFT detaches coords before every lookup, and the
    # reference kernel's backward also only produces volume gradients.
    return dvol.reshape(vol.shape), jnp.zeros_like(coords)


_sample_level.defvjp(_sample_level_fwd, _sample_level_bwd)


# ----------------------------------------- single-launch all-levels lookup
# Training-trace finding (docs/TRAIN_PROFILE.md): each custom call inside the
# 22-iteration scan carries ~1 ms of in-graph overhead/stall far above its
# isolated runtime (26 us), so 12 per-iteration launches (4 fwd + 4 remat
# recompute + 4 bwd) dominate the step.  Sampling EVERY level in one launch
# (and all level cotangents in one backward launch) cuts that to 3.  The
# levels stay separate pallas_call operands — no concatenated-volume copy.

def _fwd_kernel_multi(*refs, radius: int, levels: int):
    coords = refs[levels][:, :, 0].astype(jnp.float32)
    out_ref = refs[levels + 1]
    k = 2 * radius + 1
    for i in range(levels):
        vol = refs[i][:].astype(jnp.float32)
        centers = coords * (1.0 / (2 ** i))
        for kk, sample in hat_sample(vol, centers, radius):
            out_ref[:, :, i * k + kk] = sample.astype(out_ref.dtype)


def _bwd_kernel_multi(coords_ref, g_ref, *dvol_refs, radius: int,
                      levels: int):
    coords = coords_ref[:, :, 0].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    k = 2 * radius + 1
    for i in range(levels):
        centers = coords * (1.0 / (2 ** i))
        dvol = hat_scatter(g[:, :, i * k:(i + 1) * k], centers,
                           dvol_refs[i].shape[-1], radius)
        dvol_refs[i][:] = dvol.astype(dvol_refs[i].dtype)


def _launch_fwd_multi(vols, coords, radius: int, out_dtype=None):
    rows, w1 = coords.shape
    levels = len(vols)
    k = 2 * radius + 1
    grid = (pl.cdiv(rows, ROW_BLK), pl.cdiv(w1, W1_BLK))
    return pl.pallas_call(
        functools.partial(_fwd_kernel_multi, radius=radius, levels=levels),
        grid=grid,
        in_specs=[pl.BlockSpec((ROW_BLK, W1_BLK, v.shape[-1]),
                               lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM) for v in vols]
                 + [pl.BlockSpec((ROW_BLK, W1_BLK, 1), lambda i, j: (i, j, 0),
                                 memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((ROW_BLK, W1_BLK, levels * k),
                               lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, w1, levels * k),
                                       out_dtype or vols[0].dtype),
        interpret=_interpret(),
    )(*vols, coords[..., None])


def _launch_bwd_multi(coords, g, w2s, radius: int, dtype):
    rows, w1 = coords.shape
    levels = len(w2s)
    k = 2 * radius + 1
    grid = (pl.cdiv(rows, ROW_BLK), pl.cdiv(w1, W1_BLK))
    return pl.pallas_call(
        functools.partial(_bwd_kernel_multi, radius=radius, levels=levels),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_BLK, W1_BLK, 1), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROW_BLK, W1_BLK, levels * k), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[pl.BlockSpec((ROW_BLK, W1_BLK, w2), lambda i, j: (i, j, 0),
                                memory_space=pltpu.VMEM) for w2 in w2s],
        out_shape=[jax.ShapeDtypeStruct((rows, w1, w2), dtype)
                   for w2 in w2s],
        interpret=_interpret(),
    )(coords[..., None], g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _sample_pyramid(vols, coords, radius: int):
    """Tuple of (B,H,W1,W2_i) volumes + (B,H,W1) centers →
    (B,H,W1,levels·(2r+1)) window samples, concat level-major."""
    b, h, w1, _ = vols[0].shape
    out = _launch_fwd_multi([v.reshape(b * h, w1, v.shape[-1]) for v in vols],
                            coords.reshape(b * h, w1), radius)
    return out.reshape(b, h, w1, -1)


def _sample_pyramid_fwd(vols, coords, radius):
    # volumes ride along for static shape/dtype only; values unused in bwd
    return _sample_pyramid(vols, coords, radius), (vols, coords)


def _sample_pyramid_bwd(radius, residuals, g):
    vols, coords = residuals
    b, h, w1, _ = vols[0].shape
    dvols = _launch_bwd_multi(coords.reshape(b * h, w1),
                              g.reshape(b * h, w1, -1),
                              [v.shape[-1] for v in vols], radius,
                              vols[0].dtype)
    return (tuple(d.reshape(b, h, w1, -1) for d in dvols),
            jnp.zeros_like(coords))


_sample_pyramid.defvjp(_sample_pyramid_fwd, _sample_pyramid_bwd)


def _multi_working_set(w2s, radius: int, itemsize: int) -> int:
    """Bytes one program of ``_fwd_kernel_multi`` holds live: per level the
    input tile, its fp32 upcast, and the (w2+2r)-wide fp32 hat field; plus
    the per-tap multiply-reduce product (one level live at a time — sized by
    the widest level, matching the ``w2 * fp32`` term ``_lookup_row_bytes``
    counts so the two estimators agree) and the all-levels output tile."""
    fp32 = 4
    k = 2 * radius + 1
    per_level = sum(
        ROW_BLK * W1_BLK * (w2 * (itemsize + fp32) + (w2 + 2 * radius) * fp32)
        for w2 in w2s)
    return (per_level
            + ROW_BLK * W1_BLK * max(w2s) * fp32
            + ROW_BLK * W1_BLK * len(w2s) * k * fp32)


def lookup_pyramid_fused(pyramid: List[jnp.ndarray], coords: jnp.ndarray,
                         radius: int) -> jnp.ndarray:
    """Fused window lookup at every pyramid level, concat level-major —
    drop-in replacement for ``lookup_pyramid_xla`` (models/corr.py).

    Uses the single-launch all-levels kernel when every level's tile fits
    the per-program VMEM budget together; otherwise one launch per level
    (full-resolution volumes grow ~linearly in W2 and must not turn a
    previously-working eval into a Mosaic VMEM compile failure)."""
    w2s = [v.shape[-1] for v in pyramid]
    if (len(pyramid) > 1 and _multi_working_set(
            w2s, radius, pyramid[0].dtype.itemsize) <= VMEM_BUDGET):
        return _sample_pyramid(tuple(pyramid), coords, radius)
    outs = [_sample_level(vol, coords, radius, 1.0 / (2 ** i))
            for i, vol in enumerate(pyramid)]
    return jnp.concatenate(outs, axis=-1)


# -------------------------------------------------- quantized pyramid entry
def lookup_pyramid_fused_q(pyramid: List[jnp.ndarray],
                           coords: jnp.ndarray, radius: int,
                           out_dtype, q_dtype=None) -> jnp.ndarray:
    """Fused window lookup over a QUANTIZED pyramid (round-15 turbo
    tier; fp8-capable since r22): the kernels read the 1-byte volume
    tiles from HBM — 1/4 (vs fp32) or 1/2 (vs bf16) of the bytes the
    memory-bound lookup moves (COST_REPORT_r10.json roofline) — and the
    in-kernel fp32 upcast of each tile is the in-register dequant.  The
    caller applies the per-level scales to the RAW sampled output
    (models/corr.py): hat sampling is linear, so ``scale * sample(q)``
    equals ``sample(scale * q)`` exactly.

    ``q_dtype`` is the grid coordinate: ``int8`` (default, inferred) or
    ``float8_e4m3`` where ``fp8_corr_available()`` — the kernel body is
    dtype-generic (the upcast handles either), so the coordinate
    validates and gates rather than switching code paths; every VMEM
    fit already keys on the itemsize, identical for both grids.

    Forward-only by design — the quantized tier is inference-only and
    runs under ``stop_gradient`` (the fp custom-VJP entries above stay
    the training path), so no quantized cotangent program exists to get
    wrong.  Same multi-vs-per-level launch selection and VMEM gating as
    ``lookup_pyramid_fused`` (itemsize=1 shrinks the working set, so
    the single-launch path holds to larger shapes)."""
    check_q_dtype(pyramid, q_dtype)
    b, h, w1, _ = pyramid[0].shape
    w2s = [v.shape[-1] for v in pyramid]
    if (len(pyramid) > 1 and _multi_working_set(
            w2s, radius, pyramid[0].dtype.itemsize) <= VMEM_BUDGET):
        out = _launch_fwd_multi(
            [v.reshape(b * h, w1, v.shape[-1]) for v in pyramid],
            coords.reshape(b * h, w1), radius, out_dtype=out_dtype)
        return out.reshape(b, h, w1, -1)
    outs = []
    for i, vol in enumerate(pyramid):
        out = _launch_fwd(vol.reshape(b * h, w1, vol.shape[-1]),
                          coords.reshape(b * h, w1), radius,
                          1.0 / (2 ** i), out_dtype=out_dtype)
        outs.append(out.reshape(b, h, w1, -1))
    return jnp.concatenate(outs, axis=-1)
