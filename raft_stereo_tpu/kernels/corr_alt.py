"""Pallas TPU kernel: fused no-volume ("alt") correlation lookup.

TPU-native replacement for the reference's on-the-fly correlation backend
(reference: core/corr.py:64-107 PytorchAlternateCorrBlock1D), which exists so
full-resolution inputs never materialize the O(B·H·W1·W2) volume (reference:
README.md:121 recommends it for Middlebury-F).  The reference samples right-
feature windows with ``grid_sample`` and dots them with left features; on TPU
both the gather and the tiny dot products are hostile.

This kernel uses the algebraic identity

    out[w, k] = Σ_d f1[w, d] · interp_k(f2)[w, d]
              = hat_k ⊛ (f1 · f2ᵀ)[w, :]

i.e. a linear-interpolated feature dot product IS a hat-function reduction of
one row-block of the correlation volume.  So each (row, W1-block) tile:

  1. computes its volume tile  v = f1_tile @ f2_rowᵀ / √D  on the MXU,
     entirely in VMEM (never written to HBM — the fusion of SURVEY.md §7's
     kernels 9b and 9c), then
  2. hat-samples v exactly like the reg_fused lookup kernel
     (kernels/corr_lookup.py).

Per iteration this recomputes the volume tile (alt's memory/compute trade);
across ``corr_levels`` the right features come from the W-pooled pyramid the
XLA side builds once.

Backward (custom VJP, mirroring the identity):
    dv[w, x] = Σ_k g[w, k] · hat_k(x)        (the reg_fused backward kernel)
    df1      = dv @ f2
    df2      = dvᵀ @ f1
both matmuls fused into the same tile pass, so the backward never
materializes the volume either.  No coordinate gradient (RAFT detaches
coords each iteration — reference core/raft_stereo.py:109).
"""

from __future__ import annotations

import functools
import math
from typing import List

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.kernels.corr_lookup import (ROW_BLK, VMEM_BUDGET,
                                                 W1_BLK,
                                                 fused_lookup_available,
                                                 hat_sample, hat_scatter,
                                                 row_blk_for,
                                                 interpret_enabled as
                                                 _interpret)


def alt_fused_available() -> bool:
    return fused_lookup_available()


def alt_fused_fits(w2: int, d: int, itemsize: int, radius: int) -> bool:
    """False when even a ONE-row block of the (larger) backward launch
    exceeds the VMEM budget — row_blk_for cannot shrink below 1, so callers
    must fall back to the XLA path (make_corr_fn_alt) instead of hitting a
    Mosaic compile failure (e.g. W2 beyond ~4k at d=256 fp32)."""
    fp32 = 4
    bwd_row = (_fwd_row_bytes(W1_BLK, w2, d, itemsize, radius)
               + W1_BLK * d * fp32      # df1 tile
               + w2 * d * fp32          # df2 accumulator tile
               + W1_BLK * w2 * fp32)    # dv tile
    return bwd_row <= VMEM_BUDGET


# ------------------------------------------------------------------ kernels
def _fwd_kernel(f1_ref, f2_ref, coords_ref, out_ref, *, radius: int,
                scale: float, inv_sqrt_d: float, precision):
    """(R, W1B, D) left tile + (R, W2, D) right rows + (R, W1B) centers
    → (R, W1B, K) window correlations."""
    f1 = f1_ref[:].astype(jnp.float32)
    f2 = f2_ref[:].astype(jnp.float32)
    # Volume tile on the MXU, VMEM-resident only: (R, W1B, W2).
    v = jax.lax.dot_general(f1, f2, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32,
                            precision=precision) * inv_sqrt_d
    centers = coords_ref[:, :, 0].astype(jnp.float32) * scale
    for k, sample in hat_sample(v, centers, radius):
        out_ref[:, :, k] = sample.astype(out_ref.dtype)


def _bwd_kernel(f1_ref, f2_ref, coords_ref, g_ref, df1_ref, df2_ref, *,
                radius: int, scale: float, inv_sqrt_d: float,
                rows_total: int, w1_total: int, precision):
    """Tile transpose: reconstruct dv from the output cotangent with hat
    weights, then both feature gradients as matmuls of dv.

    df2 is accumulated over W1 blocks (grid dim 1): each block owns the same
    (R, W2, D) df2 tile, so the kernel adds into it after zeroing on the
    first block — Pallas TPU grids execute sequentially per core, making the
    accumulation race-free.

    dv is masked to the logical (rows, W1) extent: df2 reduces over the W1
    axis, so block-padding garbage (NaN in interpret mode) would otherwise
    contaminate every output element.
    """
    f1 = f1_ref[:].astype(jnp.float32)
    f2 = f2_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)          # (R, W1B, K)
    w2 = f2_ref.shape[1]
    centers = coords_ref[:, :, 0].astype(jnp.float32) * scale
    dv = hat_scatter(g, centers, w2, radius)   # (R, W1B, W2)
    r_blk, w1_blk = centers.shape
    row_idx = (pl.program_id(0) * r_blk
               + jax.lax.broadcasted_iota(jnp.int32, (r_blk, w1_blk, 1), 0))
    col_idx = (pl.program_id(1) * w1_blk
               + jax.lax.broadcasted_iota(jnp.int32, (r_blk, w1_blk, 1), 1))
    valid = (row_idx < rows_total) & (col_idx < w1_total)
    dv = jnp.where(valid, dv * inv_sqrt_d, 0.0)
    # df2 contracts over W1, so f1's padding must be zeroed as well:
    # 0 (masked dv) x NaN (padded f1) would still poison the reduction.
    f1 = jnp.where(valid, f1, 0.0)
    # df1[r, w1, d] = Σ_x dv[r, w1, x] f2[r, x, d]
    df1_ref[:] = jax.lax.dot_general(
        dv, f2, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=precision).astype(df1_ref.dtype)
    # df2[r, x, d] = Σ_w1 dv[r, w1, x] f1[r, w1, d], accumulated over blocks
    contrib = jax.lax.dot_general(
        dv, f1, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32, precision=precision)

    @pl.when(pl.program_id(1) == 0)
    def _zero():
        df2_ref[:] = jnp.zeros_like(df2_ref)

    df2_ref[:] += contrib.astype(df2_ref.dtype)


# ------------------------------------------------------------------- launch
def _precision_for(dtype) -> jax.lax.Precision:
    """fp32 features pay for exact (HIGHEST) MXU passes, matching the reg
    backend bit-for-bit; bf16 features take the fast single-pass path (the
    same trade the reference's fp16 CUDA kernel makes)."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


# Mosaic fails to compile (not fall back) when a program's live set exceeds
# VMEM, and at Middlebury-F scale (w2=496, d=256) the default ROW_BLK=8
# working set is ~12 MB before double buffering — so large shapes shrink the
# row block via the package-shared budget (corr_lookup.row_blk_for).
def _fwd_row_bytes(w1_blk, w2, d, itemsize, radius):
    fp32 = 4
    return (w2 * d * (itemsize + fp32)          # f2 rows: input + upcast
            + w1_blk * d * (itemsize + fp32)    # f1 tile: input + upcast
            + w1_blk * w2 * fp32                # volume tile
            + w1_blk * (w2 + 2 * radius) * fp32  # hat field
            + w1_blk * w2 * fp32)               # product intermediate


def _launch_fwd(f1, f2, coords, radius, scale, inv_sqrt_d,
                out_dtype=None):
    # ``out_dtype`` (default: f1's own dtype) exists for the int8
    # feature path: int8 features correlate to fp values (the in-kernel
    # fp32 upcast is the in-register dequant modulo the feature scales
    # the caller applies), so the output must not round through int8.
    rows, w1, d = f1.shape
    w2 = f2.shape[1]
    k = 2 * radius + 1
    rb = row_blk_for(_fwd_row_bytes(W1_BLK, w2, d, f1.dtype.itemsize,
                                    radius))
    grid = (pl.cdiv(rows, rb), pl.cdiv(w1, W1_BLK))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, radius=radius, scale=scale,
                          inv_sqrt_d=inv_sqrt_d,
                          precision=_precision_for(f1.dtype)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, W1_BLK, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, w2, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, W1_BLK, 1), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rb, W1_BLK, k), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, w1, k),
                                       out_dtype or f1.dtype),
        interpret=_interpret(),
    )(f1, f2, coords[..., None])


def _launch_bwd(f1, f2, coords, g, radius, scale, inv_sqrt_d):
    rows, w1, d = f1.shape
    w2 = f2.shape[1]
    k = 2 * radius + 1
    fp32 = 4
    rb = row_blk_for(
        _fwd_row_bytes(W1_BLK, w2, d, f1.dtype.itemsize, radius)
        + W1_BLK * d * fp32    # df1 tile
        + w2 * d * fp32        # df2 accumulator tile
        + W1_BLK * w2 * fp32)  # dv tile
    grid = (pl.cdiv(rows, rb), pl.cdiv(w1, W1_BLK))
    return pl.pallas_call(
        functools.partial(_bwd_kernel, radius=radius, scale=scale,
                          inv_sqrt_d=inv_sqrt_d, rows_total=rows,
                          w1_total=w1, precision=_precision_for(f1.dtype)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, W1_BLK, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, w2, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, W1_BLK, 1), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, W1_BLK, k), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rb, W1_BLK, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, w2, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, w1, d), f1.dtype),
            jax.ShapeDtypeStruct((rows, w2, d), f2.dtype),
        ],
        interpret=_interpret(),
    )(f1, f2, coords[..., None], g)


# -------------------------------------------------------------- level entry
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _alt_level(f1, f2, coords, radius: int, scale: float):
    """(B,H,W1,D) left + (B,H,W2,D) right + (B,H,W1) centers
    → (B,H,W1,2r+1) correlations at one pyramid level."""
    b, h, w1, d = f1.shape
    w2 = f2.shape[2]
    inv_sqrt_d = 1.0 / math.sqrt(d)
    out = _launch_fwd(f1.reshape(b * h, w1, d), f2.reshape(b * h, w2, d),
                      coords.reshape(b * h, w1), radius, scale, inv_sqrt_d)
    return out.reshape(b, h, w1, -1)


def _alt_level_fwd(f1, f2, coords, radius, scale):
    return _alt_level(f1, f2, coords, radius, scale), (f1, f2, coords)


def _alt_level_bwd(radius, scale, residuals, g):
    f1, f2, coords = residuals
    b, h, w1, d = f1.shape
    w2 = f2.shape[2]
    inv_sqrt_d = 1.0 / math.sqrt(d)
    df1, df2 = _launch_bwd(f1.reshape(b * h, w1, d),
                           f2.reshape(b * h, w2, d),
                           coords.reshape(b * h, w1),
                           g.reshape(b * h, w1, -1), radius, scale,
                           inv_sqrt_d)
    return (df1.reshape(f1.shape), df2.reshape(f2.shape),
            jnp.zeros_like(coords))


_alt_level.defvjp(_alt_level_fwd, _alt_level_bwd)


# ---------------------------------------------------- multi-level forward
# All pyramid levels in ONE kernel launch: the right-feature pyramid is
# concatenated along W (static level offsets) and each tile computes every
# level's volume slice + hat-samples it in the same pass.  Bit-identical to
# the per-level launches and ~1.5x faster at realtime shapes (410us ->
# 274us measured on a v5e chip) — launch overhead dominates at small W2.
def _fwd_multi_kernel(f1_ref, f2cat_ref, coords_ref, out_ref, *, radius: int,
                      offsets, widths, inv_sqrt_d: float, precision):
    f1 = f1_ref[:].astype(jnp.float32)
    centers0 = coords_ref[:].astype(jnp.float32)
    k = 2 * radius + 1
    for lvl, (off, w2) in enumerate(zip(offsets, widths)):
        f2 = f2cat_ref[:, off:off + w2, :].astype(jnp.float32)
        v = jax.lax.dot_general(f1, f2, (((2,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32,
                                precision=precision) * inv_sqrt_d
        for kk, sample in hat_sample(v, centers0 / (2 ** lvl), radius):
            out_ref[:, :, lvl * k + kk] = sample.astype(out_ref.dtype)




@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _alt_multi(f1, f2cat, coords, static):
    """Single-launch all-levels lookup.  ``static`` = (radius, offsets,
    widths) as hashable tuples."""
    radius, offsets, widths = static
    b, h, w1, d = f1.shape
    wcat = f2cat.shape[2]
    rows = b * h
    k = (2 * radius + 1) * len(offsets)
    grid = (pl.cdiv(rows, ROW_BLK), pl.cdiv(w1, W1_BLK))
    out = pl.pallas_call(
        functools.partial(_fwd_multi_kernel, radius=radius, offsets=offsets,
                          widths=widths, inv_sqrt_d=1.0 / math.sqrt(d),
                          precision=_precision_for(f1.dtype)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_BLK, W1_BLK, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROW_BLK, wcat, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROW_BLK, W1_BLK), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROW_BLK, W1_BLK, k), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, w1, k), f1.dtype),
        interpret=_interpret(),
    )(f1.reshape(rows, w1, d), f2cat.reshape(rows, wcat, d),
      coords.reshape(rows, w1))
    return out.reshape(b, h, w1, k)


def _alt_multi_fwd(f1, f2cat, coords, static):
    return _alt_multi(f1, f2cat, coords, static), (f1, f2cat, coords)


def _alt_multi_bwd(static, residuals, g):
    # Backward calls the per-level backward launch directly (training cost
    # is conv-dominated; the forward launch count is what matters for
    # inference latency).
    radius, offsets, widths = static
    f1, f2cat, coords = residuals
    k = 2 * radius + 1
    df1 = jnp.zeros_like(f1)
    df2_parts = []
    for lvl, (off, w2) in enumerate(zip(offsets, widths)):
        f2 = f2cat[:, :, off:off + w2, :]
        d1, d2, _ = _alt_level_bwd(radius, 1.0 / (2 ** lvl),
                                   (f1, f2, coords),
                                   g[..., lvl * k:(lvl + 1) * k])
        df1 = df1 + d1
        df2_parts.append(d2)
    return df1, jnp.concatenate(df2_parts, axis=2), jnp.zeros_like(coords)


_alt_multi.defvjp(_alt_multi_fwd, _alt_multi_bwd)


# Mosaic's scoped-vmem (kernel stack) limit is 16 MiB on this generation,
# and its stack allocator does NOT reuse buffers across the unrolled level
# loop of `_fwd_multi_kernel` — the live set is the per-level SUM.  One
# hard calibration point: 544x960 fp32 (wcat=450, d=256) FAILS with a
# measured 18.11 MiB scoped allocation where `_multi_alt_scoped_bytes`
# estimates 14.71 MiB — the estimator runs ~1.23x low (compiler
# temporaries it can't see).  The gate threshold therefore sits at
# 16 MiB / 1.28 = 12.5 MiB of ESTIMATED bytes, so the worst gate-passing
# program lands at ~12.5 * 1.23 = 15.4 MiB of real allocation, inside the
# limit.  The realtime shape (wcat=292, bf16) estimates 10.39 MiB and is
# proven to compile and run (bench.py r02/r03).
_MOSAIC_SCOPED_VMEM = int(12.5 * 2 ** 20)


def _multi_alt_scoped_bytes(w2s, d: int, itemsize: int, radius: int) -> int:
    """Estimated Mosaic stack bytes of one `_fwd_multi_kernel` program:
    double-buffered input blocks, fp32 upcast copies (free when the input
    is already fp32), per-level volume + hat-field + product (all live —
    no cross-level reuse), and the double-buffered output block."""
    fp32 = 4
    k = 2 * radius + 1
    wcat = sum(w2s)
    inputs = 2 * ROW_BLK * (wcat + W1_BLK) * d * itemsize
    upcasts = (0 if itemsize == fp32
               else ROW_BLK * (wcat + W1_BLK) * d * fp32)
    per_level = ROW_BLK * W1_BLK * sum(
        2 * w2 + (w2 + 2 * radius) for w2 in w2s) * fp32
    out = 2 * ROW_BLK * W1_BLK * len(w2s) * k * fp32
    return inputs + upcasts + per_level + out


def alt_lookup_fused(fmap1: jnp.ndarray, fmap2_pyramid: List[jnp.ndarray],
                     coords: jnp.ndarray, radius: int) -> jnp.ndarray:
    """Fused no-volume window correlation at every level, concat level-major —
    drop-in for the XLA alt lookup in models/corr.py make_corr_fn_alt.

    Uses the single-launch all-levels kernel when the whole program's
    Mosaic stack estimate fits the scoped-vmem limit; otherwise one launch
    per level (which shrinks row blocks for full-res pyramids)."""
    d = fmap1.shape[-1]
    w2s = [f2.shape[2] for f2 in fmap2_pyramid]
    if (_multi_alt_scoped_bytes(w2s, d, fmap1.dtype.itemsize, radius)
            <= _MOSAIC_SCOPED_VMEM):
        static = (radius,
                  tuple(int(sum(w2s[:i])) for i in range(len(w2s))),
                  tuple(int(w) for w in w2s))
        f2cat = jnp.concatenate(fmap2_pyramid, axis=2)
        return _alt_multi(fmap1, f2cat, coords, static)

    outs = [_alt_level(fmap1, f2, coords, radius, 1.0 / (2 ** i))
            for i, f2 in enumerate(fmap2_pyramid)]
    return jnp.concatenate(outs, axis=-1)


# ----------------------------------------------------- int8 feature entry
def _launch_fwd_multi_q(f1, f2cat, coords, radius: int, offsets, widths,
                        inv_sqrt_d: float, out_dtype):
    """Forward-only single-launch all-levels lookup over int8 features:
    the ``_fwd_multi_kernel`` body unchanged (its fp32 upcast is the
    in-register dequant), only the output dtype overridden."""
    rows, w1, d = f1.shape
    wcat = f2cat.shape[1]
    k = (2 * radius + 1) * len(offsets)
    grid = (pl.cdiv(rows, ROW_BLK), pl.cdiv(w1, W1_BLK))
    return pl.pallas_call(
        functools.partial(_fwd_multi_kernel, radius=radius,
                          offsets=offsets, widths=widths,
                          inv_sqrt_d=inv_sqrt_d,
                          precision=_precision_for(f1.dtype)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_BLK, W1_BLK, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROW_BLK, wcat, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROW_BLK, W1_BLK), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROW_BLK, W1_BLK, k), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, w1, k), out_dtype),
        interpret=_interpret(),
    )(f1, f2cat, coords)


def alt_lookup_fused_q(fmap1_q: jnp.ndarray,
                       fmap2_pyramid_q: List[jnp.ndarray],
                       coords: jnp.ndarray, radius: int,
                       out_dtype, q_dtype=None) -> jnp.ndarray:
    """The no-volume lookup over QUANTIZED feature maps (round-15
    turbo tier; fp8-capable since r22): each tile's volume slice is
    computed on the MXU from 1-byte features upcast in-register — the
    features move 1/4 (vs fp32) or 1/2 (vs bf16) of the HBM bytes per
    iteration.  The RAW quantized-grid correlations come back in
    ``out_dtype``; the caller applies the combined feature scales
    ``s1 * s2_level`` per level (models/corr.py) — the dot product is
    bilinear, so the scales factor out exactly.

    ``q_dtype`` is the shared grid coordinate (``int8`` default /
    ``float8_e4m3`` behind ``fp8_corr_available()``) — validated by the
    same ``check_q_dtype`` contract as ``lookup_pyramid_fused_q``; the
    kernel body is dtype-generic.

    Forward-only (inference tier, under ``stop_gradient``); same
    launch selection and scoped-VMEM gating as ``alt_lookup_fused``
    with the 1-byte itemsize shrinking the estimate."""
    from raft_stereo_tpu.kernels.corr_lookup import check_q_dtype

    check_q_dtype([fmap1_q] + list(fmap2_pyramid_q), q_dtype)
    d = fmap1_q.shape[-1]
    b, h, w1, _ = fmap1_q.shape
    w2s = [f2.shape[2] for f2 in fmap2_pyramid_q]
    rows = b * h
    inv_sqrt_d = 1.0 / math.sqrt(d)
    if (_multi_alt_scoped_bytes(w2s, d, fmap1_q.dtype.itemsize, radius)
            <= _MOSAIC_SCOPED_VMEM):
        offsets = tuple(int(sum(w2s[:i])) for i in range(len(w2s)))
        widths = tuple(int(w) for w in w2s)
        f2cat = jnp.concatenate(fmap2_pyramid_q, axis=2)
        out = _launch_fwd_multi_q(
            fmap1_q.reshape(rows, w1, d),
            f2cat.reshape(rows, sum(w2s), d),
            coords.reshape(rows, w1), radius, offsets, widths,
            inv_sqrt_d, out_dtype)
        return out.reshape(b, h, w1, -1)
    outs = []
    for i, f2 in enumerate(fmap2_pyramid_q):
        out = _launch_fwd(fmap1_q.reshape(rows, w1, d),
                          f2.reshape(rows, f2.shape[2], d),
                          coords.reshape(rows, w1), radius,
                          1.0 / (2 ** i), inv_sqrt_d,
                          out_dtype=out_dtype)
        outs.append(out.reshape(b, h, w1, -1))
    return jnp.concatenate(outs, axis=-1)
