"""Pallas TPU kernel: fused ConvGRU gate pipeline.

The GRU refinement loop is RAFT-Stereo's runtime: at the realtime
configuration the scan body is 89% of inference at 7 iterations
(INFERENCE_PROFILE_r03.json), and its hot block is the ConvGRU gate math in
models/update.py — per level per iteration, XLA dispatches the ``convzr``
conv, the ``convq`` conv, and a trail of pointwise ops (~10 ops/level), each
round-tripping activations through HBM.  This kernel computes BOTH gate
convolutions and the r-gate coupling between them in ONE row-blocked launch,
keeping every intermediate (the ``[h, x]`` concat rows, the pre-activation
``zr``, the recurrence-gated ``[r*h, x]``) in VMEM:

    zr   = conv3x3([h, x], Wzr) + bzr          # MXU, 9 shifted matmuls
    r    = sigmoid(zr[..., Ch:] + cr)          # VPU, fp32
    qpre = conv3x3([r*h, x], Wq) + bq          # MXU

The kernel intentionally stops at the pre-activation outputs ``(zr, qpre)``
— exactly the two tensors models/update.py tags with
``checkpoint_name("gru_gates")``.  The remaining tail
(``sigmoid``/``tanh``/blend) is pure elementwise work that XLA fuses into a
single kernel, and keeping it OUTSIDE the Pallas call is what makes the op
compose with the training remat policy (config.remat_save): with
``"gru_gates"`` saved, the backward's recompute of the scan body rebuilds
``h_out`` from the SAVED gates through the pointwise tail only — the fused
kernel is never re-run (the same shortcut the Flax path gets from its named
conv outputs).

Row blocking / halo scheme: output blocks are ``rb`` image rows; the gate
pipeline needs a 2-row/2-col receptive field (1 for each conv).  Inputs are
zero-padded OUTSIDE the kernel (2 rows/cols for ``[h, x]``, 1 for ``cr`` —
zero padding is exactly the convs' SAME-padding semantics, and ``r*h`` is
automatically 0 wherever ``h`` is padding) and each program reads TWO
row-block views of the same padded array — block ``i`` and block ``i+1`` —
assembling the ``rb+4`` halo rows from block ``i`` plus the first 4 rows of
block ``i+1``.  Block-granular index maps stay legal, no overlapping
BlockSpecs needed; the row pad is extended to ``(nb+1)*rb`` rows so view
``i+1`` never reads out of bounds.  This caps the row block at
``rb >= _MIN_ROW_BLK = 4``.

Backward is a custom VJP over a pure-JAX reference of the same math
(``lax.conv_general_dilated``, the ops the Flax path lowers to): residuals
are the op's INPUTS only, so under ``remat_gru`` the backward never re-runs
the Pallas kernel, and gradients agree with the Flax path to dtype
tolerance (tests/test_gru_fused.py).

Kernel-family contract (shared with corr_lookup.py / corr_alt.py):
``gru_fused_available()`` capability gate, a VMEM working-set fit check that
picks the row block (``gru_fused_row_block``; ``None`` = does not fit, fall
back), the package-wide interpret override so the tier-1 CPU suite runs the
same kernel code path, and a transparent fallback to the Flax conv path —
wired through ``config.fused_gru`` ("auto"|"on"|"off") in models/update.py.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.kernels.corr_alt import _precision_for
from raft_stereo_tpu.kernels.corr_lookup import (VMEM_BUDGET,
                                                 fused_lookup_available,
                                                 interpret_enabled)

ROW_BLK = 8      # default image rows per program
# The two-view halo assembly reads the first 4 rows of the NEXT row block,
# so blocks can never shrink below 4 rows; shapes whose working set still
# exceeds VMEM_BUDGET at rb=4 fall back to the Flax path instead of hitting
# a Mosaic VMEM compile failure (the package-wide rule, corr_lookup.py).
_MIN_ROW_BLK = 4


def gru_fused_available() -> bool:
    """Capability gate: TPU backend, or the package interpret override
    (tier-1 CPU tests run the kernel through the HLO interpreter)."""
    return fused_lookup_available()


# ------------------------------------------------------------ VMEM fit check
def _gates_fixed_bytes(cin: int, ch: int, itemsize: int) -> int:
    """Grid-invariant VMEM residents: both weight tensors + biases."""
    fp32 = 4
    return 9 * cin * 3 * ch * itemsize + 3 * ch * fp32


def _gates_row_bytes(w: int, cin: int, ch: int, itemsize: int) -> int:
    """Per-row working set of one program (scaled by the row block): the two
    halo views of ``[h, x]`` and ``cr``, the fp32 ``zr`` accumulator plus
    one live tap product, the fp32 r / r*h intermediates, the ``[r*h, x]``
    tile, the fp32 ``qpre`` accumulator + tap product, and both output
    blocks."""
    fp32 = 4
    return (2 * (w + 4) * cin * itemsize        # hx views i, i+1
            + 2 * (w + 2) * ch * itemsize       # cr views i, i+1
            + 2 * (w + 2) * 2 * ch * fp32       # zr_ext acc + tap product
            + 2 * (w + 2) * ch * fp32           # r, r*h (fp32)
            + (w + 2) * cin * itemsize          # [r*h, x] tile
            + 2 * w * ch * fp32                 # qpre acc + tap product
            + w * 3 * ch * itemsize)            # zr + qpre output blocks


def gru_fused_row_block(w: int, cin: int, ch: int,
                        itemsize: int) -> Optional[int]:
    """Largest power-of-two row block (<= ROW_BLK, >= 4) whose working set
    fits ``VMEM_BUDGET``; ``None`` when even rb=4 does not fit (very wide
    levels — full-res W with no W-blocking) and the caller must fall back."""
    fixed = _gates_fixed_bytes(cin, ch, itemsize)
    per_row = _gates_row_bytes(w, cin, ch, itemsize)
    rb = ROW_BLK
    while rb > _MIN_ROW_BLK and fixed + rb * per_row > VMEM_BUDGET:
        rb //= 2
    if fixed + rb * per_row > VMEM_BUDGET:
        return None
    return rb


def gru_fused_should_use(mode: str, *, kernel_size: int, w: int, cin: int,
                         ch: int, itemsize: int) -> bool:
    """Dispatch decision for one GRU level at trace time.

    ``auto``: use the kernel iff the backend supports it AND the level's
    working set fits VMEM — silent fallback otherwise (no workload breaks).
    ``on``: force the kernel; raise with the specific reason when it cannot
    run (explicit user intent should not silently degrade).
    ``off``: never (bitwise-preserves the Flax graph)."""
    if mode == "off":
        return False
    if mode not in ("auto", "on"):
        raise ValueError(f"fused_gru={mode!r} not in ('auto', 'on', 'off')")
    available = gru_fused_available() and kernel_size == 3
    rb = (gru_fused_row_block(w, cin, ch, itemsize) if available else None)
    if mode == "on":
        if not available:
            raise RuntimeError(
                "fused_gru='on' but the fused ConvGRU kernel is unavailable "
                f"(backend={jax.default_backend()!r}, "
                f"kernel_size={kernel_size}); use 'auto' for transparent "
                "fallback")
        if rb is None:
            raise RuntimeError(
                f"fused_gru='on' but the level working set (W={w}, Cin={cin},"
                f" Ch={ch}) exceeds the VMEM budget even at the minimum row "
                "block; use 'auto' for transparent fallback")
        return True
    return available and rb is not None


# ------------------------------------------------------------------- kernel
def _gates_kernel(hxa_ref, hxb_ref, cra_ref, crb_ref, wzr_ref, bzr_ref,
                  wq_ref, bq_ref, zr_ref, qpre_ref, *, ch: int, precision):
    """One (image, row-block) program.

    Refs (blocks):
      hxa/hxb: (1, rb, W+4, Cin) — row blocks i / i+1 of the 2-padded [h, x]
      cra/crb: (1, rb, W+2, Ch)  — row blocks i / i+1 of the 1-padded cr
      wzr/wq:  (3, 3, Cin, Cout) gate conv weights (compute dtype)
      bzr/bq:  (1, Cout) fp32 biases
      zr:      (1, rb, W, 2*Ch) out — pre-activation z|r gates
      qpre:    (1, rb, W, Ch)   out — pre-activation candidate
    """
    rb = hxa_ref.shape[1]
    w = zr_ref.shape[2]
    # Assemble the rb+4 halo rows (2-padded coords [i*rb, i*rb+rb+4)) from
    # view i plus the first 4 rows of view i+1, and likewise rb+2 cr rows.
    rows = jnp.concatenate([hxa_ref[0], hxb_ref[0, :4]], axis=0)
    crw = jnp.concatenate([cra_ref[0], crb_ref[0, :2]], axis=0)

    def conv_valid(inp, wk_ref, nr, nc):
        """3x3 VALID conv as 9 shifted MXU matmuls, fp32 accumulation:
        (nr+2, nc+2, Cin) -> (nr, nc, Cout)."""
        acc = None
        for ty in range(3):
            for tx in range(3):
                part = jax.lax.dot_general(
                    inp[ty:ty + nr, tx:tx + nc, :], wk_ref[ty, tx],
                    (((2,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=precision)
                acc = part if acc is None else acc + part
        return acc

    # zr on the rb+2 halo rows / W+2 halo cols: the q conv below needs the
    # r gate one ring beyond the output block.  Ring positions outside the
    # image compute garbage pre-activations from the zero padding — harmless
    # because r multiplies h there, and padded h is 0 (= the Flax path's
    # SAME-padding zeros on the [r*h, x] conv input).
    zr_ext = (conv_valid(rows, wzr_ref, rb + 2, w + 2)
              + bzr_ref[0].astype(jnp.float32))
    r = jax.nn.sigmoid(zr_ext[..., ch:] + crw.astype(jnp.float32))
    h_halo = rows[1:rb + 3, 1:w + 3, :ch]
    rh = (r * h_halo.astype(jnp.float32)).astype(rows.dtype)
    rhx = jnp.concatenate([rh, rows[1:rb + 3, 1:w + 3, ch:]], axis=-1)
    qpre = conv_valid(rhx, wq_ref, rb, w) + bq_ref[0].astype(jnp.float32)

    zr_ref[0] = zr_ext[1:rb + 1, 1:w + 1].astype(zr_ref.dtype)
    qpre_ref[0] = qpre.astype(qpre_ref.dtype)


def _gates_launch(h, x, cr, wzr, bzr, wq, bq):
    b, hh, ww, ch = h.shape
    cin = ch + x.shape[-1]
    dt = h.dtype
    rb = gru_fused_row_block(ww, cin, ch, dt.itemsize)
    if rb is None:
        raise ValueError(
            f"gru_fused: working set for W={ww}, Cin={cin}, Ch={ch} exceeds "
            "VMEM budget — gru_fused_should_use must gate this launch")
    nb = pl.cdiv(hh, rb)
    # Row pad to (nb+1)*rb so the i+1 halo view of the LAST block stays in
    # bounds (deterministic zeros, no reliance on OOB-block semantics);
    # output rows are allocated at nb*rb and sliced back to H.
    rows_pad = (nb + 1) * rb
    hx = jnp.concatenate([h, x], axis=-1)
    hx_pad = jnp.pad(hx, ((0, 0), (2, rows_pad - hh - 2), (2, 2), (0, 0)))
    cr_pad = jnp.pad(cr, ((0, 0), (1, rows_pad - hh - 1), (1, 1), (0, 0)))
    # Weights in the compute dtype (the cast nn.Conv(dtype=...) applies);
    # biases ride fp32 and join the fp32 accumulators directly.
    wzr_c = wzr.astype(dt)
    wq_c = wq.astype(dt)
    bzr_c = bzr.astype(jnp.float32).reshape(1, -1)
    bq_c = bq.astype(jnp.float32).reshape(1, -1)
    full = lambda bi, i: (0, 0, 0, 0)  # noqa: E731 — weights, grid-invariant
    zr, qpre = pl.pallas_call(
        functools.partial(_gates_kernel, ch=ch,
                          precision=_precision_for(dt)),
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, rb, ww + 4, cin), lambda bi, i: (bi, i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, rb, ww + 4, cin),
                         lambda bi, i: (bi, i + 1, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, rb, ww + 2, ch), lambda bi, i: (bi, i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, rb, ww + 2, ch),
                         lambda bi, i: (bi, i + 1, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 3, cin, 2 * ch), full, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2 * ch), lambda bi, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 3, cin, ch), full, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ch), lambda bi, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, rb, ww, 2 * ch), lambda bi, i: (bi, i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, rb, ww, ch), lambda bi, i: (bi, i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nb * rb, ww, 2 * ch), dt),
            jax.ShapeDtypeStruct((b, nb * rb, ww, ch), dt),
        ],
        interpret=interpret_enabled(),
    )(hx_pad, hx_pad, cr_pad, cr_pad, wzr_c, bzr_c, wq_c, bq_c)
    return zr[:, :hh], qpre[:, :hh]


# ---------------------------------------------------------------- reference
def _conv3x3_same(inp, kernel):
    """The exact conv the Flax path lowers to (nn.Conv via our
    models/extractor.conv wrapper): NHWC/HWIO, stride 1, symmetric (1,1)
    padding, default precision."""
    return jax.lax.conv_general_dilated(
        inp, kernel, window_strides=(1, 1), padding=((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _gates_reference(h, x, cr, wzr, bzr, wq, bq):
    """Pure-JAX twin of the fused kernel — the backward's linearization
    point.  Mirrors the Flax path's dtype behaviour (params cast to the
    activations' compute dtype, conv + bias in that dtype), so its VJP is
    the same XLA backward the Flax path runs."""
    dt = h.dtype
    ch = h.shape[-1]
    hx = jnp.concatenate([h, x], axis=-1)
    zr = _conv3x3_same(hx, wzr.astype(dt)) + bzr.astype(dt)
    r = jax.nn.sigmoid(zr[..., ch:] + cr)
    qpre = (_conv3x3_same(jnp.concatenate([r * h, x], axis=-1),
                          wq.astype(dt)) + bq.astype(dt))
    return zr, qpre


# --------------------------------------------------------------- custom VJP
@jax.custom_vjp
def gru_gates_fused(h, x, cr, wzr, bzr, wq, bq) -> Tuple[jnp.ndarray,
                                                         jnp.ndarray]:
    """Fused ConvGRU gate pre-activations.

    Args:
      h:   (B, H, W, Ch) hidden state, compute dtype.
      x:   (B, H, W, Cx) concatenated GRU inputs, compute dtype.
      cr:  (B, H, W, Ch) r-gate context bias (needed in-kernel for the
           recurrence coupling; cz/cq stay in the caller's pointwise tail).
      wzr, bzr: convzr parameters, (3, 3, Ch+Cx, 2*Ch) / (2*Ch,), fp32.
      wq, bq:   convq parameters, (3, 3, Ch+Cx, Ch) / (Ch,), fp32.

    Returns:
      (zr, qpre): pre-activation gate tensors in the compute dtype —
      identical in meaning (and checkpoint_name tagging site) to the Flax
      path's convzr/convq outputs.
    """
    return _gates_launch(h, x, cr, wzr, bzr, wq, bq)


def _gates_fwd(h, x, cr, wzr, bzr, wq, bq):
    # Residuals are the op's INPUTS only: under remat the residual rebuild
    # needs no Pallas re-run (the kernel outputs are dead in the recompute
    # when "gru_gates" is in config.remat_save, and the inputs themselves
    # come from the scan carry / saved motion features).
    return (gru_gates_fused(h, x, cr, wzr, bzr, wq, bq),
            (h, x, cr, wzr, bzr, wq, bq))


def _gates_bwd(residuals, g):
    # VJP of the pure-JAX twin: the identical conv backward the Flax path
    # runs (conv-transpose for activations, input x cotangent for weights).
    _, vjp = jax.vjp(_gates_reference, *residuals)
    return vjp(g)


gru_gates_fused.defvjp(_gates_fwd, _gates_bwd)
