"""Data layer: format readers, augmentors, datasets, loader."""
import os

import numpy as np
import pytest
from PIL import Image

from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.data.augment import (ColorJitter, DenseAugmentor,
                                          SparseAugmentor)
from raft_stereo_tpu.data.datasets import KITTI, StereoDataset
from raft_stereo_tpu.data.loader import StereoLoader


# ------------------------------------------------------------------ formats
def test_pfm_roundtrip(tmp_path, rng):
    disp = rng.uniform(0, 100, (13, 17)).astype(np.float32)
    path = str(tmp_path / "x.pfm")
    frame_utils.write_pfm(path, disp)
    back = frame_utils.read_pfm(path)
    np.testing.assert_array_equal(back, disp)


def test_flo_roundtrip(tmp_path, rng):
    flow = rng.normal(size=(7, 9, 2)).astype(np.float32)
    path = str(tmp_path / "x.flo")
    frame_utils.write_flo(path, flow)
    np.testing.assert_array_equal(frame_utils.read_flo(path), flow)


def test_kitti_disp_roundtrip(tmp_path, rng):
    disp = (rng.uniform(0, 200, (11, 19)) * 256).astype(np.uint16) / 256.0
    disp[0, :5] = 0.0  # invalid pixels
    path = str(tmp_path / "d.png")
    frame_utils.write_disp_kitti(path, disp)
    back, valid = frame_utils.read_disp_kitti(path)
    np.testing.assert_allclose(back, disp, atol=1 / 256)
    assert not valid[0, :5].any() and valid[5:].all()


def test_sintel_packed_disparity(tmp_path):
    # disparity d encodes as R*4 + G/64 + B/16384
    rgb = np.zeros((4, 6, 3), np.uint8)
    rgb[..., 0] = 10  # 2.5 px
    rgb[..., 1] = 64  # +1 px
    (tmp_path / "disparities").mkdir()
    (tmp_path / "occlusions").mkdir()
    Image.fromarray(rgb).save(tmp_path / "disparities" / "frame_0001.png")
    occ = np.zeros((4, 6), np.uint8)
    occ[0, 0] = 255  # occluded pixel
    Image.fromarray(occ).save(tmp_path / "occlusions" / "frame_0001.png")
    disp, valid = frame_utils.read_disp_sintel(
        str(tmp_path / "disparities" / "frame_0001.png"))
    np.testing.assert_allclose(disp, 41.0, atol=1e-5)
    assert not valid[0, 0] and valid[1:].all()


def test_read_gen_dispatch(tmp_path, rng):
    img = rng.integers(0, 255, (5, 7, 3), dtype=np.uint8)
    Image.fromarray(img).save(tmp_path / "i.png")
    out = frame_utils.read_gen(str(tmp_path / "i.png"))
    np.testing.assert_array_equal(out, img)
    with pytest.raises(ValueError):
        frame_utils.read_gen("nope.xyz")


# --------------------------------------------------------------- augmentors
def test_color_jitter_deterministic(rng):
    img = rng.integers(0, 255, (32, 32, 3), dtype=np.uint8)
    jit = ColorJitter(0.4, 0.4, (0.6, 1.4), 0.16)
    a = jit(img, np.random.default_rng(7))
    b = jit(img, np.random.default_rng(7))
    c = jit(img, np.random.default_rng(8))
    np.testing.assert_array_equal(a, b)
    assert a.shape == img.shape and a.dtype == np.uint8
    assert np.any(a != c)  # different draw actually changes the image


def test_dense_augmentor_shapes_and_determinism(rng):
    crop = (64, 96)
    aug = DenseAugmentor(crop, yjitter=True)
    img1 = rng.integers(0, 255, (120, 160, 3), dtype=np.uint8)
    img2 = rng.integers(0, 255, (120, 160, 3), dtype=np.uint8)
    flow = rng.normal(size=(120, 160, 2)).astype(np.float32)
    o1 = aug(img1, img2, flow, np.random.default_rng(3))
    o2 = aug(img1, img2, flow, np.random.default_rng(3))
    for a, b in zip(o1, o2):
        np.testing.assert_array_equal(a, b)
    assert o1[0].shape == (*crop, 3) and o1[2].shape == (*crop, 2)


def test_sparse_resize_scatters_not_interpolates():
    # one valid pixel among invalid neighbours must stay a single valid
    # pixel after 2x upscale, with flow scaled by the factor
    flow = np.zeros((8, 8, 2), np.float32)
    valid = np.zeros((8, 8), np.float32)
    flow[4, 4] = [-10.0, 0.0]
    valid[4, 4] = 1
    f2, v2 = SparseAugmentor.resize_sparse_flow(flow, valid, 2.0, 2.0)
    assert f2.shape == (16, 16, 2)
    assert v2.sum() == 1
    yy, xx = np.nonzero(v2)
    np.testing.assert_allclose(f2[yy[0], xx[0]], [-20.0, 0.0])


def test_sparse_augmentor_shapes(rng):
    crop = (64, 96)
    aug = SparseAugmentor(crop)
    img1 = rng.integers(0, 255, (120, 160, 3), dtype=np.uint8)
    img2 = rng.integers(0, 255, (120, 160, 3), dtype=np.uint8)
    flow = np.zeros((120, 160, 2), np.float32)
    valid = (rng.uniform(size=(120, 160)) < 0.3).astype(np.float32)
    i1, i2, f, v = aug(img1, img2, flow, valid, np.random.default_rng(5))
    assert i1.shape == (*crop, 3) and f.shape == (*crop, 2)
    assert v.shape == crop and set(np.unique(v)).issubset({0, 1})


def test_stereo_hflip_swaps_views(rng):
    aug = DenseAugmentor((64, 96), min_scale=0, max_scale=0, do_flip="h",
                         yjitter=False)
    aug.jitter = ColorJitter(0, 0, (1, 1), 0)  # disable photometric noise
    aug.stretch_prob = 0.0  # keep scale exactly 1 so crops match raw pixels
    img1 = rng.integers(0, 255, (80, 120, 3), dtype=np.uint8)
    img2 = rng.integers(0, 255, (80, 120, 3), dtype=np.uint8)
    flow = np.zeros((80, 120, 2), np.float32)
    # find an rng draw that triggers the flip (prob 0.5)
    for seed in range(20):
        r = np.random.default_rng(seed)
        o1, o2, _ = aug(img1, img2, flow, r)
        # after swap-and-mirror, img1's crop must come from mirrored img2
        flipped2 = img2[:, ::-1]
        found = any(
            np.array_equal(o1, flipped2[y:y + 64, x:x + 96])
            for y in range(0, 17) for x in range(0, 25))
        if found:
            return
    pytest.fail("stereo h-flip never produced a crop of mirrored img2")


# ----------------------------------------------------------------- datasets
def _make_kitti_tree(tmp_path, n=5, size=(40, 60)):
    h, w = size
    rng = np.random.default_rng(0)
    for sub in ("image_2", "image_3", "disp_occ_0"):
        (tmp_path / "training" / sub).mkdir(parents=True)
    for i in range(n):
        for sub in ("image_2", "image_3"):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(img).save(
                tmp_path / "training" / sub / f"{i:06d}_10.png")
        disp = rng.uniform(1, 30, (h, w)).astype(np.float32)
        frame_utils.write_disp_kitti(
            str(tmp_path / "training" / "disp_occ_0" / f"{i:06d}_10.png"),
            disp)
    return tmp_path


def test_kitti_dataset_sample(tmp_path):
    root = _make_kitti_tree(tmp_path)
    ds = KITTI(aug_params=None, root=str(root))
    assert len(ds) == 5
    s = ds[0]
    assert s["image1"].shape == (40, 60, 3)
    assert s["flow"].shape == (40, 60)
    assert (s["flow"] <= 0).all()  # x-flow = -disparity
    assert s["valid"].min() >= 0 and s["valid"].max() <= 1


def test_dataset_mul_and_concat(tmp_path):
    root = _make_kitti_tree(tmp_path)
    ds = KITTI(aug_params=None, root=str(root))
    tripled = ds * 3
    assert len(tripled) == 15
    both = ds + tripled
    assert len(both) == 20
    # concat indexing reaches the second part
    s = both[17]
    assert s["image1"].shape == (40, 60, 3)


def test_loader_threaded_matches_sync(tmp_path):
    root = _make_kitti_tree(tmp_path, n=6)
    aug = {"crop_size": (32, 48), "min_scale": -0.2, "max_scale": 0.4,
           "do_flip": None, "yjitter": False}
    ds = KITTI(aug_params=aug, root=str(root))
    mk = lambda workers: StereoLoader(ds, batch_size=2, num_workers=workers,
                                      seed=42, epochs=2)
    sync_batches = list(mk(0))
    thr_batches = list(mk(3))
    assert len(sync_batches) == len(thr_batches) == 6
    for a, b in zip(sync_batches, thr_batches):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    assert sync_batches[0]["image1"].shape == (2, 32, 48, 3)


def test_loader_epoch_reshuffles(tmp_path):
    root = _make_kitti_tree(tmp_path, n=6)
    ds = KITTI(aug_params=None, root=str(root))
    loader = StereoLoader(ds, batch_size=6, num_workers=0, seed=0, epochs=2)
    b1, b2 = list(loader)
    assert any(not np.array_equal(b1[k], b2[k]) for k in b1)


def test_sceneflow_loader_decode_throughput(tmp_path):
    """Guards the PFM+PNG decode -> DenseAugmentor -> batch path on the
    SceneFlow disk layout (the training recipe's input, reference:
    core/stereo_datasets.py:123-184).  Uses bench_loader's tree builder so
    the benchmark and this guard can never drift apart; asserts correctness
    and a very conservative throughput floor (the real demand check is
    bench_loader.py on the bench host)."""
    import time

    from bench_loader import build_tree
    from raft_stereo_tpu.data.datasets import SceneFlow

    root = str(tmp_path / "sf")
    build_tree(root, n_pairs=8, hw=(120, 200))
    aug = {"crop_size": (96, 160), "min_scale": -0.2, "max_scale": 0.4,
           "do_flip": None, "yjitter": True}
    ds = SceneFlow(aug, root=root, dstype="frames_cleanpass")
    assert len(ds) == 8
    loader = StereoLoader(ds, batch_size=4, num_workers=2, seed=0, epochs=2)
    t0 = time.perf_counter()
    batches = list(loader)
    dt = time.perf_counter() - t0
    assert len(batches) == 4
    b = batches[0]
    assert b["image1"].shape == (4, 96, 160, 3)
    assert b["image1"].dtype == np.uint8  # device-transfer-lean contract
    assert b["flow"].shape == (4, 96, 160)
    assert np.all(b["flow"] <= 0)  # x-flow = -disparity
    assert set(np.unique(b["valid"])) <= {0.0, 1.0}
    # 16 images decoded+augmented; wall-clock floors flake on oversubscribed
    # CI runners no matter the headroom, so the timing assert is opt-in
    # (RAFT_TPU_TIMING_ASSERTS=1 on a quiet host).  Real throughput-vs-demand
    # evidence is bench_loader.py's job on the bench host; the shape/dtype
    # contract asserts above stay unconditional.
    if os.environ.get("RAFT_TPU_TIMING_ASSERTS", "").lower() in (
            "1", "true", "yes"):
        assert 16 / dt > 2.0, f"decode path too slow: {16 / dt:.1f} images/s"
