"""Geometric guarantees of the benchmark-regime layered-scene generator.

These lock the properties every round-5 accuracy artifact rests on
(reference metric domain: evaluate_stereo.py:133-135 clips at |d| < 192;
Middlebury nocc-mask semantics: MiddEval3 mask0nocc 255=visible):

1. photometric consistency — at NON-occluded pixels the right view really
   is the left content displaced by the GT disparity (sub-quantization
   interpolation error only);
2. the occlusion mask is TRUE forward-warp visibility — pixels it marks
   are photometrically inconsistent (something nearer covers the match),
   pixels it clears are consistent;
3. the disparity corpus spans the benchmark regime (>=150 px at 190-px
   ceiling over a few draws) while every value stays positive and finite;
4. the tree builders encode occlusion the way each real benchmark does
   (Middlebury mask0nocc = 128 at occlusions, ETH3D +inf GT, KITTI occ
   split keeps occluded GT).
"""

import os

import numpy as np
import pytest

from golden_data import (hard_pair, layered_scene, make_kitti,
                         make_middlebury)
from raft_stereo_tpu.data import frame_utils

pytestmark = pytest.mark.quick


def _photometric_error(left, right, disp):
    """|left[y,x] - right[y, x-d]| per pixel (per-row linear interp)."""
    h, w, _ = left.shape
    x = np.arange(w, dtype=np.float32)[None, :]
    xm = np.clip(x - disp, 0, w - 1)
    x0 = np.clip(np.floor(xm).astype(np.int64), 0, w - 2)
    fr = (xm - x0)[..., None]
    r0 = np.take_along_axis(right.astype(np.float32), x0[..., None], axis=1)
    r1 = np.take_along_axis(right.astype(np.float32), (x0 + 1)[..., None],
                            axis=1)
    return np.abs(r0 * (1 - fr) + r1 * fr - left.astype(np.float32)).mean(-1)


def test_layered_scene_geometry():
    rng = np.random.default_rng(3)
    for _ in range(4):
        left, right, disp, occ = layered_scene(rng, 192, 448, d_max=190.0)
        assert np.isfinite(disp).all() and (disp > 0).all()
        in_frame = (np.arange(448)[None, :] - disp) >= 0
        err = _photometric_error(left, right, disp)
        vis = ~occ & in_frame
        # non-occluded pixels: right view == displaced left content
        assert err[vis].mean() < 1.0, err[vis].mean()
        assert np.percentile(err[vis], 99) < 4.0
        # occluded (in-frame) pixels: a nearer surface covers the match,
        # so the photometric error there must be much larger on average
        occ_in = occ & in_frame
        if occ_in.sum() > 100:
            assert err[occ_in].mean() > 5 * err[vis].mean()
        # occlusions exist but don't dominate
        assert 0.01 < occ.mean() < 0.5


def test_corpus_spans_benchmark_regime():
    """Over a corpus the per-scene ceiling (uniform(0.35,1)*d_max, with one
    layer pinned AT the ceiling) reaches deep into the |d|<192 domain."""
    rng = np.random.default_rng(9)
    reached = max(float(layered_scene(rng, 64, 448, d_max=190.0)[2].max())
                  for _ in range(12))
    assert reached > 170.0, f"corpus max disparity only {reached:.0f} px"


def test_hard_pair_dmax_scales_with_width():
    rng = np.random.default_rng(0)
    _, _, disp, _ = hard_pair(rng, 60, 90)
    assert disp.max() <= 0.35 * 90 * 1.15  # tiny trees stay plausible


def test_middlebury_hard_nocc_mask_is_true_occlusion(tmp_path):
    root = str(tmp_path)
    make_middlebury(root, np.random.default_rng(5), n=1, hw=(96, 200),
                    split="H", hard=True)
    scene = os.path.join(root, "MiddEval3", "trainingH", "Scene0")
    disp = frame_utils.read_gen(os.path.join(scene, "disp0GT.pfm"))
    disp = np.ascontiguousarray(disp)
    from PIL import Image
    mask = np.asarray(Image.open(os.path.join(scene, "mask0nocc.png")))
    left = np.asarray(Image.open(os.path.join(scene, "im0.png")))
    right = np.asarray(Image.open(os.path.join(scene, "im1.png")))
    known = np.isfinite(disp)
    err = _photometric_error(left, right, np.where(known, disp, 0.0))
    in_frame = (np.arange(disp.shape[1])[None, :] - disp) >= 0
    vis = (mask == 255) & known & in_frame
    occl = (mask == 128) & known & in_frame
    assert vis.any() and occl.any()
    assert err[vis].mean() < 1.0
    assert err[occl].mean() > 5 * err[vis].mean()


def test_kitti_hard_sparse_occ_split(tmp_path):
    root = str(tmp_path)
    make_kitti(root, np.random.default_rng(6), n=1, hw=(96, 200), hard=True)
    disp, valid = frame_utils.read_disp_kitti(
        os.path.join(root, "training", "disp_occ_0", "000000_10.png"))
    assert 0.4 < valid.mean() < 0.8          # LiDAR-style dropout
    assert disp[valid > 0].max() > 20.0      # hard regime reaches the crop
