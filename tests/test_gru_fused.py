"""Fused ConvGRU gate kernel (kernels/gru_fused.py) vs the Flax conv path.

Runs the kernel in Pallas interpret mode (CPU) — the same code path the TPU
compiles — via the package-wide interpret override shared with the
correlation kernels.  Covers every acceptance surface of the kernel-family
contract: forward + VJP parity for all three GRU levels (fp32 and bf16
bounds), composition with the ``remat_gru`` + ``save_only_these_names``
policy, the ``fused_gru="off"`` bitwise guarantee, and the capability /
VMEM-fit gating.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.kernels import corr_lookup, gru_fused
from raft_stereo_tpu.models.update import BasicMultiUpdateBlock, ConvGRU


@pytest.fixture
def interpret_mode():
    corr_lookup._interpret_override = True
    yield
    corr_lookup._interpret_override = None


# Per-level (Ch, n_extra_inputs, H, W) mirroring the three GRU levels'
# input arity in BasicMultiUpdateBlock (gru08: motion+interp, gru16:
# pool+interp, gru32: pool); H=9 exercises the non-divisible row-block
# tail, W is deliberately lane-unaligned.
LEVELS = [
    pytest.param(32, 2, 9, 13, id="gru08"),
    pytest.param(32, 2, 6, 7, id="gru16"),
    pytest.param(24, 1, 4, 5, id="gru32"),
]


def _level_inputs(rng, b, h, w, ch, n_x, dtype=jnp.float32):
    mk = lambda c: jnp.asarray(  # noqa: E731
        rng.normal(size=(b, h, w, c)), dtype)
    hid = mk(ch)
    xs = [mk(ch) for _ in range(n_x)]
    ctx = tuple(mk(ch) for _ in range(3))
    return hid, ctx, xs


@pytest.mark.parametrize("ch,n_x,h,w", LEVELS)
def test_forward_parity_fp32(interpret_mode, rng, ch, n_x, h, w):
    hid, ctx, xs = _level_inputs(rng, 2, h, w, ch, n_x)
    v = ConvGRU(hidden_dim=ch, fused="off", name="g").init(
        jax.random.PRNGKey(0), hid, ctx, *xs)
    out_off = ConvGRU(hidden_dim=ch, fused="off", name="g").apply(
        v, hid, ctx, *xs)
    out_on = ConvGRU(hidden_dim=ch, fused="on", name="g").apply(
        v, hid, ctx, *xs)
    np.testing.assert_allclose(np.asarray(out_on), np.asarray(out_off),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("ch,n_x,h,w", LEVELS)
def test_vjp_parity_fp32(interpret_mode, rng, ch, n_x, h, w):
    """Gradients w.r.t. params AND activations agree within fp32 tolerance
    (the kernel's 9-matmul conv reassociates differently from the XLA conv,
    so comparison is relative to each gradient tensor's scale)."""
    hid, ctx, xs = _level_inputs(rng, 1, h, w, ch, n_x)
    v = ConvGRU(hidden_dim=ch, fused="off", name="g").init(
        jax.random.PRNGKey(0), hid, ctx, *xs)

    def loss(fused):
        def f(params, hid_, xs_):
            out = ConvGRU(hidden_dim=ch, fused=fused, name="g").apply(
                {"params": params}, hid_, ctx, *xs_)
            return jnp.sum(jnp.sin(out))
        return f

    g_off = jax.grad(loss("off"), argnums=(0, 1, 2))(v["params"], hid, xs)
    g_on = jax.grad(loss("on"), argnums=(0, 1, 2))(v["params"], hid, xs)
    for a, b in zip(jax.tree_util.tree_leaves(g_off),
                    jax.tree_util.tree_leaves(g_on), strict=True):
        scale = max(1.0, float(jnp.max(jnp.abs(a))))
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-5 * scale)


def test_forward_parity_bf16(interpret_mode, rng):
    """bf16 bound: the kernel computes the gate pointwise chain in fp32
    where the Flax path rounds through bf16 at each op, so outputs agree to
    bf16 resolution (~2^-8 relative, documented bound 3e-2 on the blended
    state whose scale is ~1)."""
    hid, ctx, xs = _level_inputs(rng, 1, 8, 9, 32, 2, dtype=jnp.bfloat16)
    v = ConvGRU(hidden_dim=32, dtype=jnp.bfloat16, fused="off",
                name="g").init(jax.random.PRNGKey(0), hid, ctx, *xs)
    out_off = ConvGRU(hidden_dim=32, dtype=jnp.bfloat16, fused="off",
                      name="g").apply(v, hid, ctx, *xs)
    out_on = ConvGRU(hidden_dim=32, dtype=jnp.bfloat16, fused="on",
                     name="g").apply(v, hid, ctx, *xs)
    assert out_on.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out_on, np.float32),
                               np.asarray(out_off, np.float32), atol=3e-2)


def _update_block_io(rng, cfg, b=1, h=8, w=12, dtype=jnp.float32):
    n = cfg.n_gru_layers
    hd = cfg.hidden_dims
    mk = lambda hh, ww, c: jnp.asarray(  # noqa: E731
        rng.normal(size=(b, hh, ww, c)), dtype)
    net = [mk(h >> l, w >> l, hd[l]) for l in range(n)]
    ctx = [tuple(mk(h >> l, w >> l, hd[l]) for _ in range(3))
           for l in range(n)]
    corr = mk(h, w, cfg.corr_channels)
    flow = mk(h, w, 2)
    return net, ctx, corr, flow


def test_update_block_all_levels_fused(interpret_mode, rng):
    """End-to-end through BasicMultiUpdateBlock: all three GRU levels take
    the fused path (mode "on" would raise if any level fell back) and agree
    with the Flax path."""
    cfg = RaftStereoConfig(hidden_dims=(32, 32, 32), fnet_dim=64)
    net, ctx, corr, flow = _update_block_io(rng, cfg)
    ub_off = BasicMultiUpdateBlock(
        dataclasses.replace(cfg, fused_gru="off"), name="ub")
    v = ub_off.init(jax.random.PRNGKey(1), net, ctx, corr, flow)
    out_off = ub_off.apply(v, net, ctx, corr, flow)
    ub_on = BasicMultiUpdateBlock(
        dataclasses.replace(cfg, fused_gru="on"), name="ub")
    out_on = ub_on.apply(v, net, ctx, corr, flow)
    for a, b in zip(jax.tree_util.tree_leaves(out_off),
                    jax.tree_util.tree_leaves(out_on), strict=True):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-5)


def test_param_tree_identical_across_modes(interpret_mode, rng):
    """The fused path consumes the SAME parameter pytree nn.Conv creates —
    init under either mode yields identical names, shapes, and values, so
    checkpoints are mode-independent."""
    cfg = RaftStereoConfig(hidden_dims=(16, 16, 16), fnet_dim=32)
    net, ctx, corr, flow = _update_block_io(rng, cfg)
    v_off = BasicMultiUpdateBlock(
        dataclasses.replace(cfg, fused_gru="off"), name="ub").init(
        jax.random.PRNGKey(2), net, ctx, corr, flow)
    v_on = BasicMultiUpdateBlock(
        dataclasses.replace(cfg, fused_gru="on"), name="ub").init(
        jax.random.PRNGKey(2), net, ctx, corr, flow)
    pa = jax.tree_util.tree_structure(v_off)
    pb = jax.tree_util.tree_structure(v_on)
    assert pa == pb
    for a, b in zip(jax.tree_util.tree_leaves(v_off),
                    jax.tree_util.tree_leaves(v_on), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_remat_scan_vjp_parity(interpret_mode, rng):
    """The custom VJP composes with the model's exact training structure —
    nn.remat(policy=save_only_these_names("gru_gates", ...)) around an
    nn.scan of the update block: loss and gradients agree between fused and
    Flax paths.  (Exercised at the update-block level: this environment's
    jax lacks a differentiation rule for the encoders' optimization_barrier,
    but the remat/scan/VJP composition under test lives entirely in the
    update block.)"""
    cfg = RaftStereoConfig(hidden_dims=(16, 16), n_gru_layers=2,
                           fnet_dim=32, corr_levels=2, corr_radius=3)
    net, ctx, corr, flow = _update_block_io(rng, cfg)

    class ScanUB(nn.Module):
        config: RaftStereoConfig

        @nn.compact
        def __call__(self, net, iters=3):
            def body(module, carry, _):
                net_l = BasicMultiUpdateBlock(self.config, name="ub")(
                    carry, ctx, corr, flow)[0]
                return tuple(net_l), jnp.mean(net_l[0])
            body = nn.remat(
                body, prevent_cse=False,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "gru_gates", "motion_features"))
            scan = nn.scan(body, variable_broadcast="params",
                           split_rngs={"params": False}, length=iters)
            _, means = scan(self, tuple(net), None)
            return jnp.sum(means)

    v = ScanUB(dataclasses.replace(cfg, fused_gru="off")).init(
        jax.random.PRNGKey(3), net)
    results = {}
    for mode in ("off", "on"):
        model = ScanUB(dataclasses.replace(cfg, fused_gru=mode))
        loss, grads = jax.value_and_grad(
            lambda p, m=model: m.apply({"params": p}, net))(v["params"])
        results[mode] = (float(loss), jax.tree_util.tree_leaves(grads))
    np.testing.assert_allclose(results["on"][0], results["off"][0],
                               rtol=1e-6)
    for a, b in zip(results["off"][1], results["on"][1], strict=True):
        scale = max(1.0, float(jnp.max(jnp.abs(a))))
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-5 * scale)


def test_off_reproduces_flax_graph_bitwise(interpret_mode, rng):
    """fused_gru="off" must reproduce today's graph even when the kernel IS
    available (interpret override on): no pallas_call in the trace, and
    outputs bit-identical to "auto" on a backend where the kernel is
    unavailable (= the pre-kernel graph)."""
    cfg = RaftStereoConfig(hidden_dims=(16, 16), n_gru_layers=2,
                           fnet_dim=32, corr_levels=2, corr_radius=3)
    net, ctx, corr, flow = _update_block_io(rng, cfg)
    ub_off = BasicMultiUpdateBlock(
        dataclasses.replace(cfg, fused_gru="off"), name="ub")
    v = ub_off.init(jax.random.PRNGKey(4), net, ctx, corr, flow)
    jaxpr = jax.make_jaxpr(
        lambda *a: ub_off.apply(v, *a))(net, ctx, corr, flow)
    assert "pallas_call" not in str(jaxpr)
    out_off = ub_off.apply(v, net, ctx, corr, flow)

    corr_lookup._interpret_override = None  # kernel now unavailable (CPU)
    out_auto = BasicMultiUpdateBlock(
        dataclasses.replace(cfg, fused_gru="auto"), name="ub").apply(
        v, net, ctx, corr, flow)
    for a, b in zip(jax.tree_util.tree_leaves(out_off),
                    jax.tree_util.tree_leaves(out_auto), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_auto_uses_kernel_when_available(interpret_mode, rng):
    """auto = kernel on capable backends: with the override active the
    traced graph contains the pallas_call."""
    hid, ctx, xs = _level_inputs(rng, 1, 8, 8, 16, 1)
    gru = ConvGRU(hidden_dim=16, fused="auto", name="g")
    v = gru.init(jax.random.PRNGKey(0), hid, ctx, *xs)
    jaxpr = jax.make_jaxpr(lambda *a: gru.apply(v, *a))(hid, ctx, *xs)
    assert "pallas_call" in str(jaxpr)


def test_capability_and_fit_gating(rng):
    """Contract gating: unavailable backend → auto falls back silently,
    "on" raises; oversized working set → row block is refused."""
    assert corr_lookup._interpret_override is None
    assert not gru_fused.gru_fused_available()  # CPU, no override
    assert not gru_fused.gru_fused_should_use(
        "auto", kernel_size=3, w=64, cin=96, ch=32, itemsize=4)
    with pytest.raises(RuntimeError, match="unavailable"):
        gru_fused.gru_fused_should_use(
            "on", kernel_size=3, w=64, cin=96, ch=32, itemsize=4)
    # VMEM fit: a realistic level fits; an absurdly wide one must not, and
    # the row block never shrinks below the two-view minimum of 4.
    rb = gru_fused.gru_fused_row_block(180, 384, 128, 2)
    assert rb is not None and 4 <= rb <= 8
    assert gru_fused.gru_fused_row_block(200_000, 384, 128, 4) is None
    # "on" + unfittable working set raises even where the kernel exists.
    corr_lookup._interpret_override = True
    try:
        with pytest.raises(RuntimeError, match="VMEM"):
            gru_fused.gru_fused_should_use(
                "on", kernel_size=3, w=200_000, cin=384, ch=128, itemsize=4)
        assert not gru_fused.gru_fused_should_use(
            "auto", kernel_size=3, w=200_000, cin=384, ch=128, itemsize=4)
    finally:
        corr_lookup._interpret_override = None


def test_config_flag_validation():
    with pytest.raises(ValueError, match="fused_gru"):
        RaftStereoConfig(fused_gru="yes")
    cfg = RaftStereoConfig(fused_gru="on")
    assert RaftStereoConfig.from_json(cfg.to_json()).fused_gru == "on"
    # Old serialized configs (no field) deserialize to the default.
    d = cfg.to_dict()
    del d["fused_gru"]
    assert RaftStereoConfig.from_dict(d).fused_gru == "auto"


def test_public_kernel_api_exports():
    """kernels/__init__.py is the supported import surface."""
    from raft_stereo_tpu import kernels
    for name in ("fused_lookup_available", "alt_fused_available",
                 "lookup_pyramid_fused", "gru_fused_available",
                 "gru_gates_fused", "interpret_enabled"):
        assert callable(getattr(kernels, name)), name
