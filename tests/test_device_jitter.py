"""On-device photometric jitter (data/device_jitter.py): op-level parity vs
the host ColorJitter ops, pair semantics, determinism, and the train-step /
loader wiring of TrainConfig.device_photometric."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.data import augment as host_aug
from raft_stereo_tpu.data.device_jitter import (JitterParams,
                                                adjust_brightness,
                                                adjust_contrast,
                                                adjust_gamma, adjust_hue,
                                                adjust_saturation,
                                                apply_photometric,
                                                params_for_datasets)


@pytest.fixture
def img(rng):
    return rng.integers(0, 256, (40, 56, 3)).astype(np.uint8)


def dev(x):
    return jnp.asarray(np.asarray(x, np.float32))


def test_ops_match_host(img):
    """Fixed-factor device ops == uint8 host ops within rounding (host
    truncates to uint8 after each op; hue additionally quantizes the shift
    to cv2's 1/180-turn grid, so it gets a wider tolerance)."""
    f = dev(img)
    for factor in (0.6, 1.0, 1.37):
        np.testing.assert_allclose(
            np.asarray(adjust_brightness(f, factor)),
            host_aug.adjust_brightness(img, factor).astype(np.float32),
            atol=1.0)
        host_mean = img.mean(axis=-1, dtype=np.float32).mean(
            dtype=np.float32)
        np.testing.assert_allclose(
            np.asarray(adjust_contrast(f, factor, host_mean)),
            host_aug.adjust_contrast(img, factor).astype(np.float32),
            atol=1.0)
        np.testing.assert_allclose(
            np.asarray(adjust_saturation(f, factor)),
            host_aug.adjust_saturation(img, factor).astype(np.float32),
            atol=1.0)
    for gamma, gain in ((0.7, 1.0), (1.3, 1.1)):
        np.testing.assert_allclose(
            np.asarray(adjust_gamma(f, gamma, gain)),
            host_aug.adjust_gamma(img, gamma, gain).astype(np.float32),
            atol=1.0)
    for shift in (-0.11, 0.0, 0.25, 0.4):
        got = np.asarray(adjust_hue(f, shift))
        want = host_aug.adjust_hue(img, shift).astype(np.float32)
        # cv2 quantizes hue to 1/180 turns and round-trips through uint8
        # HSV; allow a few counts of drift on a minority of pixels
        assert np.median(np.abs(got - want)) <= 2.0
        assert np.mean(np.abs(got - want) > 6.0) < 0.02


def test_hue_identity_and_full_turn(img):
    f = dev(img)
    np.testing.assert_allclose(np.asarray(adjust_hue(f, 0.0)),
                               np.asarray(f, np.float32), atol=1e-3)
    np.testing.assert_allclose(np.asarray(adjust_hue(f, 1.0)),
                               np.asarray(f, np.float32), atol=1e-2)


def test_pair_symmetric_vs_asymmetric(rng):
    b, h, w = 6, 24, 32
    img = rng.integers(0, 256, (b, h, w, 3)).astype(np.uint8)
    key = jax.random.PRNGKey(3)

    # asymmetric_prob=0: identical views get identical jitter (shared
    # factors AND order; contrast blends toward the joint mean)
    sym = JitterParams(asymmetric_prob=0.0)
    o1, o2 = apply_photometric(dev(img), dev(img), key, sym)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-4)

    # asymmetric_prob=1: independent factors -> views diverge
    asym = JitterParams(asymmetric_prob=1.0)
    a1, a2 = apply_photometric(dev(img), dev(img), key, asym)
    assert np.max(np.abs(np.asarray(a1) - np.asarray(a2))) > 1.0

    # determinism: same key -> bit-identical stream
    r1, r2 = apply_photometric(dev(img), dev(img), key, asym)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(r1))
    np.testing.assert_array_equal(np.asarray(a2), np.asarray(r2))

    # different key -> different factors
    d1, _ = apply_photometric(dev(img), dev(img), jax.random.PRNGKey(4), asym)
    assert np.max(np.abs(np.asarray(a1) - np.asarray(d1))) > 1.0

    # range contract
    for x in (o1, a1, a2):
        arr = np.asarray(x)
        assert arr.dtype == np.float32
        assert arr.min() >= 0.0 and arr.max() <= 255.0


def test_per_sample_independence(rng):
    """Each batch sample draws its own factors: a batch of identical images
    comes out with per-sample distinct jitter."""
    img = np.broadcast_to(rng.integers(0, 256, (1, 24, 32, 3)),
                          (4, 24, 32, 3)).astype(np.uint8)
    out, _ = apply_photometric(dev(img), dev(img), jax.random.PRNGKey(0),
                               JitterParams())
    out = np.asarray(out)
    assert np.max(np.abs(out[0] - out[1])) > 1.0


def test_params_for_datasets():
    dense = params_for_datasets(("sceneflow", "falling_things"))
    assert dense.brightness == 0.4 and dense.saturation == (0.6, 1.4)
    sparse = params_for_datasets(("kitti",))
    assert sparse.brightness == 0.3 and sparse.saturation == (0.7, 1.3)
    # host SparseAugmentor jitters the stacked pair unconditionally —
    # the device profile must be symmetric-only
    assert sparse.asymmetric_prob == 0.0
    tartan = params_for_datasets(("tartan_air_seasons",))
    assert tartan.brightness == 0.4
    with pytest.raises(ValueError, match="mixture"):
        params_for_datasets(("sceneflow", "kitti"))
    # overrides flow through like build_training_mixture's aug_params
    p = params_for_datasets(("sceneflow",), saturation_range=(0.0, 1.4),
                            img_gamma=(0.5, 1.2))
    assert p.saturation == (0.0, 1.4)
    assert p.gamma == (0.5, 1.2, 1.0, 1.0)


def test_host_augmentor_photometric_opt_out(rng):
    """photometric=False skips ColorJitter on the host (the device applies
    it instead); spatial/eraser still run."""
    img1 = rng.integers(0, 256, (64, 96, 3)).astype(np.uint8)
    img2 = rng.integers(0, 256, (64, 96, 3)).astype(np.uint8)
    flow = rng.standard_normal((64, 96, 2)).astype(np.float32)
    aug = host_aug.DenseAugmentor((32, 48), photometric=False)
    a1, a2, af = aug(img1, img2, flow, np.random.default_rng(0))
    assert a1.shape == (32, 48, 3) and af.shape == (32, 48, 2)
    # pixel values of view 1 are crop/resize outputs of the ORIGINAL image
    # (no photometric changes); with jitter on they would differ.
    jit_on = host_aug.DenseAugmentor((32, 48), photometric=True)
    b1, _, _ = jit_on(img1, img2, flow, np.random.default_rng(0))
    assert not np.array_equal(a1, b1)


def test_train_step_with_device_photometric(rng):
    """make_train_step wires jitter from TrainConfig; loss stays finite and
    params update; the jitter stream is step-deterministic."""
    from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
    from raft_stereo_tpu.training.state import create_train_state
    from raft_stereo_tpu.training.step import make_train_step

    mcfg = RaftStereoConfig(hidden_dims=(16, 16, 16), fnet_dim=32,
                            corr_levels=2, corr_radius=2, n_gru_layers=1,
                            corr_backend="reg")
    tcfg = TrainConfig(batch_size=2, train_iters=2, image_size=(32, 48),
                       device_photometric=True, train_datasets=("sceneflow",))
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               (1, 32, 48, 3))
    step = make_train_step(tcfg, mesh=None, donate=False)
    batch = {
        "image1": rng.integers(0, 256, (2, 32, 48, 3)).astype(np.uint8),
        "image2": rng.integers(0, 256, (2, 32, 48, 3)).astype(np.uint8),
        "flow": rng.uniform(-8, 0, (2, 32, 48)).astype(np.float32),
        "valid": np.ones((2, 32, 48), np.float32),
    }
    new_state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(new_state.step) == 1
    # same state/batch -> same jitter key -> bit-identical loss
    _, metrics2 = step(state, batch)
    assert float(metrics["loss"]) == float(metrics2["loss"])


def test_process_worker_loader_matches_sync(tmp_path):
    """worker_type='process' yields byte-identical batches in the same
    order as the synchronous path (determinism is scheduling-free)."""
    from bench_loader import build_tree

    from raft_stereo_tpu.data.datasets import SceneFlow
    from raft_stereo_tpu.data.loader import StereoLoader

    root = str(tmp_path / "sf")
    build_tree(root, n_pairs=6, hw=(96, 144))
    aug = {"crop_size": (64, 96), "min_scale": -0.2, "max_scale": 0.4,
           "do_flip": None, "yjitter": True}

    def batches(**kw):
        ds = SceneFlow(aug, root=root, dstype="frames_cleanpass")
        return list(StereoLoader(ds, batch_size=2, seed=5, epochs=1, **kw))

    ref = batches(num_workers=0)
    got = batches(num_workers=2, worker_type="process")
    assert len(ref) == len(got) == 3
    for b_ref, b_got in zip(ref, got):
        for k in b_ref:
            np.testing.assert_array_equal(b_ref[k], b_got[k])
