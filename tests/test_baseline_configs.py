"""The five BASELINE.json benchmark configurations, exercised end-to-end on
CPU at reduced size: every config must build, jit, run forward (and for the
training config, one optimization step) with finite outputs.

These are the shapes the driver/judge measures on hardware; this file
guarantees none of them can rot between benchmark runs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-model / subprocess-scale tests

from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
from raft_stereo_tpu.models.raft_stereo import RAFTStereo


def _images(rng, h=64, w=96):
    i1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    return i1, jnp.asarray(np.roll(np.asarray(i1), -4, axis=2))


def _forward(cfg, rng, iters, h=64, w=96):
    model = RAFTStereo(cfg)
    i1, i2 = _images(rng, h, w)
    v = model.init(jax.random.PRNGKey(0), i1, i2, iters=1, test_mode=True)
    lo, up = jax.jit(
        lambda v_, a, b: model.apply(v_, a, b, iters=iters, test_mode=True)
    )(v, i1, i2)
    assert up.shape == i1.shape[:3]
    assert np.isfinite(np.asarray(up)).all()
    return np.asarray(up)


def test_config1_eth3d_reg_32iters(rng):
    """BASELINE config 1: the eth3d architecture, reg backend, 32 iters."""
    _forward(RaftStereoConfig(corr_backend="reg"), rng, iters=32)


def test_config2_realtime_7iters(rng):
    """BASELINE config 2: the realtime preset, 7 iters."""
    _forward(RaftStereoConfig.realtime(), rng, iters=7)


def test_config3_middlebury_alt_fullres_shape():
    """BASELINE config 3: alt (no-volume) backend at an odd, non-/32 aspect
    (full-res Middlebury shapes are odd; padding handles them)."""
    from raft_stereo_tpu.ops.padding import InputPadder

    cfg = RaftStereoConfig(corr_backend="alt")
    model = RAFTStereo(cfg)
    h, w = 61, 107  # odd dimensions, exercise pad→forward→unpad
    i1 = jnp.asarray(np.random.default_rng(0).uniform(0, 255, (1, h, w, 3)),
                     jnp.float32)
    padder = InputPadder(i1.shape, divis_by=32)
    p1, p2 = padder.pad(i1, i1)
    v = model.init(jax.random.PRNGKey(0), p1, p2, iters=1, test_mode=True)
    _, up = model.apply(v, p1, p2, iters=4, test_mode=True)
    out = padder.unpad(up)
    assert out.shape == (1, h, w)
    assert np.isfinite(np.asarray(out)).all()


def test_config4_sceneflow_training_step(rng):
    """BASELINE config 4: the SceneFlow training configuration (scaled
    down), one jitted train step with mixed precision."""
    from raft_stereo_tpu.training.state import create_train_state
    from raft_stereo_tpu.training.step import make_train_step

    model_cfg = RaftStereoConfig(mixed_precision=True, n_downsample=2)
    train_cfg = TrainConfig(batch_size=2, train_iters=4,
                            image_size=(64, 96), data_parallel=1)
    state = create_train_state(model_cfg, train_cfg, jax.random.PRNGKey(0),
                               image_shape=(1, 64, 96, 3))
    step = make_train_step(train_cfg, mesh=None, donate=False)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (2, 64, 96, 3)),
                              jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (2, 64, 96, 3)),
                              jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (2, 64, 96)), jnp.float32),
        "valid": jnp.ones((2, 64, 96), jnp.float32),
    }
    new_state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(new_state.step) == 1


def test_config5_kitti_eval_protocol(rng, tmp_path):
    """BASELINE config 5: the KITTI validator protocol (pad→forward→unpad→
    EPE/D1 masks) on a synthetic pair through the real validate path."""
    import os
    from PIL import Image
    from raft_stereo_tpu.data import frame_utils as fu
    from raft_stereo_tpu.eval.validate import validate_kitti

    root = str(tmp_path)
    for d in ("training/image_2", "training/image_3", "training/disp_occ_0"):
        os.makedirs(os.path.join(root, d))
    for i in range(2):
        for cam in ("image_2", "image_3"):
            Image.fromarray(np.asarray(
                rng.integers(0, 256, (64, 96, 3)), np.uint8)).save(
                os.path.join(root, "training", cam, f"{i:06d}_10.png"))
        disp = rng.uniform(1, 20, (64, 96)).astype(np.float32)
        fu.write_disp_kitti(
            os.path.join(root, "training", "disp_occ_0", f"{i:06d}_10.png"),
            disp)

    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), fnet_dim=64)
    model = RAFTStereo(cfg)
    i1, i2 = _images(rng)
    variables = model.init(jax.random.PRNGKey(0), i1, i2, iters=1,
                           test_mode=True)
    runner = InferenceRunner(cfg, variables, iters=2)
    result = validate_kitti(runner, root=root)
    assert "kitti-epe" in result and "kitti-d1" in result
    assert np.isfinite(result["kitti-epe"])
